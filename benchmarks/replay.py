"""Seeded production-replay storm generator (README §Multi-tenancy).

Deterministic multi-tenant traffic for the tenant-storm e2e config
(benchmarks/e2e.py config15) and the long-haul soak: given a seed, the
generator emits the EXACT same datagram sequence — per-tenant Zipf name
mixes, diurnal rate ramps, flash crowds, and one-tenant tag explosions
— so two runs with the same seed produce identical per-tenant sent
counts and byte streams (pinned by `checksum()` and the CLI below).
The harness owns timing, injection, rolling restarts, and concurrent
query/watch/range storms; this module owns only the reproducible
traffic plan, which is what makes the acceptance gates same-seed
comparable (noisy run vs baseline run).

Determinism contract: one numpy PCG64 stream per generator, consumed
only by the segment methods in call order. Never branch on wall-clock
or on anything the server returns — the byte stream must be a pure
function of (seed, call sequence).

CLI (reproducibility check — two invocations must print one line,
byte-identical):

  python -m benchmarks.replay --seed 7 --segments steady:2000,flash:1000
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class TenantProfile:
    """One tenant's steady-state shape: its share of total traffic, its
    name-space size, and the Zipf skew of its name mix."""
    name: str
    share: float          # fraction of steady-state datagrams
    n_names: int = 256    # distinct metric names in its steady mix
    zipf_a: float = 1.3   # name-popularity skew (>1; higher = peakier)


# the default cast: one big tenant, two mid tenants, a small one, and
# untagged traffic that must land on the default tenant
DEFAULT_TENANTS = (
    TenantProfile("acme", 0.40, n_names=512),
    TenantProfile("blue", 0.25, n_names=256),
    TenantProfile("crux", 0.20, n_names=256),
    TenantProfile("dex", 0.10, n_names=64),
    TenantProfile("", 0.05, n_names=64),       # untagged -> default
)

_KINDS = (b"c", b"g", b"ms", b"s")
# counters dominate like production statsd; sets stay rare (HLL rows)
_KIND_P = (0.55, 0.20, 0.20, 0.05)


class ReplayGenerator:
    """Seeded datagram-sequence factory. Each segment method returns a
    list of single-datagram byte strings and adds to the exact
    per-tenant `sent` ledger (the accounting gates compare this ledger
    against the engine's admitted + shed fold)."""

    def __init__(self, seed: int,
                 tenants: Tuple[TenantProfile, ...] = DEFAULT_TENANTS,
                 tag: str = "tenant:"):
        self.seed = int(seed)
        self.rng = np.random.Generator(np.random.PCG64(int(seed)))
        self.tenants = tuple(tenants)
        self.tag = tag
        shares = np.array([t.share for t in tenants], np.float64)
        self._shares = shares / shares.sum()
        self.sent: Dict[str, int] = {self._ledger_name(t.name): 0
                                     for t in tenants}
        self._explosion_next: Dict[str, int] = {}
        self._sha = hashlib.sha256()

    # -- internals -----------------------------------------------------------
    @staticmethod
    def _ledger_name(name: str) -> str:
        return name or "default"

    def _suffix(self, tenant: str) -> bytes:
        if not tenant:
            return b"|#env:prod"
        return b"|#" + self.tag.encode() + tenant.encode() + b",env:prod"

    def _value(self, kind: bytes) -> bytes:
        if kind == b"c":
            return b"1"
        if kind == b"g":
            return b"%d" % self.rng.integers(0, 1000)
        if kind == b"ms":
            # log-normal latencies: the p99-error gate needs a heavy
            # tail per tenant, not a constant
            return b"%.3f" % float(np.exp(self.rng.normal(3.0, 0.8)))
        return b"u%d" % self.rng.integers(0, 10_000)

    def _datagram(self, prof: TenantProfile, name_idx: int) -> bytes:
        kind = _KINDS[self.rng.choice(len(_KINDS), p=_KIND_P)]
        led = self._ledger_name(prof.name)
        d = b"replay.%s.m%d:%s|%s%s" % (
            led.encode(), name_idx, self._value(kind), kind,
            self._suffix(prof.name))
        self.sent[led] += 1
        self._sha.update(d)
        return d

    def _name_idx(self, prof: TenantProfile) -> int:
        # Zipf draw folded into the tenant's fixed name space: the
        # steady mix revisits hot names, exactly what the fairness path
        # sees in production (and what keeps quarantine quiet)
        return int(self.rng.zipf(prof.zipf_a) - 1) % prof.n_names

    def _pick(self, p=None) -> TenantProfile:
        return self.tenants[self.rng.choice(len(self.tenants),
                                            p=self._shares if p is None
                                            else p)]

    # -- segments ------------------------------------------------------------
    def steady(self, n: int) -> List[bytes]:
        """Production steady state: every tenant at its profile share,
        Zipf name mixes, mixed metric kinds."""
        return [self._datagram(p := self._pick(), self._name_idx(p))
                for _ in range(n)]

    def diurnal(self, n: int, cycles: float = 2.0) -> List[bytes]:
        """Diurnal ramp: tenant shares breathe sinusoidally (each tenant
        phase-shifted), so relative pressure shifts continuously — the
        controller must keep re-weighting, not settle once."""
        out = []
        k = len(self.tenants)
        phases = 2 * np.pi * np.arange(k) / k
        for i in range(n):
            t = 2 * np.pi * cycles * i / max(1, n)
            p = self._shares * (1.0 + 0.75 * np.sin(t + phases))
            p = np.clip(p, 1e-4, None)
            p = p / p.sum()
            prof = self._pick(p)
            out.append(self._datagram(prof, self._name_idx(prof)))
        return out

    def flash_crowd(self, n: int, tenant: Optional[str] = None,
                    boost: float = 5.0) -> List[bytes]:
        """Flash crowd: one tenant spikes to ~`boost`x its steady share
        while everyone else keeps their absolute mix — the noisy-
        neighbor isolation gate's traffic shape."""
        tenant = tenant if tenant is not None else self.tenants[0].name
        idx = next(i for i, t in enumerate(self.tenants)
                   if t.name == tenant)
        p = self._shares.copy()
        p[idx] *= boost
        p = p / p.sum()
        out = []
        for _ in range(n):
            prof = self._pick(p)
            out.append(self._datagram(prof, self._name_idx(prof)))
        return out

    def tag_explosion(self, n: int, tenant: str) -> List[bytes]:
        """Runaway-cardinality tenant: every datagram mints a FRESH
        metric name (a deploy gone wrong, a uuid in a name) — the
        quarantine detector's trigger. The unique counter persists
        across calls so repeated segments keep escalating."""
        idx = next(i for i, t in enumerate(self.tenants)
                   if t.name == tenant)
        prof = self.tenants[idx]
        base = self._explosion_next.get(tenant, 0)
        out = [self._datagram(prof, prof.n_names + base + i)
               for i in range(n)]
        self._explosion_next[tenant] = base + n
        return out

    # -- reproducibility -----------------------------------------------------
    def checksum(self) -> str:
        """sha256 over every datagram emitted so far, in order — the
        same-seed identity check the CLI and the e2e gate pin."""
        return self._sha.hexdigest()

    def ledger(self) -> Dict[str, int]:
        return dict(self.sent)


SEGMENTS = ("steady", "diurnal", "flash", "explosion")


def run_plan(seed: int, plan: List[Tuple[str, int]],
             tenants: Tuple[TenantProfile, ...] = DEFAULT_TENANTS,
             tag: str = "tenant:"):
    """Execute a [(segment, n)] plan; returns (generator, datagrams)."""
    gen = ReplayGenerator(seed, tenants=tenants, tag=tag)
    grams: List[bytes] = []
    for seg, n in plan:
        if seg == "steady":
            grams.extend(gen.steady(n))
        elif seg == "diurnal":
            grams.extend(gen.diurnal(n))
        elif seg == "flash":
            grams.extend(gen.flash_crowd(n))
        elif seg == "explosion":
            grams.extend(gen.tag_explosion(n, tenants[0].name))
        else:
            raise ValueError(f"unknown segment {seg!r} "
                             f"(want one of {SEGMENTS})")
    return gen, grams


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="replay")
    ap.add_argument("--seed", type=int, required=True)
    ap.add_argument("--segments", default="steady:2000",
                    help="comma list of segment:count "
                         f"(segments: {', '.join(SEGMENTS)})")
    args = ap.parse_args(argv)
    plan = []
    for part in args.segments.split(","):
        seg, _, cnt = part.partition(":")
        plan.append((seg.strip(), int(cnt or 1000)))
    gen, grams = run_plan(args.seed, plan)
    print(json.dumps({"seed": args.seed, "datagrams": len(grams),
                      "sent": gen.ledger(),
                      "sha256": gen.checksum()},
                     sort_keys=True, separators=(",", ":")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
