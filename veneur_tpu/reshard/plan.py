"""Reshard planning: the deterministic moved-key math and the snapshot
partitioner that turns one drained interval into per-destination-shard
migration units.

Shard routing is `route_digest(kind, name, joined_tags) % n_shards`
(collective/keytable.py, persistence/restore.py, and the C++ KindTable
all use the identical recipe), so whether a key moves under a resize is
a pure function of its digest and the two shard counts — the moved set
needs no enumeration protocol between peers, only (old_n, new_n).

A migration unit is a mini-snapshot in the exact persistence/snapshot.py
schema, restricted to the rows one DESTINATION shard will own under the
new map. Units are numbered by destination shard, which makes the
exactly-once envelope seq deterministic: a crashed transfer replays the
SAME (epoch, seq) per unit and the receiver's DedupWindow suppresses
every unit that already folded (see coordinator.py).
"""

from __future__ import annotations

import dataclasses
from math import gcd
from typing import Dict, List

import numpy as np

from veneur_tpu.collective.keytable import route_digest

# snapshot table name -> array keys paired with it (persistence/snapshot.py)
_KIND_ARRAYS = {"counter": ("counter",), "gauge": ("gauge",),
                "status": ("status",), "set": ("hll",),
                "histo": ("h_mean", "h_weight", "h_min", "h_max",
                          "h_recip")}


def key_moved(digest: int, old_n: int, new_n: int) -> bool:
    """True iff a key with this routing digest changes owner shard when
    the shard count goes old_n -> new_n."""
    return (digest % old_n) != (digest % new_n)


def moved_fraction(old_n: int, new_n: int) -> float:
    """Exact fraction of the digest space that changes owner, computed
    over one period of the joint residue cycle lcm(old_n, new_n). (The
    u32 digest space is not an exact multiple of the lcm, but the edge
    partial cycle is ~lcm/2^32 — negligible and direction-free.)"""
    if old_n == new_n:
        return 0.0
    period = old_n * new_n // gcd(old_n, new_n)
    moved = sum(1 for r in range(period) if r % old_n != r % new_n)
    return moved / period


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """One resize: old_n -> new_n. `signature` keys logs/metrics and the
    dedup stream so two plans never alias."""
    old_n: int
    new_n: int

    def __post_init__(self):
        if self.old_n < 1 or self.new_n < 1:
            raise ValueError(f"shard counts must be >= 1 "
                             f"({self.old_n} -> {self.new_n})")

    @property
    def signature(self) -> str:
        return f"{self.old_n}->{self.new_n}"

    def dest_shard(self, digest: int) -> int:
        return digest % self.new_n

    def moved(self, digest: int) -> bool:
        return key_moved(digest, self.old_n, self.new_n)


def _row_digest(entry) -> int:
    """Digest for one snapshot table row (the snapshot schema's
    8-field entry list). `actual_kind` disambiguates histogram vs timer
    — they share a table but are distinct key identities."""
    name, tags, _scope, _host, _msg, _imp, actual_kind, joined = entry
    if joined is None:
        joined = ",".join(tags)
    return route_digest(actual_kind, name, joined)


def partition_units(snap: dict, plan: ReshardPlan) -> List[dict]:
    """Split a drained interval's snapshot into per-destination-shard
    migration units (empty shards get no unit, but unit seq == dest
    shard id stays stable either way via the `dest_shard` field).

    Every live row re-enters the new mesh — the rebuilt aggregator
    starts empty — but rows whose owner is unchanged are counted apart
    from genuinely moved rows (`rows_moved`), which is what
    veneur.reshard.rows_moved_total reports: the cross-owner traffic a
    real fleet would put on the wire."""
    arrays = snap["arrays"]
    tables = snap["tables"]
    # destination shard -> {table kind: [row index]}
    by_dest: Dict[int, Dict[str, List[int]]] = {}
    moved_rows: Dict[int, int] = {}
    for kind, entries in tables.items():
        for i, entry in enumerate(entries):
            d = _row_digest(entry)
            dest = plan.dest_shard(d)
            by_dest.setdefault(dest, {}).setdefault(kind, []).append(i)
            if plan.moved(d):
                moved_rows[dest] = moved_rows.get(dest, 0) + 1
    units: List[dict] = []
    for dest in sorted(by_dest):
        sel = by_dest[dest]
        u_tables = {kind: [tables[kind][i] for i in sel.get(kind, ())]
                    for kind in tables}
        u_arrays = {}
        for kind, arr_keys in _KIND_ARRAYS.items():
            idx = np.asarray(sel.get(kind, ()), np.int64)
            for ak in arr_keys:
                src = np.asarray(arrays[ak])
                u_arrays[ak] = (src[idx] if len(idx)
                                else src[:0])
        units.append({
            "agg_kind": snap.get("agg_kind", "single"),
            "n_shards": int(snap.get("n_shards", plan.old_n)),
            "spec": snap["spec"],
            "interval_ts": snap.get("interval_ts", 0),
            "created_at": snap.get("created_at", 0),
            "hostname": snap.get("hostname", ""),
            "tables": u_tables,
            "arrays": u_arrays,
            "spill": b"",
            "spill_entries": 0,
            "forward": None,
            # reshard-unit bookkeeping (not part of the persisted schema)
            "dest_shard": dest,
            "rows": sum(len(v) for v in u_tables.values()),
            "rows_moved": moved_rows.get(dest, 0),
        })
    return units
