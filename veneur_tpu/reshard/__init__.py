"""Elastic live resharding: grow and shrink the mesh under fire.

The routing digest (collective/keytable.py `route_digest`, byte-identical
in the C++ preshard path) is deterministic, so the set of keys that move
when the shard count changes is computable from (old_n, new_n) alone —
no coordination, no key enumeration on the wire. The pieces:

- plan.py         the pure math: which keys move, how rows partition
                  into per-destination-shard migration units.
- quiesce.py      THE sanctioned swap-boundary helper for shard-map
                  mutation (vtlint's reshard-quiesce pass rejects any
                  other call site).
- coordinator.py  the live protocol: drain the old mesh at a flush
                  boundary, rebuild the serving aggregator on the new
                  shard map, and replay the drained rows through the
                  normal fold path under exactly-once envelopes.
"""

from veneur_tpu.reshard.coordinator import ReshardCoordinator, ReshardError
from veneur_tpu.reshard.plan import ReshardPlan, key_moved, partition_units

__all__ = ["ReshardCoordinator", "ReshardError", "ReshardPlan",
           "key_moved", "partition_units"]
