"""The documented swap-boundary helper for shard-map mutation.

Changing the shard map while packets are in flight is only safe at a
buffer-swap boundary: the native engine's staged rows were keyed under
the OLD map (slot = shard*per_shard + local at parse time), so they must
be emitted and the interval detached before the map changes, and no
packed batch may straddle two maps. `shard_map_swap` is the ONE place
that sequencing lives:

1. stage the pending shard count on the C++ engine (`shard_map_set`
   marks it; nothing changes yet — parsing continues under the old map);
2. run the aggregator's normal `swap()`, which pauses the reader rings,
   emits every staged row under the old map, detaches the interval, and
   calls `eng.reset()` — the reset applies the pending map atomically
   inside the quiesce, then the rings resume parsing under the new map.

Pure-Python backends have no engine; for them the swap alone IS the
boundary (the new aggregator object carries the new layout).

vtlint's `reshard-quiesce` pass (analysis/reshard_quiesce.py) rejects
any shard-map mutation outside this module, so the sequencing above
cannot be bypassed by accident.
"""

from __future__ import annotations


def shard_map_swap(aggregator, new_n_shards: int):
    """Detach the current interval at a flush boundary and re-learn the
    shard map without a pipeline restart. Returns the detached
    (state, table) pair exactly like `aggregator.swap()`."""
    eng = getattr(aggregator, "eng", None)
    if eng is not None:
        # staged only: applied inside eng.reset() during the swap below,
        # while the rings are paused and staging is drained
        eng.shard_map_set(int(new_n_shards))
    return aggregator.swap()
