"""Live shard-migration protocol: resize the mesh while ingest, flush,
forward, and the query tier keep running.

The protocol has four phases, each observable via /readyz's `phase`
field and the veneur.reshard.* instruments:

ANNOUNCE   the server enters the RESHARDING sub-state (ready-but-
           announcing: /readyz stays 200 so peers keep sending, but the
           machine-readable phase tells the proxy's prober and
           dashboards a move is underway).
DRAIN      one pipeline visit detaches the old interval at a flush
           boundary through the sanctioned swap-boundary helper
           (reshard/quiesce.py — the C++ rings re-learn the shard map
           inside the same quiesce, so no packed batch straddles two
           maps), then builds and installs the NEW aggregator: for
           native backends the same C++ engine is re-wrapped, so reader
           sockets, rings, and parse threads never restart. Ingest
           continues into the new mesh the moment the visit returns.
TRANSFER   a mover thread computes the drained interval's rows off the
           hot path (the same want_raw compute_flush the flush worker
           runs on detached state) and partitions them into per-
           destination-shard migration units (reshard/plan.py). Units
           replay through the pipeline queue in bounded waves
           (reshard_max_parallel_shards per visit), interleaving with
           packets, flushes, and queries. Each unit carries an
           exactly-once envelope (source_id, migration epoch, seq =
           destination shard): a crash mid-move replays the SAME seqs
           and the DedupWindow suppresses every unit that already
           folded. Rows fold through fold_snapshot — the restore path's
           merge machinery, not a duplicate.
CUTOVER    a flush that arrives mid-transfer completes the remaining
           folds synchronously on the pipeline thread before swapping
           (bounding the transition at one flush interval); otherwise
           the mover finishes and exits the announce state.

Crash matrix (what each phase loses on failure):
- announce/drain failure: nothing moved; the old aggregator keeps
  serving; failed_total increments.
- transfer fold fault: the whole epoch replays from seq 0; folded units
  return DUPLICATE and are skipped — exactly-once, no double-count.
- transfer timeout at a flush boundary: the flush proceeds with what
  has folded; the remainder of the drained interval is dropped with
  exact accounting (failed_total + log) rather than wedging the flush.
- full process crash: checkpoint restore (persistence/assembly.py)
  re-shards the newest snapshot onto whatever mesh restarts — the
  wholesale fallback this live path exists to avoid.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from veneur_tpu.forward.envelope import DedupWindow, Envelope, FRESH, \
    mint_source_id
from veneur_tpu.query.snapshot import PipelineCall, PipelineRequest
from veneur_tpu.reliability.faults import FAULTS, RESHARD_FOLD
from veneur_tpu.reshard import quiesce
from veneur_tpu.reshard.plan import ReshardPlan, partition_units

log = logging.getLogger("veneur_tpu.reshard")

# replays of a faulted transfer before the move is declared failed
_MAX_REPLAYS = 3


class ReshardError(RuntimeError):
    """A resize that could not start or did not complete: feature off,
    another move in progress, invalid target shard count, or a transfer
    that failed/timed out."""


class _Transfer:
    """Shared state of one resize: the drained interval, the migration
    units, and the fold cursor. Units fold ONLY on the pipeline thread
    (via _BeginRequest-spawned PipelineCalls or the flush-boundary
    completion), so the cursor needs the lock only against the mover
    thread's progress reads."""

    def __init__(self, new_n: int, epoch: int):
        self.new_n = int(new_n)
        self.epoch = int(epoch)
        self.plan: Optional[ReshardPlan] = None
        self.lock = threading.Lock()
        self.units: List[dict] = []
        self.units_ready = threading.Event()
        self.next_i = 0
        self.replays = 0
        self.rows_folded = 0
        self.rows_moved = 0
        self.dup_suppressed = 0
        self.failed = False
        self.detail = ""
        self.done = threading.Event()
        self.t0_ns = 0
        self.duration_ns = 0
        # detached interval, held until the transfer finishes
        self.state = None
        self.table = None
        self.old_agg = None

    def fail(self, detail: str) -> None:
        with self.lock:
            self.failed = True
            self.detail = self.detail or detail

    def remaining(self) -> int:
        with self.lock:
            return max(0, len(self.units) - self.next_i)

    def summary(self) -> dict:
        return {"plan": self.plan.signature if self.plan else "",
                "epoch": self.epoch,
                "units": len(self.units),
                "rows_folded": self.rows_folded,
                "rows_moved": self.rows_moved,
                "dup_suppressed": self.dup_suppressed,
                "replays": self.replays,
                "failed": self.failed,
                "detail": self.detail,
                "duration_ns": self.duration_ns}


class _BeginRequest(PipelineRequest):
    """The DRAIN phase as one pipeline-queue visit: swap boundary,
    shard-map re-learn, aggregator rebuild, install."""

    __slots__ = ("coord", "transfer")

    def __init__(self, coord: "ReshardCoordinator", transfer: _Transfer):
        super().__init__()
        self.coord = coord
        self.transfer = transfer

    def run(self, aggregator) -> None:
        try:
            self.coord._begin_on_pipeline(self.transfer)
            self.ok = True
        except Exception as e:  # noqa: BLE001 — waiter must always wake
            self.detail = f"reshard begin failed: {e}"
            self.transfer.fail(self.detail)
        finally:
            self.done.set()


class ReshardCoordinator:
    """One per server. Public surface: resize() (any thread),
    complete_pending_folds() (pipeline thread, called by the flush
    handler), and `active` for the health phase / query stale marking."""

    def __init__(self, server, dedup_window: int = 256):
        self._server = server
        # migration units get their OWN exactly-once stream: a dedicated
        # source identity and one epoch per resize attempt, so a replay
        # after a mid-move crash re-presents the original seqs and the
        # window answers DUPLICATE (never FRESH) for anything folded
        self._source_id = mint_source_id()
        self._epoch = -1
        self.dedup = DedupWindow(dedup_window)
        self._lock = threading.Lock()
        self._transfer: Optional[_Transfer] = None
        self.moves_total = 0
        self.failed_total = 0

    @property
    def active(self) -> bool:
        t = self._transfer
        return t is not None and not t.done.is_set()

    # -- public API ----------------------------------------------------------
    def resize(self, new_n_shards: int, wait: bool = True,
               timeout_s: Optional[float] = None):
        """Resize the mesh to `new_n_shards`. With wait=True blocks until
        the transfer finished and returns its summary dict; with
        wait=False returns the live transfer handle."""
        srv = self._server
        cfg = srv.cfg
        if not getattr(cfg, "reshard_enabled", False):
            raise ReshardError("resharding is disabled "
                               "(reshard_enabled: false)")
        new_n = int(new_n_shards)
        if new_n < 1:
            raise ReshardError(f"bad target shard count {new_n}")
        if new_n > 1:
            # early capacity guard (re-checked on the pipeline thread):
            # the per-shard layout needs every capacity divisible
            from veneur_tpu.server.sharded_aggregator import per_shard_spec
            try:
                per_shard_spec(srv.aggregator.spec, new_n)
            except ValueError as e:
                raise ReshardError(str(e))
        # The cfg transfer timeout bounds individual fold waves (see
        # _run_transfer); the resize-level wait must also absorb the
        # one-off XLA compile of the new shard layout, which on a cold
        # process dwarfs the steady-state transfer.  Callers who want a
        # tight bound pass timeout_s explicitly.
        timeout = (float(timeout_s) if timeout_s is not None
                   else max(120.0, float(cfg.reshard_transfer_timeout_s)))
        with self._lock:
            if self.active:
                raise ReshardError("a reshard is already in progress")
            self._epoch += 1
            t = _Transfer(new_n, self._epoch)
            self._transfer = t
        begin = _BeginRequest(self, t)
        srv.packet_queue.put(begin)
        if not begin.wait(timeout):
            t.fail(f"drain visit timed out after {timeout:.1f}s")
            self._finalize(t)
            raise ReshardError(t.detail)
        if not begin.ok:
            self._finalize(t)
            raise ReshardError(begin.detail or "reshard begin failed")
        mover = threading.Thread(target=self._run_transfer, args=(t,),
                                 daemon=True, name="reshard-mover")
        mover.start()
        if not wait:
            return t
        if not t.done.wait(timeout):
            t.fail(f"transfer timed out after {timeout:.1f}s")
            raise ReshardError(t.detail)
        if t.failed:
            raise ReshardError(t.detail)
        return t.summary()

    def complete_pending_folds(self, aggregator,
                               timeout_s: float) -> bool:
        """Pipeline-thread hook, called by the flush handler BEFORE the
        swap: a flush that lands mid-transfer completes the remaining
        folds synchronously, so flush output always covers the whole
        drained interval and the transition is bounded at one flush
        boundary. Returns False only when the transfer had to be
        abandoned (units never became ready inside the timeout)."""
        t = self._transfer
        if t is None or t.done.is_set():
            return True
        if not t.units_ready.wait(timeout_s):
            t.fail(f"migration units not ready within {timeout_s:.1f}s "
                   "at a flush boundary; remainder dropped")
            self._finalize(t)
            return False
        self._fold_some(t, aggregator, limit=None)
        if t.remaining() == 0 or t.failed:
            self._finalize(t)
        return not t.failed

    # -- DRAIN (pipeline thread) --------------------------------------------
    def _begin_on_pipeline(self, t: _Transfer) -> None:
        srv = self._server
        old_agg = srv.aggregator
        old_n = int(getattr(old_agg, "n_shards", 1))
        if t.new_n == old_n:
            raise ReshardError(f"mesh already has {old_n} shards")
        t.plan = ReshardPlan(old_n, t.new_n)
        t.t0_ns = time.perf_counter_ns()
        log.info("reshard %s: announce (epoch=%d)", t.plan.signature,
                 t.epoch)
        # ANNOUNCE: ready-but-announcing — /readyz stays 200, phase flips
        srv._resharding = True
        ov = getattr(srv, "_overload", None)
        if ov is not None:
            ov.enter_resharding()
        try:
            # flush boundary + shard-map re-learn inside one quiesce
            state, table = quiesce.shard_map_swap(old_agg, t.new_n)
            t.state, t.table, t.old_agg = state, table, old_agg
            new_agg, native = srv._make_aggregator(
                t.new_n, engine=getattr(old_agg, "eng", None))
            # accounting continuity: processed/dropped/h2d are cumulative
            # server-lifetime counters, not per-aggregator ones
            new_agg.processed = old_agg.processed
            new_agg.dropped_capacity = old_agg.dropped_capacity
            new_agg.h2d_bytes = getattr(old_agg, "h2d_bytes", 0)
            new_agg.last_set_shift = getattr(old_agg, "last_set_shift", 0)
            srv.aggregator = new_agg
            srv._native = native
        except Exception:
            # nothing installed: leave the old aggregator serving and
            # exit the announce state
            srv._resharding = False
            if ov is not None:
                ov.exit_resharding()
            raise
        log.info("reshard %s: new mesh serving; transfer starting",
                 t.plan.signature)

    # -- TRANSFER (mover thread + pipeline folds) ---------------------------
    def _run_transfer(self, t: _Transfer) -> None:
        srv = self._server
        try:
            from veneur_tpu.persistence import build_snapshot
            flush_arrays, table, raw = t.old_agg.compute_flush(
                t.state, t.table, srv.cfg.percentiles, want_raw=True)
            snap = build_snapshot(
                t.old_agg.spec, table, flush_arrays, raw,
                agg_kind="sharded" if t.plan.old_n > 1 else "single",
                n_shards=t.plan.old_n, interval_ts=time.time(),
                hostname=srv.hostname)
            t.units = partition_units(snap, t.plan)
        except Exception as e:
            log.exception("reshard %s: unit build failed",
                          t.plan.signature)
            t.fail(f"unit build failed: {e}")
            t.units_ready.set()
            self._finalize(t)
            return
        t.units_ready.set()
        batch = max(1, int(getattr(srv.cfg, "reshard_max_parallel_shards",
                                   4)))
        wave_s = float(getattr(srv.cfg, "reshard_transfer_timeout_s", 10.0))
        # The budget bounds lack of PROGRESS, not total wall time: every
        # wave that folds at least one unit re-arms the clock, so the
        # one-off XLA compile of the new layout (which dwarfs wave_s on
        # a cold process) cannot fail an otherwise healthy transfer,
        # while a wedged pipeline still trips within one budget.  The
        # first wave carries the compile, so it gets a generous floor.
        deadline = time.monotonic() + max(wave_s, 120.0)
        while not t.done.is_set() and t.remaining() and not t.failed:
            if time.monotonic() > deadline:
                t.fail("transfer timed out; remainder dropped")
                break
            with t.lock:
                before = t.next_i
            call = PipelineCall(
                lambda agg, _t=t, _b=batch: self._fold_some(_t, agg, _b))
            srv.packet_queue.put(call)
            call.wait(max(0.1, deadline - time.monotonic()))
            with t.lock:
                progressed = t.next_i > before
            if progressed:
                deadline = time.monotonic() + wave_s
        self._finalize(t)

    def _fold_some(self, t: _Transfer, aggregator, limit) -> int:
        """Fold up to `limit` units (None = all) into the serving
        aggregator. Pipeline thread only. A fold fault replays the WHOLE
        epoch under the original seqs — the dedup window turns already-
        folded units into DUPLICATE skips, so replay cost is bounded and
        double-folding is impossible."""
        from veneur_tpu.persistence import fold_snapshot
        folded = 0
        while limit is None or folded < limit:
            with t.lock:
                if t.failed or t.next_i >= len(t.units):
                    break
                i = t.next_i
                t.next_i = i + 1
            u = t.units[i]
            env = Envelope(self._source_id, t.epoch, u["dest_shard"])
            verdict = self.dedup.observe(env)
            if verdict != FRESH:
                with t.lock:
                    t.dup_suppressed += 1
                folded += 1
                continue
            try:
                n = fold_snapshot(aggregator, u)
                # chaos hook: a fault HERE models the receiver dying
                # after the fold but before progress is recorded — the
                # canonical replay hazard exactly-once exists for
                FAULTS.inject(RESHARD_FOLD,
                              name=f"unit{u['dest_shard']}")
            except Exception as e:
                with t.lock:
                    t.replays += 1
                    replays = t.replays
                    t.next_i = 0   # replay the epoch from seq 0
                if replays > _MAX_REPLAYS:
                    t.fail(f"fold failed after {replays} replays: {e}")
                else:
                    log.warning("reshard %s: fold fault (%s); replaying "
                                "epoch %d (attempt %d)",
                                t.plan.signature, e, t.epoch, replays)
                break
            with t.lock:
                t.rows_folded += n
                t.rows_moved += int(u.get("rows_moved", 0))
            folded += 1
        return folded

    # -- CUTOVER -------------------------------------------------------------
    def _finalize(self, t: _Transfer) -> None:
        with t.lock:
            if t.done.is_set():
                return
            t.duration_ns = (time.perf_counter_ns() - t.t0_ns
                             if t.t0_ns else 0)
            # release the drained interval's device state
            t.state = t.table = t.old_agg = None
            t.done.set()
        srv = self._server
        srv._resharding = False
        ov = getattr(srv, "_overload", None)
        if ov is not None:
            ov.exit_resharding()
        sig = t.plan.signature if t.plan else f"->{t.new_n}"
        if t.failed:
            self.failed_total += 1
            c = getattr(srv, "_c_reshard_failed", None)
            if c is not None:
                c.inc()
            log.warning("reshard %s FAILED: %s", sig, t.detail)
        else:
            self.moves_total += 1
            c = getattr(srv, "_c_reshard_moves", None)
            if c is not None:
                c.inc()
            log.info("reshard %s complete: %d units, %d rows folded "
                     "(%d moved owner), %.1f ms", sig, len(t.units),
                     t.rows_folded, t.rows_moved, t.duration_ns / 1e6)
        rc = getattr(srv, "_c_reshard_rows_moved", None)
        if rc is not None and t.rows_moved:
            rc.inc(t.rows_moved)
        tm = getattr(srv, "_t_reshard", None)
        if tm is not None and t.duration_ns:
            tm.observe(t.duration_ns)
