"""YAML configuration with reference-compatible semantics.

Mirrors the reference's config surface (reference config.go:3-122, 115 yaml
keys) and parse pipeline (reference config_parse.go:100-148): strict-then-
loose YAML unmarshal that *warns* about unknown keys instead of failing,
``VENEUR_*`` environment-variable overrides (envconfig semantics: the env
var name is VENEUR_ + fieldname uppercased, underscores removed from the
yaml key's words — we use VENEUR_<YAML_KEY_UPPERCASED> which is what
envconfig produces for these field names), then defaults
(config_parse.go:150-230).

TPU additions (the `aggregation_backend: tpu` surface promised by
BASELINE.json's north star): table capacities, staging batch sizes, and the
(replica, shard) mesh shape.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
from typing import List, Optional

import yaml

log = logging.getLogger("veneur_tpu.config")


class UnknownConfigKeys(Warning):
    """Raised-as-warning analogue of reference config_parse.go:88
    UnknownConfigKeys: config parsed fine but contains unrecognized keys."""

    def __init__(self, keys):
        self.keys = sorted(keys)
        super().__init__(f"unknown config keys: {', '.join(self.keys)}")


_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DURATION_UNITS = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
                   "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(s: str) -> float:
    """Go time.ParseDuration subset → seconds (reference config_parse.go:229
    ParseInterval)."""
    if not s:
        raise ValueError("empty duration")
    matches = list(_DURATION_RE.finditer(s))
    if not matches or "".join(m.group(0) for m in matches) != s:
        raise ValueError(f"invalid duration {s!r}")
    return sum(float(m.group(1)) * _DURATION_UNITS[m.group(2)]
               for m in matches)


@dataclasses.dataclass
class Config:
    """One server process's configuration (reference config.go Config).

    Keys the TPU build does not (yet) act on are still parsed and carried so
    existing reference YAML files load cleanly; sinks/features gate on them
    being non-empty exactly like reference server.go:472-678.
    """
    # core pipeline
    aggregates: List[str] = dataclasses.field(default_factory=list)
    interval: str = ""
    synchronize_with_interval: bool = False
    metric_max_length: int = 0
    trace_max_length_bytes: int = 0
    read_buffer_size_bytes: int = 0
    num_workers: int = 1
    num_readers: int = 1
    num_span_workers: int = 1
    span_channel_capacity: int = 0
    percentiles: List[float] = dataclasses.field(default_factory=list)
    count_unique_timeseries: bool = False
    hostname: str = ""
    omit_empty_hostname: bool = False
    tags: List[str] = dataclasses.field(default_factory=list)
    tags_exclude: List[str] = dataclasses.field(default_factory=list)
    # Go-runtime profiling knobs (server.go:331-344): accepted so
    # reference YAML loads cleanly, but they have no Python equivalent —
    # use /debug/pprof/profile (sampling) instead
    mutex_profile_fraction: int = 0
    block_profile_rate: int = 0
    sentry_dsn: str = ""
    stats_address: str = ""
    veneur_metrics_additional_tags: List[str] = dataclasses.field(
        default_factory=list)
    veneur_metrics_scopes: dict = dataclasses.field(default_factory=dict)

    # listeners
    statsd_listen_addresses: List[str] = dataclasses.field(
        default_factory=list)
    ssf_listen_addresses: List[str] = dataclasses.field(default_factory=list)
    http_address: str = ""
    grpc_address: str = ""
    http_quit: bool = False
    tls_key: str = ""
    tls_certificate: str = ""
    tls_authority_certificate: str = ""

    # forwarding / distributed tier
    forward_address: str = ""
    forward_use_grpc: bool = False
    flush_max_per_body: int = 0
    flush_file: str = ""
    flush_watchdog_missed_flushes: int = 0

    # resilience layer (veneur_tpu/reliability/; this framework's
    # addition). Reference-compatible defaults: 0 retries / threshold 0 /
    # 0 spill bytes keep every egress path single-attempt and
    # drop-on-failure, exactly today's behavior.
    sink_retry_max: int = 0            # retries per egress call (0 = off)
    sink_retry_base_ms: int = 100      # first backoff step
    circuit_failure_threshold: int = 0  # consecutive failures (0 = off)
    circuit_cooldown_s: float = 30.0   # open -> half-open probe delay
    forward_spill_max_bytes: int = 0   # merge-on-retry buffer (0 = off)
    forward_spill_max_age_s: float = 60.0
    fault_injection: str = ""          # chaos spec (reliability/faults.py)

    # exactly-once forwarding (forward/envelope.py; README §Exactly-once
    # forwarding). 0 = off: senders don't stamp envelopes, receivers
    # don't dedup — exactly the at-least-once behavior above. On a LOCAL
    # (> 0) every forwarded interval carries a (source_id, epoch, seq)
    # envelope and the spill becomes the ack-gated send queue; on a
    # GLOBAL/proxy (> 0) it is the per-source dedup window size in seqs —
    # replays more than `window` seqs behind a stream's high-water mark
    # are conservatively suppressed (the documented staleness bound).
    forward_dedup_window: int = 0
    forward_dedup_max_sources: int = 1024  # LRU bound on tracked streams

    # durability layer (veneur_tpu/persistence/; README §Durability).
    # An empty checkpoint_dir keeps the whole subsystem inert — no
    # writer thread, no restore scan, no behavior change.
    checkpoint_dir: str = ""           # checkpoint root ("" = off)
    checkpoint_interval_flushes: int = 1   # flushes between checkpoints
    checkpoint_retain: int = 3         # newest N checkpoints kept on disk
    restore_on_start: bool = False     # fold the newest valid snapshot
    checkpoint_on_shutdown: bool = True    # final snapshot of the tail

    # device kernels (veneur_tpu/ops/pallas_ingest.py; README §Device
    # kernels). True = probe-gated: the fused ingest kernel runs where
    # the backend compiles it (TPU), the XLA scatter chain everywhere
    # else (CPU tier-1 parity keeps the chain as the oracle). False
    # forces the chain even on TPU.
    pallas_ingest_enabled: bool = True

    # observability (veneur_tpu/observability/). Both switches default
    # OFF with zero hot-path overhead (a single attribute check / a 404):
    # the telemetry registry itself always runs — it IS the counter store.
    prometheus_metrics_enabled: bool = False  # serve GET /metrics
    flush_trace_enabled: bool = False  # per-phase span tree + row/byte tags
    self_timer_compression: float = 50.0  # t-digest delta for self-timers
    # serve GET /debug/profile?seconds=N — an on-demand jax.profiler
    # device trace written to a temp dir. Off by default: capture stalls
    # the runtime, so it must be an explicit operator decision.
    profile_capture_enabled: bool = False

    # overload management (veneur_tpu/reliability/overload.py; README
    # §Overload & health). Off by default: no controller, no poller
    # thread, no per-packet admission check — prior behavior exactly.
    overload_enabled: bool = False     # master switch for the controller
    overload_poll_interval_s: float = 0.25   # pressure sampling cadence
    overload_enter_pressured: float = 0.70   # state entry thresholds on
    overload_enter_shedding: float = 0.85    # max-normalized pressure
    overload_enter_critical: float = 0.95
    overload_exit_margin: float = 0.10  # hysteresis: exit below entry-margin
    overload_hold_s: float = 5.0       # min dwell before any downgrade
    overload_admit_rate: float = 0.0   # token bucket pkts/s (0 = no bucket)
    overload_admit_burst: float = 0.0  # bucket depth (0 = admit_rate)
    overload_timer_sample_rate: float = 0.5  # degraded timer admit fraction
    overload_set_shift: int = 2        # degraded HLL member-subsample bits
    shed_priority_tags: List[str] = dataclasses.field(
        default_factory=list)          # substrings shed LAST (e.g.
    #                                    "veneur.priority:high")
    overload_native_admission: bool = True  # run statsd admission inside
    #                                    the C++ reader ring (off = prior
    #                                    Python-side behavior: the native
    #                                    path bypasses admission)

    # multi-tenant fairness + quarantine (veneur_tpu/reliability/
    # tenancy.py; README §Multi-tenancy). Off by default: no identity
    # extraction, no per-tenant buckets, no quarantine — prior behavior
    # exactly.
    tenant_enabled: bool = False       # master switch for tenancy
    tenant_tag: str = "tenant:"        # datagram tag carrying the identity
    tenant_weights: dict = dataclasses.field(
        default_factory=dict)          # {tenant: weight}; unlisted -> 1.0
    tenant_fair_rate: float = 0.0      # admitted pkts/s per unit weight at
    #                                    SHEDDING+ (0 = fairness buckets off)
    tenant_fair_burst_mult: float = 2.0    # bucket depth = rate * mult
    tenant_quarantine_max_keys: int = 0    # distinct-key budget per tenant
    #                                    per flush window (0 = quarantine off)
    tenant_quarantine_decay: float = 0.5   # key-estimate decay per flush
    tenant_quarantine_readmit_frac: float = 0.5  # re-admit when the decayed
    #                                    estimate falls under frac * budget

    # TCP statsd hardening: connection cap + per-connection idle
    # deadline (a slowloris peer must not pin reader threads forever).
    tcp_max_connections: int = 0       # concurrent conns (0 = unlimited)
    tcp_idle_timeout_s: float = 0.0    # close idle conns (0 = no deadline)

    # debug
    debug: bool = False
    debug_flushed_metrics: bool = False
    debug_ingested_spans: bool = False
    enable_profiling: bool = False

    # datadog sink
    datadog_api_key: str = ""
    datadog_api_hostname: str = ""
    datadog_flush_max_per_body: int = 0
    datadog_metric_name_prefix_drops: List[str] = dataclasses.field(
        default_factory=list)
    datadog_exclude_tags_prefix_by_prefix_metric: dict = dataclasses.field(
        default_factory=dict)
    datadog_span_buffer_size: int = 0
    datadog_trace_api_address: str = ""

    # other sinks (parsed; gated on non-empty like the reference)
    signalfx_api_key: str = ""
    signalfx_endpoint_base: str = ""
    signalfx_endpoint_api: str = ""
    signalfx_hostname_tag: str = ""
    signalfx_flush_max_per_body: int = 0
    signalfx_vary_key_by: str = ""
    signalfx_per_tag_api_keys: List[dict] = dataclasses.field(
        default_factory=list)
    signalfx_dynamic_per_tag_api_keys_enable: bool = False
    signalfx_dynamic_per_tag_api_keys_refresh_period: str = ""
    signalfx_metric_name_prefix_drops: List[str] = dataclasses.field(
        default_factory=list)
    signalfx_metric_tag_prefix_drops: List[str] = dataclasses.field(
        default_factory=list)
    kafka_broker: str = ""
    kafka_metric_topic: str = ""
    kafka_span_topic: str = ""
    kafka_check_topic: str = ""
    kafka_event_topic: str = ""
    kafka_partitioner: str = ""
    kafka_metric_require_acks: str = ""
    kafka_span_require_acks: str = ""
    kafka_retry_max: int = 0
    kafka_metric_buffer_bytes: int = 0
    kafka_metric_buffer_messages: int = 0
    kafka_metric_buffer_frequency: str = ""
    kafka_span_buffer_bytes: int = 0
    kafka_span_buffer_mesages: int = 0  # sic — reference config.go typo kept
    kafka_span_buffer_frequency: str = ""
    kafka_span_serialization_format: str = ""
    kafka_span_sample_rate_percent: int = 0
    kafka_span_sample_tag: str = ""
    splunk_hec_address: str = ""
    splunk_hec_token: str = ""
    splunk_hec_batch_size: int = 0
    splunk_hec_submission_workers: int = 0
    splunk_hec_tls_validate_hostname: str = ""
    splunk_hec_send_timeout: str = ""
    splunk_hec_ingest_timeout: str = ""
    splunk_hec_max_connection_lifetime: str = ""
    splunk_hec_connection_lifetime_jitter: str = ""
    splunk_span_sample_rate: int = 0
    lightstep_access_token: str = ""
    lightstep_collector_host: str = ""
    lightstep_reconnect_period: str = ""
    lightstep_maximum_spans: int = 0
    lightstep_num_clients: int = 0
    # deprecated aliases the reference still parses with a warning
    # (config_parse.go:185-210): trace_lightstep_* fills lightstep_*
    # only when the canonical key is unset
    trace_lightstep_access_token: str = ""
    trace_lightstep_collector_host: str = ""
    trace_lightstep_reconnect_period: str = ""
    trace_lightstep_maximum_spans: int = 0
    trace_lightstep_num_clients: int = 0
    xray_address: str = ""
    xray_annotation_tags: List[str] = dataclasses.field(default_factory=list)
    xray_sample_percentage: float = 0.0
    falconer_address: str = ""
    grpsink_address: str = ""

    # span pipeline
    indicator_span_timer_name: str = ""
    objective_span_timer_name: str = ""
    ssf_buffer_size: int = 0

    # tag-frequency heavy hitters over spans (this framework's addition:
    # count-min sketch on device, BASELINE config 5)
    tag_frequency_enabled: bool = False
    tag_frequency_tag_keys: List[str] = dataclasses.field(
        default_factory=list)   # empty = every tag key
    tag_frequency_top_k: int = 100
    tag_frequency_depth: int = 4
    tag_frequency_width: int = 1 << 16
    tag_frequency_batch_size: int = 4096

    # plugins
    aws_access_key_id: str = ""
    aws_secret_access_key: str = ""
    aws_region: str = ""
    aws_s3_bucket: str = ""
    # local durable staging for S3 objects (empty = upload-only, the
    # reference behavior); see plugins/s3.py and README §Durability
    aws_s3_staging_dir: str = ""
    metric_prefix: str = ""

    # set by read_config: yaml keys that matched no field (strict-validate
    # callers fail on these; reference UnknownConfigKeys)
    unknown_keys: List[str] = dataclasses.field(default_factory=list)

    # TPU aggregation backend (this framework's addition)
    aggregation_backend: str = "tpu"
    native_ingest: bool = True   # C++ parse+key+stage path when buildable
    # C++ recvmmsg reader threads for UDP statsd (GIL-free socket reads;
    # requires native_ingest). Python reader threads otherwise.
    native_udp_readers: bool = True
    # Multi-ring host scale-out: one ring + parser + packed arena row per
    # reader core (requires native_udp_readers). 1 keeps the proven
    # single-ring engine; each SO_REUSEPORT reader fd owns its ring at
    # >1. See README "Host feed architecture".
    reader_rings: int = 1
    # Optional per-ring sched_affinity pinning: core id per ring (shorter
    # lists leave the remaining rings unpinned; empty = no pinning).
    reader_pin_cores: List[int] = dataclasses.field(default_factory=list)
    # Pre-sharded native emit on sharded/collective backends: staged rows
    # leave the engine grouped by route_digest owner shard so the
    # _split_shards argsort and the collective all_to_all shuffle are
    # no-ops on the native path. Flush output is byte-identical either
    # way (tests/test_native_preshard.py pins it).
    native_preshard_enabled: bool = False
    tpu_counter_capacity: int = 1 << 17
    tpu_gauge_capacity: int = 1 << 15
    tpu_status_capacity: int = 1 << 10
    tpu_set_capacity: int = 1 << 12
    tpu_histo_capacity: int = 1 << 14
    tpu_batch_counter: int = 8192
    tpu_batch_gauge: int = 2048
    tpu_batch_status: int = 256
    tpu_batch_set: int = 4096
    tpu_batch_histo: int = 8192
    tpu_n_shards: int = 0      # 0 = one shard per local device
    tpu_n_replicas: int = 1
    tpu_compact_every: int = 8
    # t-digest fidelity: δ (the reference's samplers.go:502 compression,
    # default 100 ≈ 157-centroid bound) and cells per k-unit (canonical
    # cells ≈ δ/2·cells_per_k + 2, ops/tdigest.py centroid_capacity;
    # higher = finer quantiles, more HBM per key)
    tpu_digest_compression: float = 100.0
    tpu_digest_cells_per_k: int = 3
    # bottom/top centroids kept exact through compression (per-key p99
    # tail accuracy; ops/tdigest.py DEFAULT_EXACT_EXTREMES)
    tpu_digest_exact_extremes: int = 64
    # collective global tier (veneur_tpu/collective/): the global tier as
    # a mesh resident over (tpu_n_replicas, shards). collective_enabled
    # makes THIS server the tier and registers it under collective_group;
    # collective_attach makes THIS (local) server hand its forwardable
    # flush rows to the co-located tier of that group as device arrays —
    # zero serialization — instead of gRPC. forward_address stays
    # authoritative for cross-host (DCN) peers.
    collective_enabled: bool = False
    collective_group: str = "default"
    collective_attach: str = ""
    # on-device query tier (veneur_tpu/query/): serve live quantile /
    # cardinality / counter reads from resident device state via
    # POST /query on the http API. Off by default — it spins up a
    # batcher thread and piggybacks snapshot requests on the ingest
    # pipeline queue. query_max_batch caps queries coalesced into one
    # device launch; query_timeout_ms is the coalescing window.
    query_enabled: bool = False
    query_max_batch: int = 64
    query_timeout_ms: float = 2.0
    # elastic live resharding (veneur_tpu/reshard/): grow/shrink the
    # shard mesh without a restart or flush gap. Off by default — the
    # coordinator object exists only when enabled, and the collective
    # tier (which manages its own mesh) always wins over this.
    # transfer_timeout_s bounds the whole move (drain visit, unit build,
    # and the fold completion a mid-move flush performs);
    # max_parallel_shards caps migration units folded per pipeline
    # visit, so transfer folds interleave with ingest instead of
    # monopolizing the pipeline thread.
    reshard_enabled: bool = False
    reshard_transfer_timeout_s: float = 10.0
    reshard_max_parallel_shards: int = 4
    # streaming watch tier (veneur_tpu/watch/): standing monitors
    # registered via POST /watch, evaluated as ONE fused device launch
    # per flush interval on the detached state, transitions streamed
    # over GET /watch/stream (SSE) and an optional webhook. Off by
    # default — it spins up an engine thread. watch_max_active caps the
    # registry (and therefore the packed evaluation's gather size);
    # watch_stream_max_subscribers caps concurrent SSE consumers;
    # watch_webhook_url, when set, POSTs each interval's transition
    # batch through the sink retry/breaker machinery.
    watch_enabled: bool = False
    watch_max_active: int = 1 << 17
    watch_stream_max_subscribers: int = 64
    watch_webhook_url: str = ""
    # on-device history tier (veneur_tpu/history/): keep the last
    # history_windows flush intervals device-resident per key (written
    # by the flush program itself), with history_decimation_tiers
    # levels of 2x-decimated older windows — history_windows *
    # 2^tiers intervals of total lookback. Range queries ride POST
    # /query (query tier) and `python -m veneur_tpu.cli.query --range`.
    # history_max_keys caps per-kind ring rows (HBM: see
    # history.HistorySpec.hbm_bytes; the veneur.history.hbm_bytes gauge
    # reports the resident figure).
    history_enabled: bool = False
    history_windows: int = 90
    history_decimation_tiers: int = 3
    history_max_keys: int = 1 << 20
    # self-adjusting key tables (veneur_tpu/tables/): per-kind capacity
    # growth at the flush swap boundary up to table_max_capacity rows
    # per kind, idle-key census TTL for exact eviction accounting, and
    # the SALSA merge-cell rung of the pressure ladder (Python key
    # tables only; counters). All default-off.
    table_grow_enabled: bool = False
    table_max_capacity: int = 1 << 24
    table_idle_ttl_s: float = 300.0
    table_salsa_enabled: bool = False

    def parse_interval(self) -> float:
        return parse_duration(self.interval)

    @property
    def is_local(self) -> bool:
        """Local ⇔ forwards to a global tier (reference server.go:1434),
        whether over the wire or into a co-located collective tier."""
        return self.forward_address != "" or self.collective_attach != ""


_DEFAULTS = {
    "aggregates": ["min", "max", "count"],
    "interval": "10s",
    "metric_max_length": 4096,
    "read_buffer_size_bytes": 2 * 1048576,
    "span_channel_capacity": 100,
    "splunk_hec_batch_size": 100,
    "splunk_hec_max_connection_lifetime": "10s",
    "datadog_flush_max_per_body": 25000,
    "percentiles": [0.5, 0.75, 0.99],
}

_FIELDS = {f.name: f for f in dataclasses.fields(Config)}


def _coerce(field: dataclasses.Field, raw: str):
    # resolve the runtime type from the default factory / default value
    if field.default_factory is not dataclasses.MISSING:  # type: ignore
        proto = field.default_factory()  # type: ignore
    else:
        proto = field.default
    if isinstance(proto, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(proto, int):
        return int(raw)
    if isinstance(proto, float):
        return float(raw)
    if isinstance(proto, list):
        return [s for s in (x.strip() for x in raw.split(",")) if s]
    if isinstance(proto, dict):
        return yaml.safe_load(raw)
    return raw


def read_config(path_or_file, env: Optional[dict] = None,
                proxy: bool = False) -> Config:
    """YAML → Config with unknown-key warning, env override, defaults
    (reference config_parse.go:100 ReadConfig)."""
    if hasattr(path_or_file, "read"):
        data = yaml.safe_load(path_or_file.read()) or {}
    else:
        with open(path_or_file) as f:
            data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ValueError("config root must be a mapping")

    cfg = Config()
    unknown = []
    for k, v in data.items():
        if k in _FIELDS:
            if v is not None:
                setattr(cfg, k, v)
        else:
            unknown.append(k)
    cfg.unknown_keys = sorted(unknown)
    if unknown:
        # reference behavior: usable config, warn loudly; strict callers
        # check cfg.unknown_keys and fail (config_parse.go:113
        # unmarshalSemiStrictly returning UnknownConfigKeys)
        log.warning(str(UnknownConfigKeys(unknown)))

    env = os.environ if env is None else env
    prefix = "VENEUR_"
    for name, field in _FIELDS.items():
        var = prefix + name.upper().replace("_", "")
        # envconfig checks both the squashed and underscored forms
        for candidate in (var, prefix + name.upper()):
            if candidate in env:
                setattr(cfg, name, _coerce(field, env[candidate]))
                break

    for k, v in _DEFAULTS.items():
        cur = getattr(cfg, k)
        if cur == _FIELDS[k].default or (
                isinstance(cur, list) and not cur) or cur in ("", 0):
            setattr(cfg, k, v)
    for stem in ("access_token", "collector_host", "reconnect_period",
                 "maximum_spans", "num_clients"):
        dep = getattr(cfg, f"trace_lightstep_{stem}")
        if dep:
            log.warning("trace_lightstep_%s has been replaced by "
                        "lightstep_%s", stem, stem)
            if not getattr(cfg, f"lightstep_{stem}"):
                setattr(cfg, f"lightstep_{stem}", dep)
    if not cfg.hostname and not cfg.omit_empty_hostname:
        import socket
        cfg.hostname = socket.gethostname()
    return cfg
