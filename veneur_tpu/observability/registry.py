"""The telemetry registry: one source of truth for self-metrics.

The reference scatters its self-observation across ad-hoc Server fields
and per-worker counters (worker.go:513, flusher.go:300-336); this module
replaces that with a single thread-safe registry that THREE consumers
read — the JSON `/stats` endpoint, the per-interval self-metric flush
(server._report_self_metrics), and the Prometheus `/metrics` exposition
(observability/export.py) — so they can never disagree.

Three owned instrument kinds plus a collector hook:

- Counter: monotonically increasing float, optional label names. inc()
  is atomic under the instrument's lock — this is what fixes the
  lost-increment race on Server.imported_total (server.py `+=` from
  multiple threads).
- Gauge: last-write-wins value per label set.
- Timer: duration samples folded into the repo's OWN fixed-shape
  t-digest (ops/tdigest.py, Dunning & Ertl arXiv:1902.04023) — the
  observability layer exercises the same mergeable-sketch machinery it
  observes. Quantiles (p50/p95/p99) come out of `ops.tdigest.quantiles`.
- callback(): a read-through collector for values owned elsewhere
  (circuit-breaker state, spill occupancy, packet counters folded from
  C++ readers) — registered once, evaluated at collect time, so the
  registry exports live values without double-owning them.

Timers buffer raw observations and fold lazily in fixed-size padded
batches: ops.tdigest.add_batch_single is jitted with shape-static
arguments, so folding a variable-length buffer directly would recompile
per batch size. Padding to _FOLD keeps it at one compiled program per
(compression, fold-size) pair for the process lifetime.
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

log = logging.getLogger("veneur_tpu.observability")

# quantiles every Timer exports (the exposition's summary lines)
TIMER_QUANTILES = (0.5, 0.95, 0.99)

# fixed fold width — see module docstring (recompile avoidance)
_FOLD = 1024

# small exact-extreme reservation: self-timers care about tail accuracy
# and hold few distinct values per interval
_EXACT_EXTREMES = 16


LabelValues = Tuple[str, ...]


def _label_key(labelnames: Tuple[str, ...], labels: Dict) -> LabelValues:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class Counter:
    """Monotonic counter; inc() under a lock is the atomic replacement
    for the racy `server.attr += 1` pattern."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labelnames: Tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: Dict[LabelValues, float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative inc {n}")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self) -> List[Tuple[LabelValues, float]]:
        with self._lock:
            if not self._values and not self.labelnames:
                return [((), 0.0)]
            return sorted(self._values.items())


class Gauge:
    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labelnames: Tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values: Dict[LabelValues, float] = {}

    def set(self, v: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = float(v)

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self) -> List[Tuple[LabelValues, float]]:
        with self._lock:
            if not self._values and not self.labelnames:
                return [((), 0.0)]
            return sorted(self._values.items())


class TimerStat:
    """One label set's snapshot: exact count/sum plus sketch quantiles."""

    __slots__ = ("count", "sum", "quantiles")

    def __init__(self, count: int, sum_: float, quantiles: Dict[float, float]):
        self.count = count
        self.sum = sum_
        self.quantiles = quantiles


class _TimerState:
    __slots__ = ("buf", "table", "count", "sum")

    def __init__(self):
        self.buf: List[float] = []
        self.table = None       # ops.tdigest.TDigestTable, scalar key
        self.count = 0
        self.sum = 0.0


class Timer:
    """Duration sketch backed by ops/tdigest.py. observe() is an append
    under the lock (plus one device fold per _FOLD observations — flush
    phases observe a handful of samples per ~10s interval, so folds are
    effectively scrape-time work)."""

    kind = "summary"

    def __init__(self, name: str, help: str = "",
                 labelnames: Tuple[str, ...] = (),
                 compression: float = 50.0):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.compression = float(compression)
        self._lock = threading.Lock()
        self._states: Dict[LabelValues, _TimerState] = {}

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        if not math.isfinite(value):
            return
        key = _label_key(self.labelnames, labels)
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _TimerState()
            st.buf.append(value)
            st.count += 1
            st.sum += value
            if len(st.buf) >= _FOLD:
                self._fold(st)

    def _fold(self, st: _TimerState) -> None:
        """Fold the buffered samples into the digest (caller holds the
        lock). Zero-padded to _FOLD with zero WEIGHT — empty slots, not
        zero-valued samples — so one compiled program serves every fold."""
        if not st.buf:
            return
        import numpy as np

        from veneur_tpu.ops import tdigest
        if st.table is None:
            st.table = tdigest.empty_table(
                (), compression=self.compression,
                exact_extremes=_EXACT_EXTREMES)
        buf, st.buf = st.buf, []
        for i in range(0, len(buf), _FOLD):
            chunk = buf[i:i + _FOLD]
            vals = np.zeros(_FOLD, np.float32)
            wts = np.zeros(_FOLD, np.float32)
            vals[:len(chunk)] = chunk
            wts[:len(chunk)] = 1.0
            st.table = tdigest.add_batch_single(
                st.table, vals, wts, compression=self.compression,
                exact_extremes=_EXACT_EXTREMES)

    def snapshot(self, qs: Tuple[float, ...] = TIMER_QUANTILES
                 ) -> List[Tuple[LabelValues, TimerStat]]:
        import numpy as np
        out = []
        with self._lock:
            states = sorted(self._states.items())
            if not states and not self.labelnames:
                states = [((), _TimerState())]
            for key, st in states:
                self._fold(st)
                quantiles: Dict[float, float] = {}
                if qs and st.table is not None and st.count:
                    from veneur_tpu.ops import tdigest
                    vals = np.asarray(
                        tdigest.quantiles(st.table,
                                          np.asarray(qs, np.float32)))
                    quantiles = {q: float(v) for q, v in zip(qs, vals)
                                 if math.isfinite(float(v))}
                out.append((key, TimerStat(st.count, st.sum, quantiles)))
        return out

    # collect-protocol alias so families iterate uniformly
    def samples(self) -> List[Tuple[LabelValues, TimerStat]]:
        return self.snapshot()


class _CallbackMetric:
    """Read-through collector: the value(s) live elsewhere; `fn` is
    evaluated at collect time. `fn` may return a scalar (unlabeled), a
    dict {labelvalues_tuple: value}, or an iterable of
    (labelvalues_tuple, value) pairs."""

    def __init__(self, name: str, fn: Callable, kind: str = "gauge",
                 help: str = "", labelnames: Tuple[str, ...] = ()):
        if kind not in ("counter", "gauge"):
            raise ValueError(f"callback kind {kind!r}")
        self.name = name
        self.fn = fn
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)

    def samples(self) -> List[Tuple[LabelValues, float]]:
        try:
            got = self.fn()
        except Exception as e:
            # a broken collector degrades that one family, never the
            # scrape (an exporter that 500s on one bad read is useless
            # during exactly the incident it exists for)
            log.warning("telemetry collector %s failed: %s", self.name, e)
            return []
        if got is None:
            return []
        if isinstance(got, (int, float)):
            return [((), float(got))]
        if isinstance(got, dict):
            return sorted((tuple(k) if isinstance(k, tuple) else (str(k),),
                           float(v)) for k, v in got.items())
        return sorted((tuple(k), float(v)) for k, v in got)


class TelemetryRegistry:
    """Thread-safe name → instrument map. Registration is get-or-create:
    re-registering an identical (class, labelnames) pair returns the
    existing instrument; a conflicting re-registration raises (the
    check_metric_names.py lint additionally enforces one registration
    SITE per name across the tree)."""

    def __init__(self, timer_compression: float = 50.0):
        self.timer_compression = float(timer_compression)
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _register(self, cls, name: str, help: str,
                  labelnames: Iterable[str], **kw):
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is cls
                        and existing.labelnames == labelnames):
                    return existing
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(existing).__name__}{existing.labelnames}")
            m = cls(name, help=help, labelnames=labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def timer(self, name: str, help: str = "",
              labelnames: Iterable[str] = (),
              compression: Optional[float] = None) -> Timer:
        return self._register(
            Timer, name, help, labelnames,
            compression=(self.timer_compression if compression is None
                         else compression))

    def callback(self, name: str, fn: Callable, kind: str = "gauge",
                 help: str = "",
                 labelnames: Iterable[str] = ()) -> _CallbackMetric:
        labelnames = tuple(labelnames)
        with self._lock:
            if name in self._metrics:
                raise ValueError(f"metric {name!r} already registered")
            m = _CallbackMetric(name, fn, kind=kind, help=help,
                                labelnames=labelnames)
            self._metrics[name] = m
            return m

    def get(self, name: str):
        with self._lock:
            return self._metrics[name]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def collect(self) -> List[object]:
        """Instruments in name order; each has .name/.kind/.help/
        .labelnames/.samples(). samples() values are floats, except
        Timers which yield TimerStat."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        return [m for _, m in metrics]

    def flat_values(self) -> Dict[str, float]:
        """The JSON-friendly view `/stats` serves: one key per series,
        labeled series as name{k=v,...}; timers contribute exact
        .count/.sum (quantile extraction is scrape-time work that a
        JSON poller doesn't need)."""
        out: Dict[str, float] = {}

        def series(name, labelnames, labelvalues):
            if not labelnames:
                return name
            inner = ",".join(f"{k}={v}"
                             for k, v in zip(labelnames, labelvalues))
            return f"{name}{{{inner}}}"

        for m in self.collect():
            if isinstance(m, Timer):
                for lv, stat in m.snapshot(qs=()):
                    base = series(m.name, m.labelnames, lv)
                    out[base + ".count"] = float(stat.count)
                    out[base + ".sum"] = float(stat.sum)
            else:
                for lv, v in m.samples():
                    out[series(m.name, m.labelnames, lv)] = float(v)
        return out
