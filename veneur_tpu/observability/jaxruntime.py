"""JAX runtime telemetry: compile events, synced step timing, HBM gauges,
and on-demand profiler captures.

The single biggest silent perf cliff in this codebase is an accidental
recompile of the ingest/flush programs (a shape-static argument that
isn't, a new batch geometry) — the whole TPU-first design is "one
resident executable per batch". jax.monitoring fires a duration event
(`.../backend_compile_duration`) every time XLA actually compiles, so a
recompile storm shows up as a climbing counter instead of a mysterious
10x flush-latency regression.

The listener is process-global and idempotent (jax.monitoring has no
unregister; multiple Server instances in one process — the test suite —
must not stack listeners). Servers export the accumulators through
registry callbacks, so every server's /metrics reports the same
process-wide truth.

This module is also the ONE sanctioned device-sync site: XLA dispatch is
async, so `perf_counter_ns` around a bare step call measures dispatch
latency, not device time. sync_and_time() times a block_until_ready on
the result token; aggregators sample it every N steps (and at every
swap) so `step_ns` means what it says while `dispatch_ns` keeps the
cheap always-on host-side number. The vtlint timer-sync pass enforces
the split everywhere else.
"""

from __future__ import annotations

import logging
import tempfile
import threading
import time

log = logging.getLogger("veneur_tpu.observability.jax")

_lock = threading.Lock()
_installed = False
_compiles_total = 0
_compile_seconds_total = 0.0

# substring match: the exact event path has varied across jax versions
# (/jax/core/compile/backend_compile_duration today)
_COMPILE_EVENT = "backend_compile_duration"


def _on_duration(event: str, duration_secs: float, **_kw) -> None:
    global _compiles_total, _compile_seconds_total
    if _COMPILE_EVENT not in event:
        return
    with _lock:
        _compiles_total += 1
        _compile_seconds_total += float(duration_secs)


def install() -> bool:
    """Register the compile listener once per process; safe to call from
    every Server.__init__. Returns False when jax.monitoring is absent
    (the accumulators then just stay 0)."""
    global _installed
    with _lock:
        if _installed:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception as e:
            log.debug("jax.monitoring unavailable: %s", e)
            return False
        _installed = True
        return True


def compiles_total() -> int:
    with _lock:
        return _compiles_total


def compile_time_ns_total() -> float:
    with _lock:
        return _compile_seconds_total * 1e9


# -- synced step timing -------------------------------------------------------

def sync_and_time(token) -> int:
    """Wall nanoseconds until `token` (a donated step result / pytree of
    device arrays) is actually ready. XLA dispatch is async, so timing a
    bare step call measures host-side dispatch, not device work; this is
    the ONE production sync point — aggregators sample it every
    _SYNC_EVERY steps and at swap(), keeping `step_ns` honest while
    `dispatch_ns` stays the cheap per-step number."""
    import jax
    t0 = time.perf_counter_ns()
    # the sanctioned sampled sync point: callers time device completion
    # here instead of around dispatch
    # vtlint: disable=jax-hot-path -- deliberate sampled device sync
    jax.block_until_ready(token)
    return time.perf_counter_ns() - t0


class SampledSync:
    """Sampled device-sync bookkeeping for dispatch sites that launch
    many small programs (the query tier's batched reads): every
    `every`-th token is synced through sync_and_time() so `sync_ns`
    means device time, while the other N-1 launches pay only enqueue
    cost. Same cadence contract as the aggregators' `_SYNC_EVERY`
    sampling — one shared shape for the vtlint timer-sync rule."""

    def __init__(self, every: int = 64) -> None:
        self.every = max(1, int(every))
        self.count = 0
        self.synced = 0
        self.sync_ns = 0

    def tick(self, token) -> int:
        """Count one launch; on the sampling edge, block on `token` and
        accumulate the wait. Returns the sampled nanoseconds (0 when
        this launch was not sampled)."""
        self.count += 1
        if self.count % self.every:
            return 0
        dt = sync_and_time(token)
        self.synced += 1
        self.sync_ns += dt
        return dt


# -- HBM accounting -----------------------------------------------------------

def hbm_stats() -> dict:
    """{device_label: {"bytes_in_use": n, "peak_bytes_in_use": n}} from
    each local device's allocator. Empty on backends that expose no
    memory_stats (CPU) — callers treat absence as 'no series'."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return {}
    out = {}
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        out[f"{d.platform}:{d.id}"] = {
            "bytes_in_use": int(ms.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", 0)),
        }
    return out


def hbm_bytes_in_use() -> dict:
    return {(label,): s["bytes_in_use"] for label, s in hbm_stats().items()}


def hbm_bytes_peak() -> dict:
    return {(label,): s["peak_bytes_in_use"]
            for label, s in hbm_stats().items()}


# -- on-demand profiler capture ----------------------------------------------

_profile_lock = threading.Lock()


def capture_profile(seconds: float, base_dir: str = None) -> str:
    """Run jax.profiler for `seconds` and return the trace directory.
    One capture at a time per process (the profiler is a global
    resource); a concurrent request raises RuntimeError — the HTTP layer
    maps it to 409."""
    if not _profile_lock.acquire(blocking=False):
        raise RuntimeError("profile capture already in progress")
    try:
        import jax
        trace_dir = tempfile.mkdtemp(prefix="veneur-trace-", dir=base_dir)
        jax.profiler.start_trace(trace_dir)
        try:
            time.sleep(max(0.0, float(seconds)))
        finally:
            jax.profiler.stop_trace()
        return trace_dir
    finally:
        _profile_lock.release()
