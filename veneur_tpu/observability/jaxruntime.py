"""JAX runtime telemetry: jit recompile counts and compile wall time.

The single biggest silent perf cliff in this codebase is an accidental
recompile of the ingest/flush programs (a shape-static argument that
isn't, a new batch geometry) — the whole TPU-first design is "one
resident executable per batch". jax.monitoring fires a duration event
(`.../backend_compile_duration`) every time XLA actually compiles, so a
recompile storm shows up as a climbing counter instead of a mysterious
10x flush-latency regression.

The listener is process-global and idempotent (jax.monitoring has no
unregister; multiple Server instances in one process — the test suite —
must not stack listeners). Servers export the accumulators through
registry callbacks, so every server's /metrics reports the same
process-wide truth.
"""

from __future__ import annotations

import logging
import threading

log = logging.getLogger("veneur_tpu.observability.jax")

_lock = threading.Lock()
_installed = False
_compiles_total = 0
_compile_seconds_total = 0.0

# substring match: the exact event path has varied across jax versions
# (/jax/core/compile/backend_compile_duration today)
_COMPILE_EVENT = "backend_compile_duration"


def _on_duration(event: str, duration_secs: float, **_kw) -> None:
    global _compiles_total, _compile_seconds_total
    if _COMPILE_EVENT not in event:
        return
    with _lock:
        _compiles_total += 1
        _compile_seconds_total += float(duration_secs)


def install() -> bool:
    """Register the compile listener once per process; safe to call from
    every Server.__init__. Returns False when jax.monitoring is absent
    (the accumulators then just stay 0)."""
    global _installed
    with _lock:
        if _installed:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception as e:
            log.debug("jax.monitoring unavailable: %s", e)
            return False
        _installed = True
        return True


def compiles_total() -> int:
    with _lock:
        return _compiles_total


def compile_time_ns_total() -> float:
    with _lock:
        return _compile_seconds_total * 1e9
