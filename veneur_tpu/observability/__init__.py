"""veneur_tpu.observability: telemetry registry, Prometheus exposition,
and JAX runtime telemetry (see registry.py for the design)."""

from veneur_tpu.observability.registry import (Counter, Gauge,  # noqa: F401
                                               TelemetryRegistry, Timer,
                                               TIMER_QUANTILES)
from veneur_tpu.observability.export import (  # noqa: F401
    render_prometheus)
