"""Prometheus text-exposition renderer for a TelemetryRegistry.

Implements the text format version 0.0.4 the reference's
cmd/veneur-prometheus poller consumes (and our cli/prometheus.py
re-implements): `# HELP` / `# TYPE` header lines per family, label
values escaped (`\\` `\"` `\n`), counters/gauges as single samples,
Timers as `summary` families — one `{quantile="..."}` line per exported
quantile plus the exact `_sum` / `_count` series.

Metric names keep veneur's dotted convention internally; dots (and any
other character outside [a-zA-Z0-9_:]) become underscores on the wire,
the same mapping every statsd→prometheus bridge applies in reverse.
"""

from __future__ import annotations

import math
import re

from veneur_tpu.observability.registry import TelemetryRegistry, Timer

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_name(name: str) -> str:
    out = _NAME_BAD_CHARS.sub("_", name)
    if not out or not _NAME_OK.match(out):
        out = "_" + out
    return out


def sanitize_label_name(name: str) -> str:
    out = _LABEL_BAD_CHARS.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels(labelnames, labelvalues, extra=()) -> str:
    pairs = [(sanitize_label_name(k), escape_label_value(v))
             for k, v in zip(labelnames, labelvalues)]
    pairs.extend((k, escape_label_value(v)) for k, v in extra)
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"


def render_prometheus(registry: TelemetryRegistry) -> str:
    lines = []
    for m in registry.collect():
        pname = sanitize_name(m.name)
        if m.help:
            lines.append(f"# HELP {pname} {escape_help(m.help)}")
        lines.append(f"# TYPE {pname} {m.kind}")
        if isinstance(m, Timer):
            for lv, stat in m.samples():
                for q, v in sorted(stat.quantiles.items()):
                    lines.append(
                        f"{pname}"
                        f"{_labels(m.labelnames, lv, [('quantile', repr(float(q)))])}"
                        f" {_fmt_value(v)}")
                base = _labels(m.labelnames, lv)
                lines.append(f"{pname}_sum{base} {_fmt_value(stat.sum)}")
                lines.append(f"{pname}_count{base} {stat.count}")
        else:
            for lv, v in m.samples():
                lines.append(f"{pname}{_labels(m.labelnames, lv)} "
                             f"{_fmt_value(v)}")
    return "\n".join(lines) + "\n"
