"""veneur-proxy configuration (reference config_proxy.go: 26-key
ProxyConfig; same parse pipeline as the server config)."""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import List

import yaml

log = logging.getLogger("veneur_tpu.config")


@dataclasses.dataclass
class ProxyConfig:
    debug: bool = False
    enable_profiling: bool = False
    http_address: str = ""
    grpc_address: str = "127.0.0.1:8128"
    grpc_forward_address: str = ""        # static single destination
    forward_address: str = ""             # legacy static destination
    consul_forward_service_name: str = ""
    consul_forward_grpc_service_name: str = ""
    consul_refresh_interval: str = ""
    consul_url: str = "http://127.0.0.1:8500"
    forward_timeout: str = "10s"
    sentry_dsn: str = ""
    stats_address: str = ""
    runtime_metrics_interval: str = "10s"
    max_idle_conns: int = 0
    max_idle_conns_per_host: int = 100    # config_parse.go:25 default
    idle_connection_timeout: str = ""
    tracing_client_capacity: int = 1024
    tracing_client_flush_interval: str = "500ms"
    tracing_client_metrics_interval: str = "1s"
    ssf_destination_address: str = ""
    trace_address: str = ""
    trace_api_address: str = ""
    # Consul service name for trace-forwarding destinations
    # (reference proxy.go:122 ConsulTraceService; parsed for config
    # compatibility — span routing rides ssf_destination_address here)
    consul_trace_service_name: str = ""
    # exactly-once relay window (forward/envelope.py): > 0 makes the
    # proxy honor sender envelopes — pin per-destination groupings
    # across retries and pass the idempotency key through to globals.
    # Match the globals' forward_dedup_window.
    forward_dedup_window: int = 0
    unknown_keys: List[str] = dataclasses.field(default_factory=list)


_FIELDS = {f.name for f in dataclasses.fields(ProxyConfig)}


def read_proxy_config(path_or_file, env=None) -> ProxyConfig:
    if hasattr(path_or_file, "read"):
        data = yaml.safe_load(path_or_file.read()) or {}
    else:
        with open(path_or_file) as f:
            data = yaml.safe_load(f) or {}
    cfg = ProxyConfig()
    unknown = []
    for k, v in data.items():
        if k in _FIELDS:
            if v is not None:
                setattr(cfg, k, v)
        else:
            unknown.append(k)
    cfg.unknown_keys = sorted(unknown)
    if unknown:
        log.warning("proxy config contains unknown keys: %s",
                    ", ".join(cfg.unknown_keys))
    env = os.environ if env is None else env
    for name in _FIELDS:
        for candidate in (f"VENEUR_PROXY_{name.upper().replace('_', '')}",
                          f"VENEUR_PROXY_{name.upper()}"):
            if candidate in env:
                cur = getattr(cfg, name)
                raw = env[candidate]
                if isinstance(cur, bool):
                    raw = raw.lower() in ("1", "true", "yes", "on")
                elif isinstance(cur, int):
                    raw = int(raw)
                setattr(cfg, name, raw)
                break
    return cfg
