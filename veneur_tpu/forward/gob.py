"""Minimal encoding/gob codec for the reference's HTTP-era forward payloads.

The reference's v1 forwarding path ships sampler state as JSONMetric
objects whose `value` bytes are Go-native encodings
(samplers/samplers.go:102-108 JSONMetric, flusher.go:338 flushForward →
handlers_global.go:115 unmarshalMetricsFromHTTP → worker.go:394
ImportMetric):

  - counter:            little-endian int64           (samplers.go:161 Export)
  - gauge/statuscheck:  little-endian float64         (samplers.go:245/:327)
  - set:                axiomhq HLL MarshalBinary     (samplers.go:406; decoded
                        by veneur_tpu/ops/hll.py)
  - histogram/timer:    encoding/gob of the t-digest  (merging_digest.go:393
                        GobEncode: []Centroid, compression, min, max,
                        reciprocalSum — five separate Encode calls)

This module implements the subset of the gob wire format those payloads
need — self-describing type definitions, struct/slice/float/int/uint/
bytes/string values — so a reference *local* veneur can HTTP-forward into
this global tier and vice versa, with no Go runtime anywhere.

Format notes (verified byte-for-byte against the reference's checked-in
fixtures `testdata/import.uncompressed` and `tdigest/testdata/
oldgob.base64`, which the tests replay):

  - unsigned int: < 128 one byte; else minimal big-endian bytes preceded
    by a byte holding the negated byte count.
  - signed int: bit 0 is the sign flag (u = x<<1, complemented if x<0).
  - float64: math.Float64bits, byte-reversed, sent as unsigned int.
  - message: uvarint byte length, then a signed type id. Negative id ⇒
    a wireType definition for type -id follows; positive id ⇒ a value.
  - struct value: (uvarint field delta, field value)* terminated by 0,
    field numbers starting from -1; zero-valued fields omitted.
  - non-struct top-level value: preceded by one 0x00 byte.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

# builtin gob type ids (the bootstrap types every stream assumes)
T_BOOL, T_INT, T_UINT, T_FLOAT, T_BYTES, T_STRING = 1, 2, 3, 4, 5, 6
T_COMPLEX, T_INTERFACE = 7, 8
T_WIRETYPE, T_ARRAYTYPE, T_COMMONTYPE, T_SLICETYPE = 16, 17, 18, 19
T_STRUCTTYPE, T_FIELDTYPE, T_FIELDTYPE_SLICE, T_MAPTYPE = 20, 21, 22, 23

# descriptors: ("struct", [(name, typeid)...]) | ("slice", elem) |
# ("array", elem, length) | ("map", key, elem) | ("builtin",)
_BOOTSTRAP = {
    T_WIRETYPE: ("struct", [("ArrayT", T_ARRAYTYPE),
                            ("SliceT", T_SLICETYPE),
                            ("StructT", T_STRUCTTYPE),
                            ("MapT", T_MAPTYPE)]),
    T_ARRAYTYPE: ("struct", [("CommonType", T_COMMONTYPE),
                             ("Elem", T_INT), ("Len", T_INT)]),
    T_COMMONTYPE: ("struct", [("Name", T_STRING), ("Id", T_INT)]),
    T_SLICETYPE: ("struct", [("CommonType", T_COMMONTYPE),
                             ("Elem", T_INT)]),
    T_STRUCTTYPE: ("struct", [("CommonType", T_COMMONTYPE),
                              ("Field", T_FIELDTYPE_SLICE)]),
    T_FIELDTYPE: ("struct", [("Name", T_STRING), ("Id", T_INT)]),
    T_FIELDTYPE_SLICE: ("slice", T_FIELDTYPE),
    T_MAPTYPE: ("struct", [("CommonType", T_COMMONTYPE),
                           ("Key", T_INT), ("Elem", T_INT)]),
}


class GobError(ValueError):
    pass


# -- primitive readers --------------------------------------------------------

def _read_uint(data: bytes, pos: int) -> Tuple[int, int]:
    if pos >= len(data):
        raise GobError("truncated gob: expected unsigned int")
    b = data[pos]
    if b < 0x80:
        return b, pos + 1
    n = 0x100 - b   # negated byte count
    if n > 8 or pos + 1 + n > len(data):
        raise GobError("truncated/overlong gob unsigned int")
    return int.from_bytes(data[pos + 1:pos + 1 + n], "big"), pos + 1 + n


def _read_int(data: bytes, pos: int) -> Tuple[int, int]:
    u, pos = _read_uint(data, pos)
    return (~(u >> 1) if u & 1 else u >> 1), pos


def _read_float(data: bytes, pos: int) -> Tuple[float, int]:
    u, pos = _read_uint(data, pos)
    rev = int.from_bytes(u.to_bytes(8, "big")[::-1], "big")
    return struct.unpack(">d", rev.to_bytes(8, "big"))[0], pos


# -- primitive writers --------------------------------------------------------

def _w_uint(out: bytearray, u: int) -> None:
    if u < 0x80:
        out.append(u)
        return
    b = u.to_bytes((u.bit_length() + 7) // 8, "big")
    out.append(0x100 - len(b))
    out.extend(b)


def _w_int(out: bytearray, x: int) -> None:
    _w_uint(out, (~x << 1) | 1 if x < 0 else x << 1)


def _w_float(out: bytearray, f: float) -> None:
    bits = struct.unpack(">Q", struct.pack(">d", f))[0]
    _w_uint(out, int.from_bytes(bits.to_bytes(8, "big")[::-1], "big"))


def _w_string(out: bytearray, s: str) -> None:
    b = s.encode()
    _w_uint(out, len(b))
    out.extend(b)


# -- decoder ------------------------------------------------------------------

class Decoder:
    """Decodes one gob stream (a sequence of Encode calls by one
    encoder). Each call to the Go side's Encode produced zero or more
    type-definition messages then one value message; decode_all returns
    the list of top-level values in order."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0
        self.types: Dict[int, tuple] = dict(_BOOTSTRAP)

    def at_end(self) -> bool:
        return self.pos >= len(self.data)

    def decode_all(self) -> List[Any]:
        out = []
        while not self.at_end():
            out.append(self._next_value())
        return out

    def _next_value(self) -> Any:
        while True:
            length, p = _read_uint(self.data, self.pos)
            if p + length > len(self.data):
                raise GobError("truncated gob message")
            end = p + length
            tid, p = _read_int(self.data, p)
            if tid < 0:
                # type definition for id -tid: a wireType value follows
                wire, p = self._decode_value(T_WIRETYPE, p)
                self._register(-tid, wire)
                if p != end:
                    raise GobError("trailing bytes in type definition")
                self.pos = end
                continue
            desc = self.types.get(tid)
            if desc is None and tid > T_INTERFACE:
                raise GobError(f"value of undefined gob type {tid}")
            if desc is None or desc[0] != "struct":
                delta, p = _read_uint(self.data, p)
                if delta != 0:
                    raise GobError("non-struct value missing 0x00 prefix")
            val, p = self._decode_value(tid, p)
            if p != end:
                raise GobError("trailing bytes in value message")
            self.pos = end
            return val

    def _register(self, tid: int, wire: Dict[str, Any]) -> None:
        if "StructT" in wire:
            st = wire["StructT"]
            fields = [(f.get("Name", ""), f.get("Id", 0))
                      for f in st.get("Field", [])]
            self.types[tid] = ("struct", fields)
        elif "SliceT" in wire:
            self.types[tid] = ("slice", wire["SliceT"].get("Elem", 0))
        elif "ArrayT" in wire:
            at = wire["ArrayT"]
            self.types[tid] = ("array", at.get("Elem", 0), at.get("Len", 0))
        elif "MapT" in wire:
            mt = wire["MapT"]
            self.types[tid] = ("map", mt.get("Key", 0), mt.get("Elem", 0))
        else:
            raise GobError(f"unsupported wireType for id {tid}: {wire}")

    def _decode_value(self, tid: int, p: int) -> Tuple[Any, int]:
        data = self.data
        if tid == T_BOOL:
            u, p = _read_uint(data, p)
            return bool(u), p
        if tid == T_INT:
            return _read_int(data, p)
        if tid == T_UINT:
            return _read_uint(data, p)
        if tid == T_FLOAT:
            return _read_float(data, p)
        if tid in (T_BYTES, T_STRING):
            n, p = _read_uint(data, p)
            if p + n > len(data):
                raise GobError("truncated gob bytes/string")
            raw = data[p:p + n]
            return (raw.decode() if tid == T_STRING else raw), p + n
        desc = self.types.get(tid)
        if desc is None:
            raise GobError(f"undefined gob type id {tid}")
        kind = desc[0]
        if kind == "struct":
            fields = desc[1]
            val: Dict[str, Any] = {}
            fieldnum = -1
            while True:
                delta, p = _read_uint(data, p)
                if delta == 0:
                    return val, p
                fieldnum += delta
                if fieldnum >= len(fields):
                    raise GobError(f"field number {fieldnum} out of range "
                                   f"for gob type {tid}")
                name, ftid = fields[fieldnum]
                val[name], p = self._decode_value(ftid, p)
        if kind == "slice":
            n, p = _read_uint(data, p)
            if n > len(data) - p:   # each element is ≥ 1 byte
                raise GobError("gob slice length exceeds buffer")
            items = []
            for _ in range(n):
                item, p = self._decode_value(desc[1], p)
                items.append(item)
            return items, p
        if kind == "array":
            n, p = _read_uint(data, p)
            if n != desc[2]:
                raise GobError("gob array length mismatch")
            items = []
            for _ in range(n):
                item, p = self._decode_value(desc[1], p)
                items.append(item)
            return items, p
        raise GobError(f"unsupported gob kind {kind!r}")


# -- encoder ------------------------------------------------------------------

class Encoder:
    """Produces gob streams for a fixed schema. Type ids are allocated
    from 65 upward in definition order, mirroring a fresh Go encoder (the
    canonical MergingDigest stream's prefix is asserted byte-identical to
    the reference fixture in tests/test_reference_compat.py)."""

    def __init__(self):
        self.out = bytearray()

    def _message(self, payload: bytes) -> None:
        _w_uint(self.out, len(payload))
        self.out.extend(payload)

    def _encode_by_desc(self, out: bytearray, desc: tuple, val: Any) -> None:
        kind = desc[0]
        if kind == "builtin":
            tid = desc[1]
            if tid == T_INT:
                _w_int(out, val)
            elif tid == T_UINT:
                _w_uint(out, val)
            elif tid == T_FLOAT:
                _w_float(out, val)
            elif tid == T_STRING:
                _w_string(out, val)
            elif tid == T_BYTES:
                _w_uint(out, len(val))
                out.extend(val)
            elif tid == T_BOOL:
                _w_uint(out, 1 if val else 0)
            else:
                raise GobError(f"cannot encode builtin {tid}")
        elif kind == "struct":
            fieldnum = -1
            for i, (name, fdesc) in enumerate(desc[1]):
                fval = val.get(name)
                if fval is None or fval == 0 or fval == "" or fval == []:
                    continue   # gob omits zero-valued fields
                _w_uint(out, i - fieldnum)
                self._encode_by_desc(out, fdesc, fval)
                fieldnum = i
            _w_uint(out, 0)
        elif kind == "slice":
            _w_uint(out, len(val))
            for item in val:
                self._encode_by_desc(out, desc[1], item)
        else:
            raise GobError(f"cannot encode kind {kind!r}")

    def write_value(self, tid: int, desc: tuple, val: Any) -> None:
        payload = bytearray()
        _w_int(payload, tid)
        if desc[0] != "struct":
            _w_uint(payload, 0)   # non-struct top-level marker
        self._encode_by_desc(payload, desc, val)
        self._message(bytes(payload))

    def write_typedef(self, tid: int, wire_field: str, body: bytes) -> None:
        """Emit a type-definition message: wireType{<field>: <body>}."""
        field_index = {"ArrayT": 0, "SliceT": 1, "StructT": 2,
                       "MapT": 3}[wire_field]
        payload = bytearray()
        _w_int(payload, -tid)
        _w_uint(payload, field_index + 1)   # delta from -1
        payload.extend(body)
        _w_uint(payload, 0)                  # end wireType
        self._message(bytes(payload))


def _common_type(name: str, tid: int) -> bytes:
    out = bytearray()
    if name:
        _w_uint(out, 1)          # field 0 Name
        _w_string(out, name)
        _w_uint(out, 1)          # delta 1 -> field 1 Id
    else:
        _w_uint(out, 2)          # skip Name: delta 2 -> field 1 Id
    _w_int(out, tid)
    _w_uint(out, 0)
    return bytes(out)


# -- the MergingDigest schema -------------------------------------------------

# Fresh-encoder id allocation for MergingDigest.GobEncode (verified
# against tdigest/testdata/oldgob.base64): 65 Centroid, 66 []float64,
# 67 []Centroid. The first Encode([]Centroid) emits defs 67, 65, 66.
_ID_CENTROID, _ID_FLOATS, _ID_CENTROIDS = 65, 66, 67

_CENTROID_DESC = ("struct", [("Mean", ("builtin", T_FLOAT)),
                             ("Weight", ("builtin", T_FLOAT)),
                             ("Samples", ("slice", ("builtin", T_FLOAT)))])
_CENTROIDS_DESC = ("slice", _CENTROID_DESC)
_FLOAT_DESC = ("builtin", T_FLOAT)


def _digest_typedefs(enc: Encoder) -> None:
    # []Centroid (unnamed slice): wireType{SliceT:{CommonType{Id:67},Elem:65}}
    body = bytearray()
    _w_uint(body, 1)                         # field 0 CommonType
    body.extend(_common_type("", _ID_CENTROIDS))
    _w_uint(body, 1)                         # field 1 Elem
    _w_int(body, _ID_CENTROID)
    _w_uint(body, 0)
    enc.write_typedef(_ID_CENTROIDS, "SliceT", bytes(body))

    # Centroid struct
    body = bytearray()
    _w_uint(body, 1)
    body.extend(_common_type("Centroid", _ID_CENTROID))
    _w_uint(body, 1)                         # field 1 Field: 3 fieldTypes
    _w_uint(body, 3)
    for fname, ftid in (("Mean", T_FLOAT), ("Weight", T_FLOAT),
                        ("Samples", _ID_FLOATS)):
        _w_uint(body, 1)
        _w_string(body, fname)
        _w_uint(body, 1)
        _w_int(body, ftid)
        _w_uint(body, 0)
    _w_uint(body, 0)
    enc.write_typedef(_ID_CENTROID, "StructT", bytes(body))

    # []float64 named slice
    body = bytearray()
    _w_uint(body, 1)
    body.extend(_common_type("[]float64", _ID_FLOATS))
    _w_uint(body, 1)
    _w_int(body, T_FLOAT)
    _w_uint(body, 0)
    enc.write_typedef(_ID_FLOATS, "SliceT", bytes(body))


def encode_digest(means, weights, compression: float, minimum: float,
                  maximum: float, reciprocal_sum: float = 0.0) -> bytes:
    """MergingDigest.GobEncode-compatible bytes (merging_digest.go:393):
    []Centroid, compression, min, max, reciprocalSum."""
    enc = Encoder()
    _digest_typedefs(enc)
    centroids = [{"Mean": float(m), "Weight": float(w), "Samples": []}
                 for m, w in zip(means, weights)]
    enc.write_value(_ID_CENTROIDS, _CENTROIDS_DESC, centroids)
    for f in (compression, minimum, maximum, reciprocal_sum):
        enc.write_value(T_FLOAT, _FLOAT_DESC, float(f))
    return bytes(enc.out)


def decode_digest(data: bytes) -> Dict[str, Any]:
    """Decode MergingDigest.GobEncode bytes into centroid arrays +
    scalars. reciprocalSum is EOF-tolerant (merging_digest.go:433: older
    peers don't send it)."""
    values = Decoder(data).decode_all()
    if len(values) < 4:
        raise GobError(f"digest gob has {len(values)} values, expected >=4")
    centroids, compression, minimum, maximum = values[:4]
    recip = values[4] if len(values) > 4 else 0.0
    if not isinstance(centroids, list):
        raise GobError("digest gob: first value is not a centroid list")
    means = [c.get("Mean", 0.0) for c in centroids]
    wts = [c.get("Weight", 0.0) for c in centroids]
    return {"means": means, "weights": wts, "compression": compression,
            "min": minimum, "max": maximum, "recip": recip}


# -- JSONMetric scalar payloads ----------------------------------------------

def encode_counter(value: int) -> bytes:
    """little-endian int64 (samplers.go:161-167)."""
    return struct.pack("<q", int(value))


def decode_counter(data: bytes) -> int:
    if len(data) != 8:
        raise GobError(f"counter payload must be 8 bytes, got {len(data)}")
    return struct.unpack("<q", data)[0]


def encode_gauge(value: float) -> bytes:
    """little-endian float64 (samplers.go:245-251, :327-333)."""
    return struct.pack("<d", float(value))


def decode_gauge(data: bytes) -> float:
    if len(data) != 8:
        raise GobError(f"gauge payload must be 8 bytes, got {len(data)}")
    return struct.unpack("<d", data)[0]
