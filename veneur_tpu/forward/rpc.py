"""gRPC bindings for the Forward service, hand-wired (no codegen plugin in
this image). The method path `/forwardrpc.Forward/SendMetrics` and message
types match the reference's forwardrpc/forward.proto, so this client can
forward to a reference global veneur and this server can accept from a
reference local one."""

from __future__ import annotations

import logging
import threading
from concurrent import futures
from typing import Callable, List

import grpc
from google.protobuf import empty_pb2

from veneur_tpu.forward.envelope import Envelope, EnvelopeError
from veneur_tpu.proto import forwardrpc_pb2 as fpb
from veneur_tpu.reliability.faults import FAULTS, FORWARD_ACK, FORWARD_SEND

log = logging.getLogger("veneur_tpu.forward.rpc")

METHOD = "/forwardrpc.Forward/SendMetrics"


class AmbiguousResultError(Exception):
    """The send MAY have been applied: DEADLINE_EXCEEDED/CANCELLED land
    after the request left this process, so the receiver could have
    folded the batch before the deadline fired. Retrying must re-send
    the SAME (source_id, epoch, seq) — never a re-merged payload — so
    the receiver's dedup window can suppress the possible duplicate."""

    def __init__(self, code, cause: Exception):
        super().__init__(f"ambiguous forward result ({code}): {cause}")
        self.code = code
        self.cause = cause


# status codes where the request may have reached (and been folded by)
# the receiver even though the caller saw an error
_AMBIGUOUS_CODES = (grpc.StatusCode.DEADLINE_EXCEEDED,
                    grpc.StatusCode.CANCELLED)


class ForwardClient:
    """Forwarding client (reference flusher.go:474 forwardGRPC; single Dial
    at Start, server.go:843-851).

    Unlike the reference's one-Dial-forever channel, a send that fails
    with UNAVAILABLE tears the channel down and redials before the next
    attempt: grpc-python channels can wedge permanently after the peer
    restarts, and a local that never re-resolves its global is an outage
    that survives the outage. `wait_for_ready` queues RPCs while the
    channel (re)connects instead of failing fast."""

    def __init__(self, address: str, wait_for_ready: bool = False):
        self.address = address
        self.wait_for_ready = wait_for_ready
        self.reconnects_total = 0
        self._lock = threading.Lock()
        self._connect()

    def _connect(self) -> None:
        self._channel = grpc.insecure_channel(self.address)
        self._send = self._channel.unary_unary(
            METHOD,
            request_serializer=fpb.MetricList.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)
        self._send_raw = None   # rebuilt lazily against the new channel

    def reconnect(self) -> None:
        """Replace the channel (and its cached callables) with a fresh
        dial. Safe under concurrent sends: they hold a reference to the
        old callable and merely fail once more."""
        with self._lock:
            old = self._channel
            self._connect()
            self.reconnects_total += 1
        log.warning("forward channel to %s recreated after UNAVAILABLE "
                    "(%d reconnects)", self.address, self.reconnects_total)
        try:
            old.close()
        except Exception as e:
            log.debug("closing stale forward channel: %s", e)

    def send_metrics(self, metrics: List, timeout: float = 10.0,
                     parent_span=None, trace_client=None,
                     envelope: Envelope = None) -> None:
        # parent_span/trace_client accepted for interface parity with the
        # HTTP client; the reference's gRPC forward doesn't propagate
        # trace headers either (flusher.go:474 forwardGRPC has no Inject)
        FAULTS.inject(FORWARD_SEND, name=self.address)
        md = envelope.to_metadata() if envelope is not None else None
        try:
            self._send(fpb.MetricList(metrics=metrics), timeout=timeout,
                       metadata=md, wait_for_ready=self.wait_for_ready)
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            if code == grpc.StatusCode.UNAVAILABLE:
                self.reconnect()
                raise
            if code in _AMBIGUOUS_CODES:
                # the receiver may have folded this batch; surface it as
                # ambiguous so the retry layer re-sends the same seq
                raise AmbiguousResultError(code, e) from e
            raise
        # a lost ack: the RPC succeeded (receiver folded) but the armed
        # fault makes this sender see a failure and retry the same seq
        FAULTS.inject(FORWARD_ACK, name=self.address)

    def send_serialized(self, data: bytes, timeout: float = 10.0,
                        wait: bool = True, envelope: Envelope = None):
        """Send an ALREADY-serialized MetricList (sustained-absorption
        benchmarking: client-side marshal cost out of the timed loop).
        With wait=False returns a grpc future — callers overlap requests
        the way a whole local fleet does against one global."""
        with self._lock:
            if self._send_raw is None:
                self._send_raw = self._channel.unary_unary(
                    METHOD, request_serializer=bytes,
                    response_deserializer=empty_pb2.Empty.FromString)
            send_raw = self._send_raw
        md = envelope.to_metadata() if envelope is not None else None
        if wait:
            send_raw(data, timeout=timeout, metadata=md)
            return None
        return send_raw.future(data, timeout=timeout, metadata=md)

    def close(self):
        with self._lock:
            self._channel.close()


class HTTPForwardClient:
    """HTTP-era forwarding (reference flusher.go:338 flushForward →
    POST /import): a zlib-deflated JSON array of JSONMetric objects whose
    value bytes are the reference's own sampler encodings (gob digests,
    LE scalars, axiomhq HLLs — veneur_tpu/forward/{jsonmetric,gob}.py),
    so the peer may be a reference global or this framework's. Pass
    json_body=False for the deflated-protobuf MetricList body instead
    (this framework's compact v2-over-HTTP variant)."""

    def __init__(self, address: str, json_body: bool = True,
                 retry_policy=None):
        self.address = address.rstrip("/")
        self.json_body = json_body
        # reliability.policy.RetryPolicy (or None = single attempt);
        # applied per-POST inside traced_post so every attempt re-runs
        # the whole connect/send/status pipeline
        self.retry_policy = retry_policy
        if not self.address.startswith(("http://", "https://")):
            self.address = "http://" + self.address

    def send_metrics(self, metrics: List, timeout: float = 10.0,
                     parent_span=None, trace_client=None,
                     envelope: Envelope = None) -> None:
        import json

        if self.json_body:
            from veneur_tpu.forward.jsonmetric import to_json_metrics
            payload = to_json_metrics(metrics)
            if envelope is not None:
                # the envelope rides in the JSON import body itself (and
                # the headers, below) so a peer that re-serializes the
                # body — the proxy — keeps the idempotency key attached
                payload = {"envelope": envelope.to_json(),
                           "metrics": payload}
            body = json.dumps(payload).encode()
            ctype = "application/json"
        else:
            body = fpb.MetricList(metrics=metrics).SerializeToString()
            ctype = "application/x-protobuf"
        self._post(body, ctype, timeout, parent_span, trace_client,
                   envelope=envelope)
        # lost-ack injection point: the POST got its 202 (receiver
        # folded) but this sender is made to see a failure and retry
        FAULTS.inject(FORWARD_ACK, name=self.address)

    def send_json(self, json_metrics: List[dict], timeout: float = 10.0,
                  envelope: Envelope = None) -> None:
        """POST an already-formed JSONMetric array unchanged — the proxy
        re-routing path (proxy.go:622 doPost forwards the incoming
        samplers.JSONMetric values verbatim). With an envelope the body
        is the wrapped form {"envelope": ..., "metrics": [...]}."""
        import json
        payload = json_metrics
        if envelope is not None:
            payload = {"envelope": envelope.to_json(),
                       "metrics": json_metrics}
        self._post(json.dumps(payload).encode(), "application/json",
                   timeout, envelope=envelope)

    def _post(self, body: bytes, ctype: str, timeout: float,
              parent_span=None, trace_client=None,
              envelope: Envelope = None) -> None:
        import zlib

        headers = {"Content-Type": ctype, "Content-Encoding": "deflate"}
        if envelope is not None:
            headers.update(envelope.to_metadata())
        if parent_span is not None:
            # propagate the caller's flush trace like the reference's
            # instrumented PostHelper (http/http.go InjectRequest): the
            # global's /import child spans join the local's flush tree
            from veneur_tpu.trace.opentracing import GLOBAL_TRACER
            GLOBAL_TRACER.inject_header(parent_span, headers)
        # per-connection-event span chain (http/http.go TraceRoundTripper)
        from veneur_tpu.forward.tracedhttp import traced_post
        traced_post(f"{self.address}/import", zlib.compress(body), headers,
                    timeout=timeout, parent_span=parent_span,
                    trace_client=trace_client, action="forward",
                    retry_policy=self.retry_policy)

    def close(self):
        pass


def make_forward_service(handler: Callable[[List], None],
                         raw: bool = False, with_metadata: bool = False,
                         on_reject: Callable[[], None] = None):
    """A generic gRPC handler for the Forward service calling
    `handler(metrics)` per request (the shape of reference
    internal/forwardtest/server.go). With `raw`, the request is NOT
    deserialized — `handler(serialized_bytes)` receives the wire
    MetricList for the native import decoder (vi_import), skipping the
    Python protobuf object layer entirely.

    With `with_metadata`, the exactly-once contract applies: the call is
    `handler(payload, envelope=Envelope|None)`; a malformed envelope
    aborts INVALID_ARGUMENT (rejected, never folded; `on_reject` is
    called first so the server can account it — handler-raised
    EnvelopeErrors are NOT re-counted, the handler already did), and a
    handler returning False (shed/unadmitted) aborts RESOURCE_EXHAUSTED
    so the sender does NOT take the RPC as an ack and keeps the unit
    spilled."""

    def _dispatch(payload, context):
        if not with_metadata:
            handler(payload)
            return empty_pb2.Empty()
        try:
            env = Envelope.from_mapping(dict(context.invocation_metadata()))
        except EnvelopeError as e:
            if on_reject is not None:
                on_reject()
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        try:
            ok = handler(payload, envelope=env)
        except EnvelopeError as e:
            # window-skip rejection; the handler counted it — the sender
            # must not take this as an ack either
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if ok is False:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          "import not admitted")
        return empty_pb2.Empty()

    def send_metrics(request: fpb.MetricList, context):
        return _dispatch(list(request.metrics), context)

    def send_metrics_raw(request: bytes, context):
        return _dispatch(request, context)

    rpc_handler = grpc.method_handlers_generic_handler(
        "forwardrpc.Forward",
        {"SendMetrics": grpc.unary_unary_rpc_method_handler(
            send_metrics_raw if raw else send_metrics,
            request_deserializer=(bytes if raw
                                  else fpb.MetricList.FromString),
            response_serializer=empty_pb2.Empty.SerializeToString)})
    return rpc_handler


def serve(handler: Callable[[List], None], address: str = "127.0.0.1:0",
          max_workers: int = 4, raw: bool = False,
          with_metadata: bool = False,
          on_reject: Callable[[], None] = None):
    """Start a Forward gRPC server; returns (server, bound_port)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (make_forward_service(handler, raw=raw,
                              with_metadata=with_metadata,
                              on_reject=on_reject),))
    port = server.add_insecure_port(address)
    server.start()
    return server, port
