"""Outbound HTTP POST with per-connection-event trace spans.

Reference behavioral contract: http/http.go:55-129 — PostHelper wraps
its transport in a TraceRoundTripper whose httptrace hooks emit a CHAIN
of consecutive child spans, each covering one phase of the connection:

    http.resolvingDNS      DNS start            -> connect start
    http.connecting        connect start        -> connection obtained
    http.gotConnection.*   connection obtained  -> headers written
    http.finishedHeaders   headers written      -> body written
    http.finishedWrite     body written         -> first response byte
    http.gotFirstByte      first response byte  -> request done

`gotConnection.{new,reused}` also carries a `was_idle` tag and a
`<action>.connections_used_total` count sample (http.go:73-81). Python's
urllib exposes no httptrace equivalent, so this module drives the
request through raw socket + http.client and marks the phases itself;
with no connection pool every connection is `new`. The roundtrip parent
span is tagged `action` like the reference (http.go:130 RoundTrip).

Used by the HTTP forward client (forward/rpc.py); sink POSTs keep plain
urllib — their flushes are already individually span-wrapped by the
server's sink fan-out (server.py _flush_sink), which covers the same
observability need the reference meets via PostHelper's action spans.
"""

from __future__ import annotations

import http.client
import socket
import ssl
from typing import Dict, Optional, Tuple
from urllib.parse import urlparse

from veneur_tpu.reliability.faults import FAULTS, HTTP_POST
from veneur_tpu.samplers import ssf_samples


class _SpanChain:
    """The rolling span of http.go:61 startSpan: starting a phase
    finishes the previous one, so the chain tiles the request timeline
    with no gaps."""

    def __init__(self, parent, client):
        self.parent = parent
        self.client = client
        self.cur = None

    def start(self, name: str):
        if self.cur is not None:
            self.cur.client_finish(self.client)
            self.cur = None
        if self.parent is not None:
            self.cur = self.parent.child(name)
        return self.cur

    def finish(self):
        if self.cur is not None:
            self.cur.client_finish(self.client)
            self.cur = None


def traced_post(url: str, body: bytes, headers: Dict[str, str],
                timeout: float = 10.0, parent_span=None,
                trace_client=None, action: str = "forward",
                retry_policy=None) -> Tuple[int, bytes]:
    """POST `body` to `url`, emitting the reference's connection-event
    span chain as children of a roundtrip span under `parent_span`
    (no-ops when parent_span/trace_client are None). Returns
    (status, response body); raises on connection errors and on any
    non-2xx status — redirects are never followed (a followed 301
    would silently drop the forward body).

    `retry_policy` (reliability.policy.RetryPolicy) reruns the whole
    attempt — DNS, connect, send, status check — with its backoff; each
    attempt emits its own span chain, so retried forwards are visible as
    repeated http.post spans rather than one long mystery gap."""
    if retry_policy is None:
        return _traced_post_once(url, body, headers, timeout, parent_span,
                                 trace_client, action)
    return retry_policy.run(
        lambda: _traced_post_once(url, body, headers, timeout, parent_span,
                                  trace_client, action))


def _traced_post_once(url: str, body: bytes, headers: Dict[str, str],
                      timeout: float, parent_span, trace_client,
                      action: str) -> Tuple[int, bytes]:
    # inside the retry loop so an armed `times=N` fault exhausts after N
    # attempts — the recover-after-retries chaos scenario
    FAULTS.inject(HTTP_POST, name=url)
    u = urlparse(url)
    host = u.hostname or ""
    tls = u.scheme == "https"
    port = u.port or (443 if tls else 80)
    path = u.path or "/"
    if u.query:
        path += "?" + u.query

    rt = parent_span.child("http.post") if parent_span is not None else None
    if rt is not None:
        rt.set_tag("action", action)
        # README §Monitoring: veneur.<action>.content_length_bytes — the
        # POST body size PostHelper reports (http/http.go:202, a count
        # sample carrying the byte length)
        rt.add(ssf_samples.count(
            "veneur." + action + ".content_length_bytes", len(body)))

    import urllib.request
    proxies = urllib.request.getproxies()
    if u.scheme in proxies and not urllib.request.proxy_bypass(host):
        # an egress proxy owns the connection lifecycle — the event
        # chain would describe the proxy hop, not the destination.
        # Route through urllib (which applies the proxy) under the
        # roundtrip span alone.
        try:
            req = urllib.request.Request(url, data=body, method="POST",
                                         headers=headers)
            # refuse redirects: urllib's default handler would reissue
            # a 301 as a bodyless GET and report success — the same
            # silent forward drop the direct path's non-2xx guard
            # prevents. Returning None makes 3xx raise HTTPError.
            class _NoRedirect(urllib.request.HTTPRedirectHandler):
                def redirect_request(self, *a, **k):
                    return None

            opener = urllib.request.build_opener(
                urllib.request.ProxyHandler(proxies), _NoRedirect())
            # non-2xx (3xx included, via _NoRedirect) raises HTTPError
            # from opener.open — no status check needed here
            with opener.open(req, timeout=timeout) as resp:
                return resp.status, resp.read()
        except Exception:
            if rt is not None:
                rt.error = True
            raise
        finally:
            if rt is not None:
                rt.client_finish(trace_client)

    chain = _SpanChain(rt, trace_client)
    sock = None
    conn: Optional[http.client.HTTPConnection] = None
    try:
        chain.start("http.resolvingDNS")
        infos = socket.getaddrinfo(host, port, type=socket.SOCK_STREAM)

        chain.start("http.connecting")
        err = None
        for af, stype, proto, _cn, sa in infos:
            # urllib/create_connection semantics: try each resolved
            # address (a dual-stack host with no v6 route must still
            # reach the v4 address)
            try:
                sock = socket.socket(af, stype, proto)
                sock.settimeout(timeout)
                sock.connect(sa)
                err = None
                break
            except OSError as e:
                err = e
                if sock is not None:
                    sock.close()
                    sock = None
        if err is not None:
            raise err
        if tls:
            ctx = ssl.create_default_context()
            sock = ctx.wrap_socket(sock, server_hostname=host)

        sp = chain.start("http.gotConnection.new")
        if sp is not None:
            sp.set_tag("was_idle", "false")
            sp.add(ssf_samples.count(
                "veneur." + action + ".connections_used_total", 1,
                {"state": "new"}))

        # HTTPSConnection for its default_port=443, so the Host header
        # omits the port exactly as a stock client would (strict virtual
        # hosts reject 'Host: example.com:443')
        conn_cls = (http.client.HTTPSConnection if tls
                    else http.client.HTTPConnection)
        conn = conn_cls(host, port, timeout=timeout)
        conn.sock = sock
        sock = None   # conn owns it now
        conn.putrequest("POST", path, skip_host=False,
                        skip_accept_encoding=True)
        for k, v in headers.items():
            conn.putheader(k, v)
        conn.putheader("Content-Length", str(len(body)))
        conn.endheaders()

        chain.start("http.finishedHeaders")
        conn.send(body)

        chain.start("http.finishedWrite")
        resp = conn.getresponse()

        chain.start("http.gotFirstByte")
        data = resp.read()
        if resp.status >= 300:
            # redirects are NOT followed — a 301 that urllib would chase
            # must surface as an error, never as a silently-dropped
            # forward (the reference's PostHelper accepts 2xx only)
            raise RuntimeError(
                f"POST {url} -> {resp.status}: {data[:200]!r}")
        return resp.status, data
    except Exception:
        if rt is not None:
            rt.error = True
        raise
    finally:
        chain.finish()
        if conn is not None:
            conn.close()
        if sock is not None:
            sock.close()
        if rt is not None:
            rt.client_finish(trace_client)
