"""Exactly-once forwarding envelope and the receiver-side dedup window.

The local→proxy→global forward path is at-least-once by construction:
ambiguous gRPC timeouts re-send, the spill buffer replays across
restarts, and a crash-restore re-forwards the last checkpointed
interval. HLL register folds and LWW gauges absorb duplicates, but
counter accumulators and t-digest centroid weights are ADDITIVE — every
duplicate fold inflates global counts and quantile weights. The
transport therefore carries an idempotency key:

    (source_id, epoch, seq)

  source_id  128-bit hex id minted once per local server and persisted
             in the checkpoint manifest, so a restart keeps its stream.
  epoch      bumped on EVERY restore/restart. Seqs minted after the
             last checkpoint are lost with the process; reusing them
             would make the receiver falsely suppress fresh data, so a
             restarted sender opens a new epoch instead.
  seq        monotone per (source_id, epoch), one per forward unit (an
             interval's exported payload). Retries — ambiguous timeout,
             spill replay, proxy re-attempt — re-send the SAME seq.

The envelope travels as gRPC metadata / HTTP headers (and optionally a
wrapped JSON import body), so it survives proxy re-routing: a re-routed
duplicate is suppressed at whichever global instance folds it.

Receivers keep one DedupWindow per (source_id, epoch) stream: a
high-water mark plus a bitmap of the last `window` seqs. Semantics:

  seq unseen and within the window        -> fresh (fold it)
  seq already marked                      -> duplicate (suppress + ACK)
  seq below high-water - window (stale)   -> conservatively suppressed;
     the window size bounds how stale a replay can be and still be
     distinguished — see README §Exactly-once forwarding
  seq jumping more than max_skip ahead    -> EnvelopeError (rejected;
     a corrupt or hostile envelope must not wipe the whole bitmap)

Suppressed duplicates are still ACKED (success to the sender) so the
sender evicts the unit from its spill — a NACK would replay forever.
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
from collections import OrderedDict
from typing import Mapping, Optional

SOURCE_ID_LEN = 32          # hex chars (128 bits)
_SOURCE_ID_RE = re.compile(r"^[0-9a-f]{%d}$" % SOURCE_ID_LEN)

# one key set for both transports: gRPC metadata keys must be lowercase,
# and http.server's header mapping is case-insensitive, so the lowercase
# spelling works verbatim on either side of the wire
META_SOURCE_ID = "veneur-source-id"
META_EPOCH = "veneur-epoch"
META_SEQ = "veneur-seq"
_META_KEYS = (META_SOURCE_ID, META_EPOCH, META_SEQ)

# optional trace context (cross-tier flush tracing): the local tier's
# flush.forward span rides the envelope so the global tier's
# import/absorb spans parent onto it. Both-or-none: absent = legacy /
# untraced peer, exactly one present = corruption (same contract as the
# partial-envelope rule). Zero is not a valid id (tracer ids are
# `getrandbits(63) | 1`), so "zero keys" cannot masquerade as a trace.
META_TRACE_ID = "veneur-trace-id"
META_PARENT_SPAN_ID = "veneur-parent-span-id"
_TRACE_KEYS = (META_TRACE_ID, META_PARENT_SPAN_ID)

FRESH = "fresh"
DUPLICATE = "duplicate"
STALE = "stale"


class EnvelopeError(ValueError):
    """A malformed or unacceptable envelope: partial metadata, bad
    source_id, negative/non-integer epoch or seq, or a seq skip past the
    dedup window's bound. Receivers REJECT (4xx / INVALID_ARGUMENT) and
    account in veneur.forward.envelope_rejected_total — never fold."""


def mint_source_id() -> str:
    return os.urandom(SOURCE_ID_LEN // 2).hex()


@dataclasses.dataclass(frozen=True)
class Envelope:
    source_id: str
    epoch: int
    seq: int
    # cross-tier trace context; None/None = untraced (legacy-compatible)
    trace_id: Optional[int] = None
    parent_span_id: Optional[int] = None

    def validate(self) -> "Envelope":
        if not _SOURCE_ID_RE.match(self.source_id or ""):
            raise EnvelopeError(
                f"bad source_id {self.source_id!r}: want {SOURCE_ID_LEN} "
                "lowercase hex chars")
        if self.epoch < 0 or self.seq < 0:
            raise EnvelopeError(
                f"negative epoch/seq ({self.epoch}, {self.seq})")
        if (self.trace_id is None) != (self.parent_span_id is None):
            raise EnvelopeError(
                "partial trace context: trace_id and parent_span_id "
                "travel together")
        if self.trace_id is not None \
                and (self.trace_id <= 0 or self.parent_span_id <= 0):
            raise EnvelopeError(
                f"non-positive trace context ({self.trace_id}, "
                f"{self.parent_span_id})")
        return self

    # -- wire codecs --------------------------------------------------------
    def to_metadata(self) -> tuple:
        """gRPC invocation metadata / HTTP header pairs; trace-context
        keys ride only when present, so untraced senders stay
        byte-identical to pre-trace peers."""
        meta = ((META_SOURCE_ID, self.source_id),
                (META_EPOCH, str(self.epoch)),
                (META_SEQ, str(self.seq)))
        if self.trace_id is not None:
            meta += ((META_TRACE_ID, str(self.trace_id)),
                     (META_PARENT_SPAN_ID, str(self.parent_span_id)))
        return meta

    def to_json(self) -> dict:
        d = {"source_id": self.source_id, "epoch": self.epoch,
             "seq": self.seq}
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
            d["parent_span_id"] = self.parent_span_id
        return d

    @staticmethod
    def _parse_trace(get_trace, get_parent) -> tuple:
        """Shared trace-context parse for both codecs: both absent ->
        (None, None); exactly one present or non-integer -> reject."""
        tid_s, psid_s = get_trace, get_parent
        if tid_s is None and psid_s is None:
            return None, None
        if tid_s is None or psid_s is None:
            missing = (META_TRACE_ID if tid_s is None
                       else META_PARENT_SPAN_ID)
            raise EnvelopeError(
                f"partial trace context: missing {missing}")
        try:
            return int(tid_s), int(psid_s)
        except (TypeError, ValueError):
            raise EnvelopeError(
                f"non-integer trace context ({tid_s!r}, {psid_s!r})")

    @classmethod
    def from_mapping(cls, meta: Mapping) -> Optional["Envelope"]:
        """Parse from a metadata/header mapping (anything with .get —
        dict(grpc invocation_metadata) or an email.message.Message).
        Returns None when NO envelope keys are present (legacy sender);
        raises EnvelopeError when the envelope is partial or malformed —
        a half-present envelope is corruption, not a legacy peer. The
        trace-context pair follows the same rule independently: absent
        = untraced, half-present = rejected."""
        vals = [meta.get(k) for k in _META_KEYS]
        if all(v is None for v in vals):
            return None
        if any(v is None for v in vals):
            missing = [k for k, v in zip(_META_KEYS, vals) if v is None]
            raise EnvelopeError(f"partial envelope: missing {missing}")
        sid, epoch_s, seq_s = vals
        try:
            epoch, seq = int(epoch_s), int(seq_s)
        except (TypeError, ValueError):
            raise EnvelopeError(
                f"non-integer epoch/seq ({epoch_s!r}, {seq_s!r})")
        tid, psid = cls._parse_trace(meta.get(META_TRACE_ID),
                                     meta.get(META_PARENT_SPAN_ID))
        return cls(str(sid), epoch, seq, tid, psid).validate()

    @classmethod
    def from_json(cls, d: object) -> Optional["Envelope"]:
        """Parse the wrapped-JSON-body form ({"envelope": {...}})."""
        if d is None:
            return None
        if not isinstance(d, dict):
            raise EnvelopeError(f"envelope must be an object, got "
                                f"{type(d).__name__}")
        try:
            epoch, seq = int(d.get("epoch")), int(d.get("seq"))
        except (TypeError, ValueError):
            raise EnvelopeError("non-integer epoch/seq in JSON envelope")
        tid, psid = cls._parse_trace(d.get("trace_id"),
                                     d.get("parent_span_id"))
        return cls(str(d.get("source_id") or ""), epoch, seq,
                   tid, psid).validate()


class DedupWindow:
    """Bounded per-stream duplicate suppression: for each
    (source_id, epoch) a high-water mark plus a `window`-bit bitmap of
    recently seen seqs. Streams are LRU-bounded at `max_sources`; an
    evicted stream's re-appearance re-opens at its next seq (its old
    seqs would then read fresh — evictions are counted in
    veneur.dedup.window_evictions_total so the bound is observable).

    Thread-safe; the import paths call observe() from gRPC worker and
    HTTP handler threads concurrently."""

    def __init__(self, window: int, max_sources: int = 1024,
                 max_skip: Optional[int] = None):
        if window <= 0:
            raise ValueError("window must be > 0")
        self.window = int(window)
        self.max_sources = max(1, int(max_sources))
        # the acceptance bound on forward jumps: a hostile/corrupt seq
        # must not be able to slide the high-water mark arbitrarily far
        # (wiping the bitmap's memory of everything actually folded)
        self.max_skip = (int(max_skip) if max_skip is not None
                         else self.window * 64)
        self._lock = threading.Lock()
        # (source_id, epoch) -> [high_water, bitmap]; bit k of the
        # bitmap marks seq (high_water - k), k in [0, window)
        self._streams: "OrderedDict[tuple, list]" = OrderedDict()
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._streams)

    def _verdict_locked(self, env: Envelope, mark: bool) -> str:
        key = (env.source_id, env.epoch)
        st = self._streams.get(key)
        if st is None:
            if env.seq > self.max_skip:
                raise EnvelopeError(
                    f"seq {env.seq} opens a stream past max skip "
                    f"{self.max_skip}")
            if mark:
                while len(self._streams) >= self.max_sources:
                    self._streams.popitem(last=False)
                    self.evictions += 1
                self._streams[key] = [env.seq, 1]
            return FRESH
        self._streams.move_to_end(key)
        high, bits = st
        if env.seq > high:
            skip = env.seq - high
            if skip > self.max_skip:
                raise EnvelopeError(
                    f"seq {env.seq} skips {skip} past high-water {high} "
                    f"(max {self.max_skip})")
            if mark:
                st[0] = env.seq
                st[1] = ((bits << skip) | 1) & ((1 << self.window) - 1)
            return FRESH
        k = high - env.seq
        if k >= self.window:
            # below the window: indistinguishable from an already-folded
            # seq whose bit scrolled off — suppress conservatively (the
            # documented staleness bound: a replay older than `window`
            # seqs behind the stream head is dropped, never double-folded)
            return STALE
        if bits & (1 << k):
            return DUPLICATE
        if mark:
            st[1] = bits | (1 << k)
        return FRESH

    def observe(self, env: Envelope) -> str:
        """Check-and-mark: FRESH (and now marked), DUPLICATE, or STALE.
        Raises EnvelopeError on an over-bound seq skip."""
        with self._lock:
            return self._verdict_locked(env, mark=True)

    def peek(self, env: Envelope) -> str:
        """Check without marking (the proxy's two-phase use: mark only
        after every destination delivered)."""
        with self._lock:
            return self._verdict_locked(env, mark=False)

    def mark(self, env: Envelope) -> None:
        with self._lock:
            self._verdict_locked(env, mark=True)

    # -- checkpoint persistence (persistence/snapshot.py "forward") ---------
    def snapshot(self) -> dict:
        """JSON-able state, LRU order preserved (oldest first)."""
        with self._lock:
            return {"window": self.window,
                    "streams": [[sid, epoch, high, format(bits, "x")]
                                for (sid, epoch), (high, bits)
                                in self._streams.items()]}

    def restore(self, snap: dict) -> int:
        """Fold a snapshot()'s streams back in, re-masking bitmaps to
        THIS window's width (a restore into a smaller window keeps the
        newest seqs, the conservative end). Returns streams restored."""
        streams = (snap or {}).get("streams") or []
        n = 0
        with self._lock:
            for entry in streams:
                try:
                    sid, epoch, high, bits_hex = entry
                    high = int(high)
                    bits = int(str(bits_hex), 16)
                except (TypeError, ValueError):
                    continue   # one bad row must not void the rest
                while len(self._streams) >= self.max_sources:
                    self._streams.popitem(last=False)
                    self.evictions += 1
                self._streams[(str(sid), int(epoch))] = [
                    high, bits & ((1 << self.window) - 1)]
                n += 1
        return n
