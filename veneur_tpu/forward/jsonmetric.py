"""JSONMetric <-> metricpb.Metric conversion for the HTTP-era forward path.

The reference's v1 forwarding body is a JSON array of JSONMetric
(samplers/samplers.go:102-108): `{name, type, tagstring, tags, value}`
where `value` is base64 bytes of Go-native sampler state (flusher.go:338
flushForward builds it from each sampler's Export; worker.go:394
ImportMetric merges via Combine). The byte formats are implemented in
veneur_tpu/forward/gob.py (digests, scalars) and veneur_tpu/ops/hll.py
(axiomhq sets), so a mixed fleet of reference locals and this global —
or the reverse — interoperates over plain HTTP.

Internally both forward paths (gRPC and HTTP) speak metricpb.Metric;
this module converts at the HTTP boundary only.
"""

from __future__ import annotations

import base64
from typing import Dict, List

from veneur_tpu.forward import gob
from veneur_tpu.proto import metricpb_pb2 as mpb

_JSON_TYPE = {mpb.Counter: "counter", mpb.Gauge: "gauge",
              mpb.Histogram: "histogram", mpb.Set: "set",
              mpb.Timer: "timer"}
_TYPE_JSON = {v: k for k, v in _JSON_TYPE.items()}


def to_json_metrics(metrics: List[mpb.Metric]) -> List[Dict]:
    """metricpb.Metric list -> JSONMetric dicts (the reference local's
    flushForward wire shape, flusher.go:350-415)."""
    out = []
    for m in metrics:
        which = m.WhichOneof("value")
        if which == "counter":
            value = gob.encode_counter(m.counter.value)
        elif which == "gauge":
            value = gob.encode_gauge(m.gauge.value)
        elif which == "set":
            value = m.set.hyper_log_log   # already axiomhq MarshalBinary
        elif which == "histogram":
            td = m.histogram.t_digest
            value = gob.encode_digest(
                [c.mean for c in td.main_centroids],
                [c.weight for c in td.main_centroids],
                td.compression, td.min, td.max, td.reciprocalSum)
        else:
            continue
        out.append({
            "name": m.name,
            "type": _JSON_TYPE[m.type],
            "tagstring": ",".join(m.tags),
            "tags": list(m.tags),
            "value": base64.b64encode(bytes(value)).decode(),
        })
    return out


def from_json_metric(jm: Dict) -> mpb.Metric:
    """One JSONMetric dict -> metricpb.Metric (the global's HTTP import,
    handlers_global.go:115 + worker.go:394 Combine semantics). Raises
    ValueError/KeyError/gob.GobError on malformed input."""
    name = jm.get("name") or ""
    jtype = jm.get("type") or ""
    if not name or jtype not in _TYPE_JSON:
        raise ValueError(f"bad JSONMetric key: name={name!r} type={jtype!r}")
    tags = jm.get("tags") or []
    if not isinstance(tags, list):
        raise ValueError("JSONMetric tags must be a list")
    raw = base64.b64decode(jm.get("value") or "")

    m = mpb.Metric(name=name, tags=[str(t) for t in tags],
                   type=_TYPE_JSON[jtype], scope=mpb.Mixed)
    if jtype == "counter":
        m.counter.value = gob.decode_counter(raw)
    elif jtype == "gauge":
        m.gauge.value = gob.decode_gauge(raw)
    elif jtype == "set":
        m.set.hyper_log_log = raw   # validated downstream by hll.deserialize
    else:
        d = gob.decode_digest(raw)
        td = m.histogram.t_digest
        td.compression = d["compression"]
        td.min = d["min"]
        td.max = d["max"]
        td.reciprocalSum = d["recip"]
        for mean, wt in zip(d["means"], d["weights"]):
            td.main_centroids.add(mean=float(mean), weight=float(wt))
    return m
