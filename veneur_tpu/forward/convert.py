"""metricpb.Metric <-> aggregator state conversion.

Export mirrors reference worker.go:181 ForwardableMetrics + the samplers'
Metric() methods (samplers/samplers.go: Counter.Metric :171, Gauge.Metric
:266, Set.Metric :432, Histo.Metric :688): scope-global counters/gauges and
non-local histograms/timers/sets ship their mergeable sketch state. Import
mirrors importsrv/server.go:102 SendMetrics → worker.go:438
ImportMetricGRPC, including the scope coercion of counters/gauges to
GlobalOnly (:442-447).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from veneur_tpu.aggregation.host import (
    KeyTable, SCOPE_GLOBAL, SCOPE_LOCAL)
from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.proto import metricpb_pb2 as mpb
from veneur_tpu.utils.hashing import fnv1a_32

_KIND_TO_TYPE = {
    "counter": mpb.Counter, "gauge": mpb.Gauge, "histogram": mpb.Histogram,
    "set": mpb.Set, "timer": mpb.Timer,
}
_TYPE_TO_KIND = {v: k for k, v in _KIND_TO_TYPE.items()}
_TYPE_NAMES = {mpb.Counter: "counter", mpb.Gauge: "gauge",
               mpb.Histogram: "histogram", mpb.Set: "set",
               mpb.Timer: "timer"}


def metric_digest(name: str, pb_type: int, tags) -> int:
    """Sharding digest over name+type+tags, identical to the reference's
    importsrv hash (importsrv/server.go:141-148 hashMetric: fnv1a-32 over
    name, the capitalized enum name from Type.String(), then each tag).
    Inputs are deserialized protobuf strings — always valid UTF-8 (the
    export side replaces invalid bytes at the wire boundary, _wire_str),
    so a plain encode cannot raise."""
    h = fnv1a_32(name.encode())
    h = fnv1a_32(mpb.Type.Name(pb_type).encode(), h)
    for t in tags:
        h = fnv1a_32(t.encode(), h)
    return h


def _wire_str(s: str) -> str:
    """Name/tag strings entering metricpb protobuf STRING fields. A
    metric whose name arrived as invalid UTF-8 is held host-side with
    surrogates (key identity must round-trip); protobuf rejects
    surrogates, and ONE such global-scoped key would otherwise make
    export_metrics raise EVERY interval — permanently killing the whole
    forward stream for one corrupt datagram. Replace to U+FFFD at the
    wire boundary instead: only the corrupt key's name is mangled, the
    stream lives. (The Go reference has the harsher behavior: proto3
    marshal errors on invalid UTF-8, failing the whole batch.)"""
    try:
        s.encode()
        return s
    except UnicodeEncodeError:
        return s.encode("utf-8", "surrogateescape").decode("utf-8",
                                                           "replace")


def iter_forwardable(raw: Dict[str, np.ndarray], table: KeyTable,
                     hll_precision: int):
    """Yield (kind, meta, scope, payload) for every forward-eligible
    live row of a flush — the scope filters of worker.go:181
    ForwardableMetrics with payloads in the exact form
    Aggregator.import_metric receives after an export -> wire -> import
    round-trip (scope already coerced per worker.go:442-447). BOTH
    forward paths consume this one generator: export_metrics builds
    protobuf from it for the DCN/gRPC path, and the collective tier's
    absorb_raw feeds the payloads straight into device staging (zero
    serialization), so the two paths cannot drift.

    raw arrays are COMPACT: row i pairs with get_meta(kind)[i]
    (aggregator.compute_flush want_raw gathers live rows on device).

    One deviation: set payloads carry the losslessly unpacked dense
    registers, where the wire's axiomhq nibble form saturates register
    spreads > 15 (hll_ops.serialize tailcut) — identical whenever the
    spread fits, strictly more accurate otherwise."""
    for i, (_slot, meta) in enumerate(table.get_meta("counter")):
        if meta.scope != SCOPE_GLOBAL:
            continue  # only global counters forward (worker.go:186-193)
        yield ("counter", meta, SCOPE_GLOBAL,
               {"value": int(round(float(raw["counter"][i])))})

    for i, (_slot, meta) in enumerate(table.get_meta("gauge")):
        if meta.scope != SCOPE_GLOBAL:
            continue
        yield ("gauge", meta, SCOPE_GLOBAL,
               {"value": float(raw["gauge"][i])})

    for i, (_slot, meta) in enumerate(table.get_meta("set")):
        if meta.scope == SCOPE_LOCAL:
            continue  # local-only sets flush locally, never forward
        regs = hll_ops.unpack_registers_np(
            np.asarray(raw["hll"][i], np.int32), precision=hll_precision)
        yield ("set", meta,
               SCOPE_GLOBAL if meta.scope == SCOPE_GLOBAL else 0,
               {"registers": np.asarray(regs, np.uint8)})

    for i, (_slot, meta) in enumerate(table.get_meta("histogram")):
        if meta.scope == SCOPE_LOCAL:
            continue
        w = raw["h_weight"][i]
        live = w > 0
        if not live.any():
            continue
        kind = "timer" if meta.kind == "timer" else "histogram"
        yield (kind, meta,
               SCOPE_GLOBAL if meta.scope == SCOPE_GLOBAL else 0,
               {"means": raw["h_mean"][i][live], "weights": w[live],
                "min": float(raw["h_min"][i]),
                "max": float(raw["h_max"][i]),
                "recip": float(raw["h_recip"][i])})


def export_metrics(raw: Dict[str, np.ndarray], table: KeyTable,
                   compression: float, hll_precision: int
                   ) -> List[mpb.Metric]:
    """Build the forwardable MetricList from a flush's raw state."""
    out: List[mpb.Metric] = []
    for kind, meta, _scope, payload in iter_forwardable(raw, table,
                                                        hll_precision):
        name = _wire_str(meta.name)
        tags = [_wire_str(t) for t in meta.tags]
        pb_scope = (mpb.Global if meta.scope == SCOPE_GLOBAL
                    else mpb.Mixed)
        if kind == "counter":
            m = mpb.Metric(name=name, tags=tags, type=mpb.Counter,
                           scope=mpb.Global)
            m.counter.value = payload["value"]
        elif kind == "gauge":
            m = mpb.Metric(name=name, tags=tags, type=mpb.Gauge,
                           scope=mpb.Global)
            m.gauge.value = payload["value"]
        elif kind == "set":
            m = mpb.Metric(name=name, tags=tags, type=mpb.Set,
                           scope=pb_scope)
            # serialize unpacks packed rows itself, so dense registers
            # produce the identical wire bytes
            m.set.hyper_log_log = hll_ops.serialize(payload["registers"],
                                                    hll_precision)
        else:
            mtype = mpb.Timer if kind == "timer" else mpb.Histogram
            m = mpb.Metric(name=name, tags=tags, type=mtype,
                           scope=pb_scope)
            td = m.histogram.t_digest
            td.compression = compression
            td.min = payload["min"]
            td.max = payload["max"]
            td.reciprocalSum = payload["recip"]
            for mean, wt in zip(payload["means"], payload["weights"]):
                td.main_centroids.add(mean=float(mean), weight=float(wt))
        out.append(m)

    return out


def import_into(aggregator, metric: mpb.Metric) -> None:
    """Apply one received metricpb.Metric to a global aggregator
    (worker.go:438 ImportMetricGRPC)."""
    kind = _TYPE_NAMES[metric.type]
    tags = tuple(metric.tags)
    digest = metric_digest(metric.name, metric.type, tags)
    # counters/gauges arriving via import are global by definition
    # (worker.go:442-447 scope coercion)
    scope = SCOPE_GLOBAL if kind in ("counter", "gauge") else (
        SCOPE_GLOBAL if metric.scope == mpb.Global else 0)

    which = metric.WhichOneof("value")
    if which == "counter":
        payload = {"value": metric.counter.value}
    elif which == "gauge":
        payload = {"value": metric.gauge.value}
    elif which == "set":
        _, regs = hll_ops.deserialize(metric.set.hyper_log_log)
        payload = {"registers": regs}
    elif which == "histogram":
        td = metric.histogram.t_digest
        payload = {
            "means": [c.mean for c in td.main_centroids],
            "weights": [c.weight for c in td.main_centroids],
            "min": td.min, "max": td.max, "recip": td.reciprocalSum,
        }
    else:
        # the reference ERRORS on a nil value (worker.go:441
        # ImportMetricGRPC; worker_test.go:327) so the import server
        # counts it — a silent return would hide malformed peers
        raise ValueError(
            f"metric {metric.name!r} has no value field set")
    aggregator.import_metric(kind, metric.name, tags, scope, digest,
                             payload)
