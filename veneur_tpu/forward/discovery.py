"""Service discovery for the proxy tier.

reference discoverer.go:5 Discoverer interface + consul.go:29 (healthy
instances via /v1/health/service) + kubernetes.go:32 (pod list by label).
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import List

log = logging.getLogger("veneur_tpu.forward.discovery")


class StaticDiscoverer:
    """Fixed destination list (the reference's non-discovery config path)."""

    def __init__(self, destinations: List[str]):
        self.destinations = list(destinations)

    def get_destinations_for_service(self, service: str) -> List[str]:
        return list(self.destinations)


class ConsulDiscoverer:
    """Healthy-instance lookup (reference consul.go:29
    GetDestinationsForService: /v1/health/service/<name>?passing)."""

    def __init__(self, consul_url: str = "http://127.0.0.1:8500",
                 opener=None):
        self.consul_url = consul_url.rstrip("/")
        self._open = opener or urllib.request.urlopen

    def get_destinations_for_service(self, service: str) -> List[str]:
        url = f"{self.consul_url}/v1/health/service/{service}?passing"
        with self._open(url, timeout=10) as resp:
            entries = json.loads(resp.read())
        dests = []
        for e in entries:
            svc = e.get("Service", {})
            node = e.get("Node", {})
            host = svc.get("Address") or node.get("Address")
            port = svc.get("Port")
            if host and port:
                dests.append(f"{host}:{port}")
        return dests


class KubernetesDiscoverer:
    """Pod-list lookup (reference kubernetes.go:32: label
    app=veneur-global). Requires in-cluster credentials; reads the
    service-account token mounted by k8s."""

    def __init__(self, namespace: str = "default",
                 label_selector: str = "app=veneur-global",
                 api_base: str = "https://kubernetes.default.svc"):
        self.namespace = namespace
        self.label_selector = label_selector
        self.api_base = api_base

    def get_destinations_for_service(self, service: str) -> List[str]:
        import ssl
        token_path = "/var/run/secrets/kubernetes.io/serviceaccount/token"
        try:
            with open(token_path) as f:
                token = f.read()
        except OSError:
            log.warning("not running in-cluster; k8s discovery unavailable")
            return []
        url = (f"{self.api_base}/api/v1/namespaces/{self.namespace}/pods"
               f"?labelSelector={self.label_selector}")
        req = urllib.request.Request(
            url, headers={"Authorization": f"Bearer {token}"})
        ctx = ssl.create_default_context(
            cafile="/var/run/secrets/kubernetes.io/serviceaccount/ca.crt")
        with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
            pods = json.loads(resp.read())
        dests = []
        for pod in pods.get("items", []):
            ip = pod.get("status", {}).get("podIP")
            if ip and pod.get("status", {}).get("phase") == "Running":
                dests.append(f"{ip}:8128")
        return dests
