"""Service discovery for the proxy tier.

reference discoverer.go:5 Discoverer interface + consul.go:29 (healthy
instances via /v1/health/service) + kubernetes.go:32 (pod list by label).

Fail-static: a transient discovery failure (Consul restart, apiserver
blip, DNS hiccup) serves the LAST KNOWN GOOD destination set instead of
an empty list. Fail-empty at the proxy means every refresh outage
becomes a full traffic outage; stale-but-routable destinations degrade
to individual connection errors, which the per-destination breakers
already contain. Staleness is visible: each discoverer exposes
`stale` (0/1), surfaced as the `veneur.discovery.stale` gauge by the
proxy's registry.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import List

log = logging.getLogger("veneur_tpu.forward.discovery")


class StaticDiscoverer:
    """Fixed destination list (the reference's non-discovery config path)."""

    def __init__(self, destinations: List[str]):
        self.destinations = list(destinations)
        self.stale = 0  # a static list is never stale

    def get_destinations_for_service(self, service: str) -> List[str]:
        return list(self.destinations)


class _FailStatic:
    """Last-known-good fallback shared by the network discoverers."""

    def __init__(self):
        self.last_good: List[str] = []
        self.stale = 0

    def _fetched(self, dests: List[str]) -> List[str]:
        self.last_good = list(dests)
        self.stale = 0
        return dests

    def _failed(self, service: str, err: Exception) -> List[str]:
        if self.last_good:
            self.stale = 1
            log.warning(
                "discovery for %r failed (%s); serving %d last-known-good "
                "destinations", service, err, len(self.last_good))
            return list(self.last_good)
        # nothing to fall back to: propagate so the caller's own
        # keep-last-ring logic (proxysrv.refresh) can decide
        raise err


class ConsulDiscoverer(_FailStatic):
    """Healthy-instance lookup (reference consul.go:29
    GetDestinationsForService: /v1/health/service/<name>?passing)."""

    def __init__(self, consul_url: str = "http://127.0.0.1:8500",
                 opener=None):
        super().__init__()
        self.consul_url = consul_url.rstrip("/")
        self._open = opener or urllib.request.urlopen

    def get_destinations_for_service(self, service: str) -> List[str]:
        url = f"{self.consul_url}/v1/health/service/{service}?passing"
        try:
            with self._open(url, timeout=10) as resp:
                entries = json.loads(resp.read())
            dests = []
            for e in entries:
                svc = e.get("Service", {})
                node = e.get("Node", {})
                host = svc.get("Address") or node.get("Address")
                port = svc.get("Port")
                if host and port:
                    dests.append(f"{host}:{port}")
        except Exception as e:
            return self._failed(service, e)
        return self._fetched(dests)


class KubernetesDiscoverer(_FailStatic):
    """Pod-list lookup (reference kubernetes.go:32: label
    app=veneur-global). Requires in-cluster credentials; reads the
    service-account token mounted by k8s."""

    def __init__(self, namespace: str = "default",
                 label_selector: str = "app=veneur-global",
                 api_base: str = "https://kubernetes.default.svc",
                 opener=None):
        super().__init__()
        self.namespace = namespace
        self.label_selector = label_selector
        self.api_base = api_base
        self._open = opener or urllib.request.urlopen

    def get_destinations_for_service(self, service: str) -> List[str]:
        import ssl
        token_path = "/var/run/secrets/kubernetes.io/serviceaccount/token"
        try:
            with open(token_path) as f:
                token = f.read()
        except OSError as e:
            # no in-cluster credentials is a config condition, not a
            # transient failure — but last-known-good still beats empty
            # (e.g. a token briefly unreadable during rotation)
            log.warning("not running in-cluster; k8s discovery unavailable")
            if self.last_good:
                return self._failed(service, e)
            return []
        url = (f"{self.api_base}/api/v1/namespaces/{self.namespace}/pods"
               f"?labelSelector={self.label_selector}")
        req = urllib.request.Request(
            url, headers={"Authorization": f"Bearer {token}"})
        try:
            ctx = ssl.create_default_context(
                cafile="/var/run/secrets/kubernetes.io/"
                       "serviceaccount/ca.crt")
            with self._open(req, timeout=10, context=ctx) as resp:
                pods = json.loads(resp.read())
            dests = []
            for pod in pods.get("items", []):
                ip = pod.get("status", {}).get("podIP")
                if ip and pod.get("status", {}).get("phase") == "Running":
                    dests.append(f"{ip}:8128")
        except Exception as e:
            return self._failed(service, e)
        return self._fetched(dests)
