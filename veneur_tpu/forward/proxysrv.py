"""The consistent-hash routing tier: veneur-proxy.

reference proxysrv/server.go: a Forward gRPC server that consistent-hashes
each metric's key to one global destination and forwards per-destination
batches; the ring refreshes from discovery on an interval (proxy.go:321-347)
and keeps the last good set when discovery returns empty (proxy.go:498-508);
connections are cached per destination (client_conn_map.go).
"""

from __future__ import annotations

import bisect
import logging
import threading
from typing import Dict, List, Optional

from veneur_tpu.forward.rpc import ForwardClient, serve
from veneur_tpu.utils.hashing import fnv1a_64, splitmix64


def _point(data: bytes) -> int:
    """Ring placement hash: fnv1a-64 finalized through splitmix64 — raw fnv
    clusters badly on short, similar strings (node#i)."""
    return splitmix64(fnv1a_64(data))

log = logging.getLogger("veneur_tpu.forward.proxysrv")


class HashRing:
    """Consistent-hash ring with virtual nodes (the role of the reference's
    stathat.com/c/consistent ring, proxy.go:603; our node hash is fnv1a-64
    — routing placement is an internal choice, not a wire format)."""

    def __init__(self, destinations: List[str], replicas: int = 128):
        self.replicas = replicas
        self.destinations = sorted(set(destinations))
        self._points: List[int] = []
        self._owners: List[str] = []
        for dest in self.destinations:
            for i in range(replicas):
                h = _point(f"{dest}#{i}".encode())
                self._points.append(h)
                self._owners.append(dest)
        order = sorted(range(len(self._points)),
                       key=lambda i: self._points[i])
        self._points = [self._points[i] for i in order]
        self._owners = [self._owners[i] for i in order]

    def get(self, key: bytes) -> Optional[str]:
        if not self._points:
            return None
        h = _point(key)
        i = bisect.bisect(self._points, h) % len(self._points)
        return self._owners[i]


class ProxyServer:
    """Forward-service server that re-forwards by MetricKey hash
    (proxysrv/server.go:273 destForMetric keyed on MetricKey.String())."""

    def __init__(self, discoverer, service: str = "veneur-global",
                 refresh_interval: float = 0.0, replicas: int = 128):
        self.discoverer = discoverer
        self.service = service
        self.refresh_interval = refresh_interval
        self.replicas = replicas
        self._ring = HashRing([], replicas)
        self._conns: Dict[str, ForwardClient] = {}
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._grpc = None
        self.port = None
        self.forwarded = 0
        self.errors = 0
        self.refresh()

    # -- ring maintenance ---------------------------------------------------
    def refresh(self):
        """proxy.go:321 RefreshDestinations, incl. keep-last-good-on-empty
        (proxy.go:498-508) and connection cache pruning
        (proxysrv/server.go:148-176)."""
        try:
            dests = self.discoverer.get_destinations_for_service(self.service)
        except Exception as e:
            log.warning("discovery failed: %s", e)
            return
        if not dests:
            log.warning("discovery returned no hosts; keeping last ring")
            return
        with self._lock:
            self._ring = HashRing(dests, self.replicas)
            for dest in list(self._conns):
                if dest not in self._ring.destinations:
                    self._conns.pop(dest).close()

    def _conn(self, dest: str) -> ForwardClient:
        with self._lock:
            if dest not in self._conns:
                self._conns[dest] = ForwardClient(dest)
            return self._conns[dest]

    # -- forwarding ---------------------------------------------------------
    def handle(self, metrics: List):
        """Group by ring destination, then one SendMetrics per destination
        (proxysrv/server.go:180-188, :286)."""
        by_dest: Dict[str, List] = {}
        with self._lock:
            ring = self._ring  # immutable once built; snapshot suffices
        for m in metrics:
            key = f"{m.name}{m.type}{','.join(m.tags)}".encode()
            dest = ring.get(key)
            if dest is None:
                self.errors += 1
                continue
            by_dest.setdefault(dest, []).append(m)
        for dest, batch in by_dest.items():
            try:
                self._conn(dest).send_metrics(batch)
                self.forwarded += len(batch)
            except Exception as e:
                self.errors += len(batch)
                log.warning("proxy forward to %s failed: %s", dest, e)

    # -- lifecycle ----------------------------------------------------------
    def start(self, address: str = "127.0.0.1:0"):
        self._grpc, self.port = serve(self.handle, address)
        if self.refresh_interval > 0:
            t = threading.Thread(target=self._refresh_loop, daemon=True)
            t.start()

    def _refresh_loop(self):
        while not self._shutdown.wait(self.refresh_interval):
            self.refresh()

    def stop(self):
        self._shutdown.set()
        if self._grpc is not None:
            self._grpc.stop(grace=1.0)
        with self._lock:
            for c in self._conns.values():
                c.close()
            self._conns.clear()
