"""The consistent-hash routing tier: veneur-proxy.

reference proxysrv/server.go: a Forward gRPC server that consistent-hashes
each metric's key to one global destination and forwards per-destination
batches; the ring refreshes from discovery on an interval (proxy.go:321-347)
and keeps the last good set when discovery returns empty (proxy.go:498-508);
connections are cached per destination (client_conn_map.go).
"""

from __future__ import annotations

import bisect
import logging
import socket
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from veneur_tpu.forward.envelope import (FRESH, DedupWindow, Envelope,
                                         EnvelopeError)
from veneur_tpu.forward.rpc import ForwardClient, serve
from veneur_tpu.observability.registry import TelemetryRegistry
from veneur_tpu.reliability.faults import FAULTS, PROXY_FORWARD
from veneur_tpu.reliability.policy import OPEN, CircuitBreaker
from veneur_tpu.utils.hashing import fnv1a_64, splitmix64


def _point(data: bytes) -> int:
    """Ring placement hash: fnv1a-64 finalized through splitmix64 — raw fnv
    clusters badly on short, similar strings (node#i)."""
    return splitmix64(fnv1a_64(data))

log = logging.getLogger("veneur_tpu.forward.proxysrv")


class HashRing:
    """Consistent-hash ring with virtual nodes (the role of the reference's
    stathat.com/c/consistent ring, proxy.go:603; our node hash is fnv1a-64
    — routing placement is an internal choice, not a wire format)."""

    def __init__(self, destinations: List[str], replicas: int = 128):
        self.replicas = replicas
        self.destinations = sorted(set(destinations))
        self._points: List[int] = []
        self._owners: List[str] = []
        for dest in self.destinations:
            for i in range(replicas):
                h = _point(f"{dest}#{i}".encode())
                self._points.append(h)
                self._owners.append(dest)
        order = sorted(range(len(self._points)),
                       key=lambda i: self._points[i])
        self._points = [self._points[i] for i in order]
        self._owners = [self._owners[i] for i in order]

    def get(self, key: bytes) -> Optional[str]:
        if not self._points:
            return None
        h = _point(key)
        i = bisect.bisect(self._points, h) % len(self._points)
        return self._owners[i]


class ProxyServer:
    """Forward-service server that re-forwards by MetricKey hash
    (proxysrv/server.go:273 destForMetric keyed on MetricKey.String())."""

    def __init__(self, discoverer, service: str = "veneur-global",
                 refresh_interval: float = 0.0, replicas: int = 128,
                 failure_threshold: int = 0, cooldown_s: float = 30.0,
                 readyz_port: int = 0, readyz_opener=None,
                 dedup_window: int = 0):
        self.discoverer = discoverer
        self.service = service
        self.refresh_interval = refresh_interval
        self.replicas = replicas
        # per-destination breakers (failure_threshold=0 disables): a dead
        # global otherwise eats a full send timeout per batch per interval
        # while its ring partition backs up behind it
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._breakers: Dict[str, CircuitBreaker] = {}
        self.rejected_open = 0
        # exactly-once relay (dedup_window > 0): the proxy is NOT a dedup
        # endpoint — it passes the sender's envelope through to each
        # destination — but it must survive its OWN retry hazard: a ring
        # change between a partial failure and the sender's retry would
        # re-route already-delivered keys to a different global, which
        # would fold them as fresh. So the first attempt at a
        # (source_id, epoch, seq) STORES its per-destination grouping,
        # retries re-attempt only the still-undelivered sub-batches, and
        # _done marks the seq only once every destination has it.
        self._done = (DedupWindow(dedup_window) if dedup_window > 0
                      else None)
        self._inflight: "OrderedDict[tuple, dict]" = OrderedDict()
        self._inflight_cap = 4096
        self._inflight_lock = threading.Lock()
        # plain ints, emitted under the lint-exempt veneur_proxy.*
        # statsd namespace (emit_stats_once) — the veneur.* spellings
        # belong to the server's registry
        self.dup_suppressed = 0
        self.envelope_rejected = 0
        self._ring = HashRing([], replicas)
        # overload-aware routing: peers answering /readyz non-200 (the
        # server's overload state machine) and OPEN-breaker destinations
        # are ejected from a derived routing ring so their keyspace
        # rehashes to survivors instead of queueing behind a sick peer.
        # readyz_port=0 disables probing (destinations' gRPC port is not
        # their HTTP port, so it must be configured explicitly).
        self.readyz_port = readyz_port
        self._readyz_open = readyz_opener  # injectable for tests
        self._not_ready: frozenset = frozenset()
        self._routing_cache = None  # ((id(base), excluded), derived ring)
        # registry: the proxy's own veneur.* instruments (the statsd
        # emitter's veneur_proxy.* lines are a separate, lint-exempt
        # namespace)
        self.metrics = TelemetryRegistry()
        self.metrics.callback(
            "veneur.discovery.stale",
            lambda: float(getattr(self.discoverer, "stale", 0) or 0),
            kind="gauge",
            help="1 while discovery serves last-known-good destinations")
        self._conns: Dict[str, ForwardClient] = {}
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._grpc = None
        self._http = None
        self.port = None
        self.http_port = None
        self.forwarded = 0
        self.errors = 0
        # counter lock: handle()/_deliver_enveloped() run on gRPC/HTTP
        # worker threads concurrently with each other and with the stats
        # emitter; bare `+=` on these ints loses increments. _bump() is
        # the single mutation path. Narrower than self._lock so counter
        # bumps never contend with ring rebuilds or connection setup.
        self._stats_lock = threading.Lock()
        # per-(destination, protocol) forwarded-metric counts — the
        # reference's metrics_by_destination self-metric
        # (proxysrv/server.go:299-301 grpc, proxy.go:651-653 http). The
        # reference samples these at 10%; exact counts are strictly
        # better and cost one dict add per batch.
        self.metrics_by_destination: Dict[tuple, int] = {}
        self._stats_thread = None
        self._stats_sock = None
        self._stats_last: Dict[tuple, int] = {}
        # ring rebuilds actually performed by refresh() — membership
        # changes only, not polls (the regression guard for the
        # rebuild-every-poll bug: a stable fleet must not churn the ring
        # object, which would also invalidate the derived routing-ring
        # cache keyed by id(base))
        self.ring_rebuilds = 0
        self.refresh()

    # -- ring maintenance ---------------------------------------------------
    def refresh(self):
        """proxy.go:321 RefreshDestinations, incl. keep-last-good-on-empty
        (proxy.go:498-508) and connection cache pruning
        (proxysrv/server.go:148-176)."""
        try:
            dests = self.discoverer.get_destinations_for_service(self.service)
        except Exception as e:
            log.warning("discovery failed: %s", e)
            self._probe_ready()
            return
        if not dests:
            log.warning("discovery returned no hosts; keeping last ring")
            self._probe_ready()
            return
        with self._lock:
            # rebuild only on a membership change: HashRing stores
            # sorted(set(...)), so comparing against that canonical form
            # is the membership signature. A stable fleet keeps the SAME
            # ring object across polls — which also keeps the derived
            # routing-ring cache (keyed by id(base)) warm.
            if sorted(set(dests)) != list(self._ring.destinations):
                self._ring = HashRing(dests, self.replicas)
                self.ring_rebuilds += 1
                for dest in list(self._conns):
                    if dest not in self._ring.destinations:
                        self._conns.pop(dest).close()
                for dest in list(self._breakers):
                    if dest not in self._ring.destinations:
                        del self._breakers[dest]
        self._probe_ready()

    def _probe_ready(self) -> None:
        """Consult each destination's GET /readyz (server/health.py) and
        record the non-ready set for _routing_ring. Fail-open per peer: a
        probe that errors (connection refused, no HTTP listener) admits
        the destination — actually-dead peers are the breakers' job, and
        a proxy must not de-route its whole ring because probing broke."""
        if self.readyz_port <= 0:
            return
        import urllib.request
        opener = self._readyz_open or urllib.request.urlopen
        with self._lock:
            dests = list(self._ring.destinations)
        not_ready = set()
        for dest in dests:
            host = dest.rsplit(":", 1)[0]
            url = f"http://{host}:{self.readyz_port}/readyz"
            try:
                with opener(url, timeout=2) as resp:
                    code = getattr(resp, "status", None) or resp.getcode()
                if code != 200:
                    not_ready.add(dest)
            except Exception as e:
                log.debug("readyz probe of %s failed (admitting): %s",
                          dest, e)
        if not_ready != self._not_ready:
            log.info("readyz: not-ready destinations now %s",
                     sorted(not_ready) or "(none)")
        self._not_ready = frozenset(not_ready)

    def _routing_ring(self) -> HashRing:
        """The ring handle()/handle_json route over: the discovery ring
        minus OPEN-breaker and not-ready destinations, rebuilt (and
        cached) only when that exclusion set changes so the hot path
        normally costs two dict scans. A breaker whose cooldown elapsed
        reads HALF_OPEN, so its destination re-enters here and the
        per-batch allow() gate claims the single probe — success closes
        the breaker and the destination stays admitted. Fail-static:
        with every destination excluded, route over the full ring."""
        with self._lock:
            base = self._ring
            excluded = set(self._not_ready)
            for dest, b in self._breakers.items():
                if b.state == OPEN:
                    excluded.add(dest)
            excluded &= set(base.destinations)
            if not excluded or len(excluded) == len(base.destinations):
                return base
            key = (id(base), frozenset(excluded))
            cached = self._routing_cache
            if cached is not None and cached[0] == key:
                return cached[1]
            ring = HashRing(
                [d for d in base.destinations if d not in excluded],
                self.replicas)
            self._routing_cache = (key, ring)
            return ring

    def _conn(self, dest: str) -> ForwardClient:
        with self._lock:
            if dest not in self._conns:
                self._conns[dest] = ForwardClient(dest)
            return self._conns[dest]

    def _breaker(self, dest: str) -> Optional[CircuitBreaker]:
        if self.failure_threshold <= 0:
            return None
        with self._lock:
            if dest not in self._breakers:
                self._breakers[dest] = CircuitBreaker(
                    self.failure_threshold, self.cooldown_s)
            return self._breakers[dest]

    # -- forwarding ---------------------------------------------------------
    def handle(self, metrics: List, envelope: Envelope = None):
        """Group by ring destination, then one SendMetrics per destination
        (proxysrv/server.go:180-188, :286). With an envelope (exactly-once
        sender, dedup_window > 0) delivery is all-or-error: partial
        failure raises so the sender retries the SAME seq, and the retry
        re-attempts only the stored undelivered sub-batches."""
        if envelope is not None and self._done is not None:
            return self._deliver_enveloped(
                metrics, envelope, "grpc",
                lambda m: f"{m.name}{m.type}{','.join(m.tags)}".encode(),
                lambda dest, batch: self._conn(dest).send_metrics(
                    batch, envelope=envelope))
        by_dest: Dict[str, List] = {}
        ring = self._routing_ring()  # rings are immutable once built
        for m in metrics:
            key = f"{m.name}{m.type}{','.join(m.tags)}".encode()
            dest = ring.get(key)
            if dest is None:
                self._bump("errors")
                continue
            by_dest.setdefault(dest, []).append(m)
        for dest, batch in by_dest.items():
            breaker = self._breaker(dest)
            if breaker is not None and not breaker.allow():
                self._bump("errors", len(batch))
                self._bump("rejected_open", len(batch))
                continue
            try:
                FAULTS.inject(PROXY_FORWARD, name=dest)
                self._conn(dest).send_metrics(batch)
                self._bump("forwarded", len(batch))
                self._count_dest(dest, "grpc", len(batch))
                if breaker is not None:
                    breaker.record_success()
            except Exception as e:
                self._bump("errors", len(batch))
                if breaker is not None:
                    breaker.record_failure()
                log.warning("proxy forward to %s failed: %s", dest, e)

    def _deliver_enveloped(self, items: List, envelope: Envelope,
                           protocol: str, keyfn, sendfn) -> bool:
        """Exactly-once relay of one (source_id, epoch, seq) unit: peek
        the done-window (suppressed units were already fully delivered —
        ack without re-sending), pin the per-destination grouping on
        first attempt, deliver undelivered sub-batches with the SENDER'S
        envelope attached (each destination's own dedup window absorbs
        ambiguous re-sends), and mark done only when none remain."""
        try:
            verdict = self._done.peek(envelope)
        except EnvelopeError:
            self._bump("envelope_rejected")
            raise
        if verdict != FRESH:
            self._bump("dup_suppressed")
            return True
        key = (protocol, envelope.source_id, envelope.epoch, envelope.seq)
        # _routing_ring acquires self._lock internally: call it before
        # taking any proxy lock of our own
        ring = self._routing_ring()
        with self._inflight_lock:
            stored = self._inflight.get(key)
            if stored is None:
                stored = {}
                for it in items:
                    dest = ring.get(keyfn(it))
                    if dest is None:
                        self._bump("errors")
                        continue
                    stored.setdefault(dest, []).append(it)
                self._inflight[key] = stored
                while len(self._inflight) > self._inflight_cap:
                    # dropping a pinned grouping degrades that unit's
                    # retry to re-hash-on-current-ring; bounded memory
                    # wins over a pathological backlog of dead seqs
                    self._inflight.popitem(last=False)
            pending = list(stored.items())
        failed = 0
        for dest, batch in pending:
            breaker = self._breaker(dest)
            if breaker is not None and not breaker.allow():
                self._bump("errors", len(batch))
                self._bump("rejected_open", len(batch))
                failed += 1
                continue
            try:
                FAULTS.inject(PROXY_FORWARD, name=dest)
                sendfn(dest, batch)
                self._bump("forwarded", len(batch))
                self._count_dest(dest, protocol, len(batch))
                if breaker is not None:
                    breaker.record_success()
                with self._inflight_lock:
                    stored.pop(dest, None)
            except Exception as e:
                failed += 1
                self._bump("errors", len(batch))
                if breaker is not None:
                    breaker.record_failure()
                log.warning("proxy forward to %s failed: %s", dest, e)
        if failed:
            raise RuntimeError(
                f"delivered {len(pending) - failed}/{len(pending)} "
                f"destinations for seq {envelope.seq}; sender must "
                "retry the same seq")
        self._done.mark(envelope)
        with self._inflight_lock:
            self._inflight.pop(key, None)
        return True

    def _bump(self, attr: str, n: int = 1) -> None:
        """Increment one of the plain-int stat counters under
        _stats_lock — `self._bump("errors")` from two worker threads is a
        read-modify-write that loses increments."""
        with self._stats_lock:
            setattr(self, attr, getattr(self, attr) + n)

    def _count_dest(self, dest: str, protocol: str, n: int) -> None:
        with self._lock:
            key = (dest, protocol)
            self.metrics_by_destination[key] = \
                self.metrics_by_destination.get(key, 0) + n

    # -- HTTP-era (v1) routing ----------------------------------------------
    def handle_json(self, json_metrics: List[dict]) -> Dict[str, List[dict]]:
        """Split a JSONMetric array by MetricKey over the ring
        (proxy.go:580 ProxyMetrics: key = Name+Type+JoinedTags). Returns
        the per-destination batches; callers POST each to <dest>/import."""
        by_dest: Dict[str, List[dict]] = {}
        ring = self._routing_ring()
        for jm in json_metrics:
            key = (f"{jm.get('name', '')}{jm.get('type', '')}"
                   f"{jm.get('tagstring', '')}").encode()
            dest = ring.get(key)
            if dest is None:
                self._bump("errors")
                continue
            by_dest.setdefault(dest, []).append(jm)
        return by_dest

    def _post_import(self, dest: str, batch: List[dict],
                     envelope: Envelope = None) -> None:
        """POST one batch to <dest>/import as deflate-compressed JSON
        (the reference's vhttp.PostHelper with compress=true,
        proxy.go:622 doPost). HTTPForwardClient owns scheme handling."""
        from veneur_tpu.forward.rpc import HTTPForwardClient
        HTTPForwardClient(dest).send_json(batch, envelope=envelope)

    def proxy_json_metrics(self, json_metrics: List[dict],
                           envelope: Envelope = None) -> None:
        """ProxyMetrics (proxy.go:580): hash-split, then one POST per
        destination, counting errors per batch like the gRPC path.
        With an envelope, the all-or-error exactly-once relay applies
        (see _deliver_enveloped)."""
        if envelope is not None and self._done is not None:
            self._deliver_enveloped(
                json_metrics, envelope, "http",
                lambda jm: (f"{jm.get('name', '')}{jm.get('type', '')}"
                            f"{jm.get('tagstring', '')}").encode(),
                lambda dest, batch: self._post_import(
                    dest, batch, envelope=envelope))
            return
        for dest, batch in self.handle_json(json_metrics).items():
            breaker = self._breaker(dest)
            if breaker is not None and not breaker.allow():
                self._bump("errors", len(batch))
                self._bump("rejected_open", len(batch))
                continue
            try:
                FAULTS.inject(PROXY_FORWARD, name=dest)
                self._post_import(dest, batch)
                self._bump("forwarded", len(batch))
                self._count_dest(dest, "http", len(batch))
                if breaker is not None:
                    breaker.record_success()
            except Exception as e:
                self._bump("errors", len(batch))
                if breaker is not None:
                    breaker.record_failure()
                log.warning("proxy POST to %s failed: %s", dest, e)

    def start_http(self, address: str = "127.0.0.1:0") -> int:
        """The v1 proxy surface (proxy.go:518 mux): POST /import routes a
        JSONMetric array across the ring; GET /healthcheck. Returns the
        bound port. The 202 is sent BEFORE forwarding, matching the
        reference ("the response has already been returned at this
        point", proxy.go:607)."""
        import http.server
        import json as _json
        import zlib
        srv = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code, body=b""):
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthcheck":
                    self._reply(200, b"ok")
                else:
                    self._reply(404)

            def do_POST(self):
                if self.path != "/import":
                    self._reply(404)
                    return
                body = self.rfile.read(
                    int(self.headers.get("Content-Length", "0")))
                if self.headers.get("Content-Encoding", "") == "deflate":
                    try:
                        body = zlib.decompress(body)
                    except zlib.error:
                        self._reply(400, b"bad deflate body")
                        return
                try:
                    jms = _json.loads(body)
                except ValueError:
                    self._reply(400, b"bad JSON body")
                    return
                body_env = None
                if isinstance(jms, dict):
                    # exactly-once wrapped form: {"envelope": ...,
                    # "metrics": [...]} (forward/rpc.py send_metrics)
                    body_env = jms.get("envelope")
                    jms = jms.get("metrics")
                if not isinstance(jms, list) or not all(
                        isinstance(jm, dict) for jm in jms):
                    self._reply(400, b"bad JSONMetric array")
                    return
                envelope = None
                if srv._done is not None:
                    try:
                        envelope = (Envelope.from_json(body_env)
                                    if body_env is not None else
                                    Envelope.from_mapping(self.headers))
                    except EnvelopeError:
                        srv.envelope_rejected += 1
                        self._reply(400, b"bad envelope")
                        return
                if envelope is not None:
                    # the 202 IS the ack: send it only once every
                    # destination has the batch, else the sender evicts
                    # a unit the ring never fully delivered
                    try:
                        srv.proxy_json_metrics(jms, envelope=envelope)
                    except EnvelopeError:
                        self._reply(400, b"bad envelope")
                        return
                    except Exception:
                        self._reply(503, b"partial delivery; retry")
                        return
                    self._reply(202, b"accepted")
                    return
                # an empty array is a valid no-op, not an error
                self._reply(202, b"accepted")
                if jms:
                    srv.proxy_json_metrics(jms)

        # accept the same spellings the server's http_address does:
        # optional tcp:// (or http://) scheme and bracketed IPv6 literals
        if "://" in address:
            address = address.partition("://")[2]
        if address.startswith("["):
            host, _, rest = address[1:].partition("]")
            port = rest.lstrip(":")
        else:
            host, _, port = address.rpartition(":")
            if not host:
                host, port = port, ""

        class _Server(http.server.ThreadingHTTPServer):
            address_family = (socket.AF_INET6 if ":" in host
                              else socket.AF_INET)

        httpd = _Server((host, int(port or 0)), Handler)
        self._http = httpd
        self.http_port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return self.http_port

    # -- self-telemetry -----------------------------------------------------
    def runtime_metrics(self) -> List[tuple]:
        """Process runtime gauges, the role of proxy.go:656
        ReportRuntimeMetrics. The Go fields map to their CPython
        equivalents: HeapAlloc -> current resident set size (the
        live-memory measure a CPython process has), NumGC -> total
        collections across gc generations. Go's PauseTotalNs has no
        CPython counterpart (collections are not stop-the-world-timed)
        and is deliberately not faked; gc.alloc_heap_bytes mirrors
        mem.heap_alloc_bytes exactly as the reference emits HeapAlloc
        under both names. Returns (name, value, type_char) tuples."""
        from veneur_tpu.utils.statsd_emit import runtime_gauges
        rss, ngc = runtime_gauges()
        return [("mem.heap_alloc_bytes", rss, "g"),
                ("gc.number", ngc, "g"),
                ("gc.alloc_heap_bytes", rss, "g")]

    def start_stats(self, stats_address: str, interval: float = 10.0):
        """Emit veneur_proxy.-namespaced self-metrics to a statsd daemon
        on a ticker (proxy.go:213-217 statsd.New + Namespace, :354-365
        runtime ticker): runtime gauges each tick, plus
        metrics_by_destination / forward.error_total deltas."""
        from veneur_tpu.utils.statsd_emit import parse_addr
        self._stats_dest = parse_addr(stats_address)
        self._stats_sock = socket.socket(socket.AF_INET,
                                         socket.SOCK_DGRAM)
        self._stats_interval = interval
        self._stats_thread = threading.Thread(target=self._stats_loop,
                                              daemon=True)
        self._stats_thread.start()

    def _stats_loop(self):
        while not self._shutdown.wait(self._stats_interval):
            try:
                self.emit_stats_once()
            except OSError as e:
                log.warning("proxy stats emit failed: %s", e)

    def emit_stats_once(self):
        from veneur_tpu.utils.statsd_emit import format_line, send_lines
        lines = [format_line("veneur_proxy." + n, v, t)
                 for n, v, t in self.runtime_metrics()]
        with self._lock:
            counts = dict(self.metrics_by_destination)
        with self._stats_lock:
            counts[("", "error")] = self.errors
            counts[("", "dup")] = self.dup_suppressed
            counts[("", "rej")] = self.envelope_rejected
        for key, total in counts.items():
            delta = total - self._stats_last.get(key, 0)
            self._stats_last[key] = total
            if delta <= 0:
                continue
            dest, proto = key
            if proto == "error":
                lines.append(format_line(
                    "veneur_proxy.forward.error_total", delta, "c"))
            elif proto == "dup":
                lines.append(format_line(
                    "veneur_proxy.forward.dup_suppressed_total",
                    delta, "c"))
            elif proto == "rej":
                lines.append(format_line(
                    "veneur_proxy.forward.envelope_rejected_total",
                    delta, "c"))
            else:
                lines.append(format_line(
                    "veneur_proxy.metrics_by_destination", delta, "c",
                    tags=f"destination:{dest},protocol:{proto}"))
        send_lines(self._stats_sock, self._stats_dest, lines)

    # -- lifecycle ----------------------------------------------------------
    def start(self, address: str = "127.0.0.1:0"):
        def _count_reject():
            self._bump("envelope_rejected")
        self._grpc, self.port = serve(
            self.handle, address, with_metadata=self._done is not None,
            on_reject=_count_reject)
        if self.refresh_interval > 0:
            t = threading.Thread(target=self._refresh_loop, daemon=True)
            t.start()

    def _refresh_loop(self):
        while not self._shutdown.wait(self.refresh_interval):
            self.refresh()

    def stop(self):
        self._shutdown.set()
        if self._stats_sock is not None:
            self._stats_sock.close()
        if self._grpc is not None:
            self._grpc.stop(grace=1.0)
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()   # release the listening fd now
        with self._lock:
            for c in self._conns.values():
                c.close()
            self._conns.clear()
