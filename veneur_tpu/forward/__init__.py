"""The distributed tier: local→global sketch forwarding over gRPC
(reference forwardrpc/, importsrv/, proxysrv/ — SURVEY §2.4)."""
