"""Multi-tenant fairness: identity, weighted admission, quarantine mirror.

Tenant identity is a configurable tag (default ``tenant:``) extracted
from the raw datagram BEFORE parsing — admission must not pay a parse
for traffic it is about to shed, and the per-tenant shed count must
land on the same identity the fairness decision used. The extraction
here is the byte-exact Python mirror of the C++ ring-boundary
extractor (dogstatsd.cpp tenant_extract); tests/test_intake_fuzz.py
pins the parity over a corpus of malformed datagrams. Every anomaly —
missing tag, tag split across a truncated datagram, empty / oversized /
invalid-UTF-8 value — maps to the DEFAULT tenant, never to a drop: the
datagram is still admitted-and-accounted under ``default``.

Fairness is a weighted token bucket per tenant (rate = base_rate *
weight), layered UNDER the per-class admission ladder at SHEDDING+ by
OverloadController.admit and by the C++ rings (admit_datagram2): a
tenant over its fair share is throttled to its own bucket while
isolated tenants keep their full budget. Buckets are host-wide, not
per ring — SO_REUSEPORT flow hashing can concentrate one tenant on one
ring, and placement must not decide fair share.

Quarantine (the tag-explosion detector) lives in the native engine:
per-tenant distinct-key counters with geometric decay (the additive-
error end of the arXiv:2004.10332 counter family) demote a runaway
tenant to aggregate-only rollup rows, SALSA-style bounded degradation
(arXiv:2102.12531) — measured, not dropped. This module mirrors that
state for telemetry/health and carries it through checkpoint/restore;
the pure-Python parse path does not demote.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

DEFAULT_TENANT = "default"

# mirror of dogstatsd.cpp kTenantValueMax: longer values -> default
TENANT_VALUE_MAX = 64


def _utf8_valid(b: bytes) -> bool:
    """Byte-exact mirror of the C++ validator (dogstatsd.cpp
    utf8_valid): lead-byte ranges and continuation count only — NOT
    full strict UTF-8 (it deliberately stays cheap on the admission
    path), so `bytes.decode` would diverge on e.g. overlong 3-byte
    forms. Parity with the ring boundary matters more than strictness:
    both sides must map the same values to the same tenant."""
    i, n = 0, len(b)
    while i < n:
        c = b[i]
        if c < 0x80:
            i += 1
            continue
        if (c & 0xE0) == 0xC0:
            if c < 0xC2:
                return False
            need = 1
        elif (c & 0xF0) == 0xE0:
            need = 2
        elif (c & 0xF8) == 0xF0:
            if c > 0xF4:
                return False
            need = 3
        else:
            return False
        if i + need >= n:
            return False
        for k in range(1, need + 1):
            if (b[i + k] & 0xC0) != 0x80:
                return False
        i += need + 1
    return True


def extract_tenant(tag: str, data: bytes) -> Optional[str]:
    """The tenant value of the first well-formed `tag` occurrence in a
    raw datagram, or None for every default-tenant outcome. Mirror of
    dogstatsd.cpp tenant_extract: the occurrence must follow '#' or ','
    (i.e. sit in a tag section), the value runs to ','/'|'/newline, and
    empty, oversized, or invalid-UTF-8 values all resolve to None."""
    tag_b = tag.encode("utf-8", "surrogateescape")
    if not tag_b or len(data) <= len(tag_b):
        return None
    start = 0
    while True:
        hit = data.find(tag_b, start)
        if hit < 0:
            return None
        if hit > 0 and data[hit - 1:hit] in (b"#", b","):
            val_start = hit + len(tag_b)
            end = val_start
            while end < len(data) and data[end:end + 1] not in (
                    b",", b"|", b"\n"):
                end += 1
            val = data[val_start:end]
            if not val or len(val) > TENANT_VALUE_MAX \
                    or not _utf8_valid(val):
                return None
            return val.decode("utf-8", "surrogateescape")
        start = hit + 1


class TenantFairness:
    """Host-wide tenant state: weighted admission buckets (the Python
    fallback path's twin of the C++ per-tenant buckets), exact
    per-(tenant, class) admitted/shed counters that both admission
    sites fold into, and the quarantine mirror fed from the native
    engine's tenant table. All public methods are thread-safe — counts
    arrive from the pipeline thread, the controller poll thread, and
    (native fold) the flush path."""

    def __init__(self, *,
                 tag: str = "tenant:",
                 weights: Optional[Dict[str, float]] = None,
                 base_rate: float = 0.0,
                 burst_mult: float = 2.0,
                 quarantine_max_keys: int = 0,
                 quarantine_decay: float = 0.5,
                 quarantine_readmit_frac: float = 0.5,
                 clock: Callable[[], float] = time.monotonic):
        self.tag = tag
        self.weights = dict(weights or {})
        self.base_rate = float(base_rate)
        self.burst_mult = float(burst_mult) if burst_mult > 0 else 2.0
        self.quarantine_max_keys = int(quarantine_max_keys)
        self.quarantine_decay = float(quarantine_decay)
        self.quarantine_readmit_frac = float(quarantine_readmit_frac)
        self._clock = clock
        self._lock = threading.Lock()
        # tenant -> (tokens, last); weighted bucket state (Python path)
        self._buckets: Dict[str, Tuple[float, float]] = {}
        # exact accounting: tenant -> {class: n}
        self.admitted: Dict[str, Dict[str, int]] = {}
        self.shed: Dict[str, Dict[str, int]] = {}
        self.demoted_rows: Dict[str, int] = {}
        # quarantine mirror, refreshed from the engine table each poll:
        # tenant -> {"demoted": bool, "key_est": float}
        self.table: Dict[str, dict] = {}

    # -- identity ------------------------------------------------------------
    def resolve(self, data: bytes) -> str:
        return extract_tenant(self.tag, data) or DEFAULT_TENANT

    def weight(self, tenant: str) -> float:
        return float(self.weights.get(tenant, 1.0))

    # -- weighted admission (Python-parser fallback path) --------------------
    def allow(self, tenant: str) -> bool:
        """Weighted token bucket, mirror of dogstatsd.cpp tenant_allow:
        rate = base_rate * weight, burst = rate * burst_mult (floor 1).
        rate <= 0 disables the bucket (always admit)."""
        rate = self.base_rate * self.weight(tenant)
        if rate <= 0.0:
            return True
        burst = max(rate * self.burst_mult, 1.0)
        now = self._clock()
        with self._lock:
            tokens, last = self._buckets.get(tenant, (burst, now))
            tokens = min(burst, tokens + (now - last) * rate)
            if tokens >= 1.0:
                self._buckets[tenant] = (tokens - 1.0, now)
                return True
            self._buckets[tenant] = (tokens, now)
            return False

    # -- exact accounting ----------------------------------------------------
    def count(self, tenant: str, cls: str, admitted: bool,
              n: int = 1) -> None:
        with self._lock:
            d = self.admitted if admitted else self.shed
            per = d.setdefault(tenant, {})
            per[cls] = per.get(cls, 0) + n

    def fold_native(self, tenants: Dict[str, dict]) -> None:
        """Fold one host-wide per-tenant drain (the "tenants" sub-dict
        of NativeIngest.admission_drain / ring_admission_drain_one,
        already summed across rings by the caller's fold) into the same
        counters the Python admit path feeds — per-tenant
        sent == admitted + shed stays exact across both sites."""
        with self._lock:
            for tenant, ent in tenants.items():
                for side, dst in (("admitted", self.admitted),
                                  ("shed", self.shed)):
                    for cls, n in ent.get(side, {}).items():
                        if n:
                            per = dst.setdefault(tenant, {})
                            per[cls] = per.get(cls, 0) + int(n)
                rows = ent.get("demoted_rows", 0)
                if rows:
                    self.demoted_rows[tenant] = \
                        self.demoted_rows.get(tenant, 0) + int(rows)

    # -- quarantine mirror / checkpoint --------------------------------------
    def update_table(self, table: Dict[str, dict]) -> None:
        """Refresh the quarantine mirror from the engine's
        non-destructive tenant_table() snapshot."""
        with self._lock:
            self.table = {name: dict(ent) for name, ent in table.items()}

    def quarantined_tenants(self) -> List[str]:
        with self._lock:
            return sorted(name for name, ent in self.table.items()
                          if ent.get("demoted"))

    def snapshot_state(self) -> dict:
        """Checkpoint sidecar payload: the engine table in a stable
        order (id order is not recoverable here; name order restores
        deterministically on both ends) plus the exact demoted-row
        totals so restored telemetry is monotonic across a restart."""
        with self._lock:
            return {
                "table": [
                    [name, bool(ent.get("demoted")),
                     float(ent.get("key_est", 0.0))]
                    for name, ent in sorted(self.table.items())],
                "demoted_rows": dict(self.demoted_rows),
            }

    def restore_state(self, snap: dict) -> List[tuple]:
        """Apply a checkpoint sidecar: seeds the mirror and the
        monotonic demoted-row totals, and returns the (name, demoted,
        key_est) entries for push-down into the engine
        (NativeIngest.tenant_restore)."""
        entries = [(str(name), bool(dem), float(est))
                   for name, dem, est in snap.get("table", [])]
        with self._lock:
            self.table = {name: {"demoted": dem, "key_est": est}
                          for name, dem, est in entries}
            for tenant, n in snap.get("demoted_rows", {}).items():
                self.demoted_rows[tenant] = \
                    self.demoted_rows.get(tenant, 0) + int(n)
        return entries

    # -- telemetry snapshots (registry callback shapes) ----------------------
    def _labeled_totals(self, d: Dict[str, Dict[str, int]]
                        ) -> List[Tuple[Tuple[str], int]]:
        return [((tenant,), sum(per.values()))
                for tenant, per in sorted(d.items())]

    def admitted_snapshot(self) -> List[Tuple[Tuple[str], int]]:
        with self._lock:
            return self._labeled_totals(self.admitted)

    def shed_snapshot(self) -> List[Tuple[Tuple[str], int]]:
        with self._lock:
            return self._labeled_totals(self.shed)

    def demoted_rows_snapshot(self) -> List[Tuple[Tuple[str], int]]:
        with self._lock:
            return [((tenant,), n)
                    for tenant, n in sorted(self.demoted_rows.items())]

    def quarantined_snapshot(self) -> List[Tuple[Tuple[str], int]]:
        """0/1 gauge per tenant currently known to the engine table."""
        with self._lock:
            return [((name,), 1 if ent.get("demoted") else 0)
                    for name, ent in sorted(self.table.items())]

    # -- native push-down ----------------------------------------------------
    def native_config(self) -> dict:
        """kwargs for NativeIngest.tenant_config (pre-rings, once)."""
        return {
            "enabled": True,
            "tag": self.tag,
            "burst_mult": self.burst_mult,
            "q_max_keys": self.quarantine_max_keys,
            "q_decay": self.quarantine_decay,
            "q_readmit_frac": self.quarantine_readmit_frac,
        }

    def native_params(self) -> tuple:
        """(base_rate, weights) snapshot for the per-poll push
        (NativeIngest.tenant_params)."""
        with self._lock:
            return self.base_rate, dict(self.weights)
