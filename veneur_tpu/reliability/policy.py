"""Retry and circuit-breaker policies for egress paths.

Both are pure Python with injectable clock/sleep so tests run in virtual
time. Defaults are reference-compatible: a RetryPolicy is only built when
`sink_retry_max > 0`, a CircuitBreaker only when
`circuit_failure_threshold > 0` — unconfigured, every egress path keeps
today's single-attempt behavior byte for byte.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from veneur_tpu.utils.hashing import splitmix64

# CircuitBreaker states; the numeric values ARE the wire values of the
# veneur.circuit.state gauge (0 healthy, 2 fully tripped, 1 probing).
CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half-open", OPEN: "open"}


class CircuitOpenError(RuntimeError):
    """An egress call was refused because the destination's breaker is
    open — counted as a skip, never silent."""


class RetryPolicy:
    """Exponential backoff with deterministic seeded jitter.

    backoff(attempt) = min(base_ms * 2^attempt, max_ms) * (1 + U*jitter)
    where U in [0, 1) is derived from splitmix64(seed, attempt) — the
    same (seed, attempt) always yields the same delay, so retry schedules
    are reproducible in tests and across a fleet each instance decorrelates
    by seeding with something instance-unique.

    `deadline_s` bounds the WHOLE retry loop (a retry that cannot finish
    before the deadline is not started); `attempt_timeout_s` is the
    per-attempt budget, advisory for callers whose underlying call takes
    a timeout parameter (a thread cannot be interrupted mid-call).
    """

    def __init__(self, max_retries: int = 2, base_ms: float = 100.0,
                 max_ms: float = 10_000.0, jitter: float = 0.5,
                 seed: int = 0, attempt_timeout_s: Optional[float] = None,
                 deadline_s: Optional[float] = None):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.max_retries = int(max_retries)
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.attempt_timeout_s = attempt_timeout_s
        self.deadline_s = deadline_s

    def backoff(self, attempt: int) -> float:
        """Delay in seconds before retry number `attempt` (0-based)."""
        base = min(self.base_ms * (2.0 ** attempt), self.max_ms) / 1000.0
        u = splitmix64(((self.seed & 0xFFFFFFFF) << 20) ^ attempt) / 2.0**64
        return base * (1.0 + u * self.jitter)

    def run(self, fn: Callable, *, sleep: Callable[[float], None] = None,
            clock: Callable[[], float] = None,
            on_retry: Optional[Callable] = None):
        """Call `fn()` with up to max_retries retries. `on_retry(attempt,
        exc, delay)` fires before each backoff sleep. The final failure
        re-raises — callers keep their own error accounting."""
        sleep = time.sleep if sleep is None else sleep
        clock = time.monotonic if clock is None else clock
        start = clock()
        attempt = 0
        while True:
            try:
                return fn()
            except CircuitOpenError:
                # retrying into an open breaker is pure delay: the
                # cooldown is longer than any backoff step by design
                raise
            except Exception as e:
                if attempt >= self.max_retries:
                    raise
                delay = self.backoff(attempt)
                if (self.deadline_s is not None
                        and clock() - start + delay > self.deadline_s):
                    raise
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                sleep(delay)
                attempt += 1


class CircuitBreaker:
    """Per-destination breaker: closed -> open -> half-open.

    `failure_threshold` consecutive failures open the circuit (the
    degenerate 100%-rate window — consecutive counting keeps the state
    machine exactly testable where a sampled-rate window is not). While
    open, allow() is False until `cooldown_s` has elapsed, then ONE probe
    is admitted (half-open); its success closes the circuit, its failure
    re-opens it for another cooldown. Thread-safe: sink flush threads,
    aux forward threads, and the self-telemetry reporter all touch it.
    """

    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold <= 0:
            raise ValueError("failure_threshold must be > 0")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self.opens_total = 0
        self.rejected_total = 0

    @property
    def state(self) -> int:
        with self._lock:
            # an expired cooldown reads as half-open even before the
            # next allow() call arms the probe
            if (self._state == OPEN
                    and self._clock() - self._opened_at >= self.cooldown_s):
                return HALF_OPEN
            return self._state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self.state]

    def allow(self) -> bool:
        """May a call proceed right now? Open-state refusals are counted
        (rejected_total); a True in half-open claims the single probe."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._state = HALF_OPEN
                    self._probe_in_flight = True
                    return True
                self.rejected_total += 1
                return False
            # HALF_OPEN: one probe at a time
            if self._probe_in_flight:
                self.rejected_total += 1
                return False
            self._probe_in_flight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._failures = 0
            self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if (self._state == HALF_OPEN
                    or self._failures >= self.failure_threshold):
                self._state = OPEN
                self._opened_at = self._clock()
                self._probe_in_flight = False
                self._failures = 0
                self.opens_total += 1
