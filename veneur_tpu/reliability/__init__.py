"""Shared resilience layer for every egress path.

The reference bills veneur as distributed and fault-tolerant, yet its
flush->sink and flush->forward paths are single-attempt: one failed POST
drops an interval. This package holds the pieces that close that gap —
pure-Python, injectable-clock, so everything is testable in virtual time:

- policy:  RetryPolicy (exponential backoff + deterministic seeded
           jitter) and CircuitBreaker (closed -> open -> half-open).
- spill:   ForwardSpillBuffer — failed forwards keep their mergeable
           sketch payloads and merge into the NEXT interval's forward
           batch losslessly (t-digests merge, HLL registers fold with
           max, counters add), instead of the reference's drop.
- faults:  a process-global FaultInjector with named injection points in
           the egress paths, so chaos tests force errors, latency, and
           partial failures deterministically. Default no-op.
- overload: OverloadController — samples the pipeline's pressure
           signals and drives the HEALTHY -> PRESSURED -> SHEDDING ->
           CRITICAL hysteresis state machine behind admission control,
           priority shedding, degraded aggregation, and the /healthz +
           /readyz endpoints (README §Overload & health).
"""
