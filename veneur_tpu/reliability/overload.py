"""Overload management: one controller that sees every pressure signal.

The pipeline already measures everything that matters under load —
packet-queue depth, flush-job backlog, flush lag vs. the deadline,
key-table capacity drops, spill occupancy, breaker states, checkpoint
age — but each signal acted alone: queue.Full dropped, capacity dropped,
deferred flushes deferred, with no coordination, no priority, and no
externally visible health state. The OverloadController samples those
signals on a poller thread and drives a hysteresis state machine

    HEALTHY -> PRESSURED -> SHEDDING -> CRITICAL

whose states activate concrete degradations, shed-last-by-priority:

- admission control at the ingest boundaries (token bucket per source,
  priority classifier: self-metrics never shed, `shed_priority_tags`
  matches shed last, everything else sheds first);
- degraded aggregation (timers switch to probabilistic sampling with
  recorded sample-rate correction; sets subsample members by hash
  prefix with an exact 2^k flush correction — accuracy degrades
  boundedly instead of rows dropping), see server/aggregator.py;
- flush protection (CRITICAL skips sink fan-out for low-priority rows
  but never the device update, forward, or checkpoint cadence), see
  server/server.py _do_flush.

Upgrades are immediate (pressure is an emergency); downgrades step one
level at a time, gated on a dwell time (`hold_s`) AND an exit margin
below the state's entry threshold, so a load step cannot flap the
state machine across a threshold (SALSA, arXiv:2102.12531, motivates
the bounded-degradation stance). Everything takes an injectable clock
and signal source, so tests run in virtual time — the CircuitBreaker
pattern (policy.py).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

log = logging.getLogger("veneur_tpu.reliability.overload")

HEALTHY = 0
PRESSURED = 1
SHEDDING = 2
CRITICAL = 3

STATE_NAMES = {HEALTHY: "healthy", PRESSURED: "pressured",
               SHEDDING: "shedding", CRITICAL: "critical"}

# priority classes, shed-first order: low sheds first, high sheds only
# under CRITICAL rate-limiting, self NEVER sheds (blinding the operator's
# own telemetry during an incident is the one unforgivable degradation)
CLASS_SELF = "self"
CLASS_HIGH = "high"
CLASS_LOW = "low"
CLASS_IMPORT = "import"

_SELF_PREFIXES = (b"veneur.", b"veneur_tpu.")


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill up to `burst`.
    Single-threaded by design (admission runs on the pipeline thread);
    the clock is injectable for virtual-time tests."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else float(rate)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()

    def allow(self, n: float = 1.0) -> bool:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


class PriorityClassifier:
    """Raw-bytes packet classifier (it must run before parsing — the
    whole point is to shed before paying the parse). Granularity is the
    packet: a multi-line datagram classifies by its strongest line
    (any high-priority tag match promotes the packet)."""

    def __init__(self, high_tags: Iterable = ()):
        self._high = tuple(
            t.encode() if isinstance(t, str) else bytes(t)
            for t in high_tags if t)

    def classify(self, data: bytes) -> str:
        if data.startswith(_SELF_PREFIXES):
            return CLASS_SELF
        for tag in self._high:
            if tag in data:
                return CLASS_HIGH
        return CLASS_LOW


class OverloadController:
    """Samples pressure signals and drives the health state machine.

    `signals` is a zero-arg callable returning {name: pressure} where
    each pressure is normalized to [0, 1] against that resource's
    capacity; overall pressure is the max (one saturated resource is
    an overloaded server — averaging would hide it).

    Admission policy by state (self-class always admitted):
      HEALTHY    admit everything
      PRESSURED  low-priority through the token bucket (if configured)
      SHEDDING   shed low-priority; degraded aggregation active
      CRITICAL   shed low; high through the token bucket; imports shed
    """

    def __init__(self, *,
                 signals: Callable[[], Dict[str, float]],
                 enter_pressured: float = 0.70,
                 enter_shedding: float = 0.85,
                 enter_critical: float = 0.95,
                 exit_margin: float = 0.10,
                 hold_s: float = 5.0,
                 admit_rate: float = 0.0,
                 admit_burst: float = 0.0,
                 timer_sample_rate: float = 0.5,
                 set_shift: int = 2,
                 shed_priority_tags: Iterable = (),
                 tenancy=None,
                 clock: Callable[[], float] = time.monotonic):
        self._signals = signals
        self._clock = clock
        self._enter = {PRESSURED: float(enter_pressured),
                       SHEDDING: float(enter_shedding),
                       CRITICAL: float(enter_critical)}
        self.exit_margin = float(exit_margin)
        self.hold_s = float(hold_s)
        self.admit_rate = float(admit_rate)
        self.admit_burst = float(admit_burst)
        self.timer_sample_rate = float(timer_sample_rate)
        self.set_shift = int(set_shift)
        self.classifier = PriorityClassifier(shed_priority_tags)
        # optional reliability/tenancy.py TenantFairness: layers the
        # weighted per-tenant bucket under the class ladder at SHEDDING+
        # and receives per-(tenant, class) counts for every decision
        self.tenancy = tenancy
        self._buckets: Dict[str, TokenBucket] = {}
        # accounting: exact per-class admit/shed counters. The lock only
        # guards the increments — imports arrive on gRPC/HTTP threads
        # while packets arrive on the pipeline thread, and the storm
        # benchmark asserts shed + admitted == sent EXACTLY.
        self._lock = threading.Lock()
        self.admitted: Dict[str, int] = {}
        self.shed: Dict[str, int] = {}
        self.degraded_flushes = 0
        self.state = HEALTHY
        # RESHARDING is a sub-state orthogonal to the pressure ladder:
        # ready-but-announcing. /readyz stays ok (peers keep sending —
        # the whole point of LIVE resharding), but health exposes it as
        # the machine-readable phase so the proxy prober and dashboards
        # can tell "moving shards" from "broken".
        self.resharding = False
        self.pressure = 0.0
        self.last_signals: Dict[str, float] = {}
        self.state_since = clock()
        # (clock_ts, from_state, to_state), newest last, bounded
        self.transitions: List[Tuple[float, int, int]] = []
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # -- state machine -------------------------------------------------------
    @property
    def state_name(self) -> str:
        return STATE_NAMES[self.state]

    def poll(self) -> int:
        """Sample signals once and advance the state machine. Called by
        the poller thread in production, directly by virtual-time
        tests."""
        now = self._clock()
        try:
            sig = dict(self._signals() or {})
        except Exception as e:
            # a broken signal source must never take down the poller;
            # pressure holds at its last value for this tick
            log.warning("overload signal sampling failed: %s", e)
            sig = dict(self.last_signals)
        pressure = 0.0
        for v in sig.values():
            if v > pressure:
                pressure = min(float(v), 1.0)
        self.pressure = pressure
        self.last_signals = sig
        target = HEALTHY
        for s in (CRITICAL, SHEDDING, PRESSURED):
            if pressure >= self._enter[s]:
                target = s
                break
        cur = self.state
        if target > cur:
            # upgrades are immediate: waiting out a dwell during an
            # ingest storm just converts the dwell into queue drops
            self._transition(now, target)
        elif target < cur and now - self.state_since >= self.hold_s \
                and pressure < self._enter[cur] - self.exit_margin:
            # downgrades step ONE level with dwell + exit margin: a
            # load step that hovers at a threshold cannot flap
            self._transition(now, cur - 1)
        return self.state

    def _transition(self, now: float, to: int) -> None:
        log.info("overload state %s -> %s (pressure=%.3f, signals=%s)",
                 STATE_NAMES[self.state], STATE_NAMES[to], self.pressure,
                 {k: round(v, 3) for k, v in self.last_signals.items()})
        self.transitions.append((now, self.state, to))
        del self.transitions[:-256]
        self.state = to
        self.state_since = now

    # -- resharding sub-state ------------------------------------------------
    def enter_resharding(self) -> None:
        self.resharding = True

    def exit_resharding(self) -> None:
        self.resharding = False

    # -- admission -----------------------------------------------------------
    def _bucket_allow(self, key: str) -> bool:
        if self.admit_rate <= 0:
            return True
        b = self._buckets.get(key)
        if b is None:
            b = self._buckets[key] = TokenBucket(
                self.admit_rate, self.admit_burst, self._clock)
        return b.allow()

    def admit(self, data: bytes, source: str = "statsd") -> bool:
        """Admission decision for one raw wire packet at an ingest
        boundary. Token buckets are keyed per (source, class) so a
        flood of low-priority traffic cannot starve high-priority
        packets out of their own bucket. With tenancy configured, the
        tenant's weighted bucket layers under the class ladder at
        SHEDDING+ (mirror of dogstatsd.cpp admit_datagram2): low-class
        traffic the ladder would shed outright instead runs the
        tenant's bucket, so isolated tenants keep their budget while a
        noisy one is throttled to its share."""
        cls = self.classifier.classify(data)
        s = self.state
        ten = self.tenancy
        tenant = ten.resolve(data) if ten is not None else None
        fair = ten is not None and ten.base_rate > 0
        if s == HEALTHY or cls == CLASS_SELF:
            ok = True
        elif cls == CLASS_HIGH:
            ok = s < CRITICAL or self._bucket_allow(source + "/high")
            if ok and fair and s >= SHEDDING:
                ok = ten.allow(tenant)
        elif s >= SHEDDING:
            ok = ten.allow(tenant) if fair else False
        else:  # low priority under PRESSURED
            ok = self._bucket_allow(source)
        with self._lock:
            d = self.admitted if ok else self.shed
            d[cls] = d.get(cls, 0) + 1
        if ten is not None:
            ten.count(tenant, cls, ok)
        return ok

    def import_blocked(self) -> bool:
        """Imports (global-tier merges) shed only at CRITICAL: they are
        pre-aggregated sketches — dense value per byte — so they are the
        last boundary to close."""
        return self.state >= CRITICAL

    def admit_import(self, n: int = 1) -> bool:
        ok = not self.import_blocked()
        with self._lock:
            d = self.admitted if ok else self.shed
            d[CLASS_IMPORT] = d.get(CLASS_IMPORT, 0) + n
        return ok

    # -- degradation knobs ---------------------------------------------------
    def degraded_timer_rate(self) -> float:
        """Timer admit fraction for the aggregators: <1.0 switches timers
        to probabilistic sampling with recorded sample-rate correction
        (exact in expectation; see Aggregator._histo_admit)."""
        if self.state >= SHEDDING and 0.0 < self.timer_sample_rate < 1.0:
            return self.timer_sample_rate
        return 1.0

    def degraded_set_shift(self) -> int:
        """HLL member-subsample bits: admit a member iff the low k bits
        of its 64-bit hash are zero (rate 2^-k) and multiply the flushed
        estimate by 2^k. Deterministic per member, so repeated members
        stay idempotent — cardinality accuracy degrades boundedly
        instead of set rows dropping."""
        return self.set_shift if self.state >= SHEDDING else 0

    def note_degraded_flush(self) -> None:
        with self._lock:
            self.degraded_flushes += 1

    def count_flush_shed(self, n: int) -> None:
        """Rows withheld from sink fan-out by CRITICAL flush
        protection (class `flush`)."""
        if n <= 0:
            return
        with self._lock:
            self.shed["flush"] = self.shed.get("flush", 0) + n

    # -- telemetry snapshots -------------------------------------------------
    @property
    def admitted_total(self) -> int:
        with self._lock:
            return sum(self.admitted.values())

    def shed_snapshot(self) -> List[Tuple[Tuple[str], int]]:
        """Labeled pairs for a registry counter callback."""
        with self._lock:
            return [((cls,), n) for cls, n in sorted(self.shed.items())]

    # -- native-ring admission push-down / fold-back -------------------------
    def native_admission_params(self) -> tuple:
        """(state, admit_rate, admit_burst, high_tags) snapshot for
        push-down into the C++ reader ring (vr_admission_set), which
        replicates admit(source='statsd') off-GIL at the ring boundary.
        Pushed on every poll so state transitions reach the ring within
        one poll interval."""
        tags = tuple(t.decode("utf-8", "surrogateescape")
                     for t in self.classifier._high)
        return self.state, self.admit_rate, self.admit_burst, tags

    def fold_native_counts(self, drained: dict) -> None:
        """Fold the exact per-class admitted/shed deltas drained from the
        C++ reader ring (vr_admission_counters drain-and-reset) into the
        same counters admit() feeds, preserving sent == admitted + shed
        exactly across both admission sites."""
        with self._lock:
            for cls, n in drained.get("admitted", {}).items():
                if n:
                    self.admitted[cls] = self.admitted.get(cls, 0) + int(n)
            for cls, n in drained.get("shed", {}).items():
                if n:
                    self.shed[cls] = self.shed.get(cls, 0) + int(n)
        # per-tenant deltas (already summed across rings by the drain
        # fold) route to the tenancy ledger, same exactness contract
        if self.tenancy is not None and drained.get("tenants"):
            self.tenancy.fold_native(drained["tenants"])

    # -- poller thread -------------------------------------------------------
    def start(self, poll_interval: float,
              on_poll: Optional[Callable[["OverloadController"], None]]
              = None) -> None:
        if self._thread is not None:
            return
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(poll_interval):
                self.poll()
                if on_poll is not None:
                    try:
                        on_poll(self)
                    except Exception as e:
                        log.warning("overload on_poll hook failed: %s", e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="overload-poller")
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None
            self._stop = None
