"""Process-global fault injector for chaos testing the egress paths.

Named injection points are wired into forward/rpc.py (`forward.send`),
forward/tracedhttp.py (`http.post`), forward/proxysrv.py
(`proxy.forward`), the sink flush dispatch in sinks/base.py
(`sink.flush`), and the server's flush worker (`flush.worker`). Each
point calls `FAULTS.inject(point, name=...)`, which is a single
attribute check when nothing is armed — the production cost is nil.

Activation:
- tests: `FAULTS.arm("sink.flush", error="boom", times=2)` (and
  `FAULTS.reset()` in teardown);
- env:    VENEUR_FAULT_INJECTION="forward.send:error:2,sink.flush:latency:0.05"
- config: the `fault_injection` key, same spec grammar.

Spec grammar (comma-separated):  point:error[:times]  or
point:latency:seconds[:times]. Latency uses the injector's sleep, which
tests may replace with a virtual clock.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, Optional

log = logging.getLogger("veneur_tpu.reliability.faults")

# the canonical point names (keep in sync with the wiring listed above)
FORWARD_SEND = "forward.send"
# injected AFTER a forward send succeeded on the wire: the receiver has
# folded the batch but the sender sees a failure — i.e. a lost ack. The
# sender must retry the SAME (source_id, epoch, seq) and the receiver's
# dedup window must suppress the re-fold.
FORWARD_ACK = "forward.ack"
HTTP_POST = "http.post"
PROXY_FORWARD = "proxy.forward"
SINK_FLUSH = "sink.flush"
FLUSH_WORKER = "flush.worker"
CHECKPOINT_WRITE = "checkpoint.write"
# injected AFTER a migration unit folded into the receiving aggregator
# but BEFORE the coordinator records its progress — the mid-move receiver
# crash: the whole migration epoch replays under the ORIGINAL seqs and
# the dedup window must answer DUPLICATE for everything already folded.
RESHARD_FOLD = "reshard.fold"


class InjectedFault(RuntimeError):
    """The error raised by an armed `error` rule — distinguishable from
    organic failures in logs and assertions."""


@dataclasses.dataclass
class _Rule:
    error: bool = False
    latency_s: float = 0.0
    times: Optional[int] = None   # None = until reset
    match: Optional[str] = None   # substring filter on the point's name
    message: str = ""
    fired: int = 0


class FaultInjector:
    def __init__(self, sleep: Callable[[float], None] = time.sleep):
        self._sleep = sleep
        self._lock = threading.Lock()
        self._rules: Dict[str, _Rule] = {}
        self._armed = False   # fast-path gate, read without the lock
        self.injected_total = 0   # faults actually fired, across all
        #                           points (exported by the telemetry
        #                           registry so a chaos drill is visible
        #                           in /metrics next to its victims)

    def arm(self, point: str, *, error: bool = False, latency_s: float = 0.0,
            times: Optional[int] = None, match: Optional[str] = None,
            message: str = "") -> None:
        with self._lock:
            self._rules[point] = _Rule(error=error, latency_s=latency_s,
                                       times=times, match=match,
                                       message=message or
                                       f"injected fault at {point}")
            self._armed = True

    def reset(self) -> None:
        with self._lock:
            self._rules.clear()
            self._armed = False

    def fired(self, point: str) -> int:
        with self._lock:
            rule = self._rules.get(point)
            return rule.fired if rule is not None else 0

    def inject(self, point: str, name: str = "") -> None:
        """The hook every wired egress point calls. No-op unless armed."""
        if not self._armed:
            return
        with self._lock:
            rule = self._rules.get(point)
            if rule is None:
                return
            if rule.match is not None and rule.match not in name:
                return
            if rule.times is not None:
                if rule.times <= 0:
                    return
                rule.times -= 1
            rule.fired += 1
            self.injected_total += 1
            latency, raise_error, msg = (rule.latency_s, rule.error,
                                         rule.message)
        if latency > 0:
            self._sleep(latency)
        if raise_error:
            raise InjectedFault(f"{msg} ({point}{f' {name}' if name else ''})")

    def configure(self, spec: str) -> None:
        """Arm from the env/config spec grammar (see module docstring)."""
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            if len(parts) < 2:
                raise ValueError(f"bad fault spec {entry!r}: want "
                                 "point:error[:times] or "
                                 "point:latency:seconds[:times]")
            point, mode = parts[0], parts[1]
            if mode == "error":
                times = int(parts[2]) if len(parts) > 2 else None
                self.arm(point, error=True, times=times)
            elif mode == "latency":
                if len(parts) < 3:
                    raise ValueError(
                        f"bad fault spec {entry!r}: latency needs seconds")
                times = int(parts[3]) if len(parts) > 3 else None
                self.arm(point, latency_s=float(parts[2]), times=times)
            else:
                raise ValueError(f"bad fault mode {mode!r} in {entry!r}")
            log.warning("fault injection ARMED: %s", entry)


# the process-global injector every wired point consults
FAULTS = FaultInjector()
