"""Forward spill buffer: merge-on-retry instead of drop-on-failure.

The Go reference drops a failed forward's payload — one gRPC error loses
an interval of sketch state. Our forward payloads are MERGEABLE
(metricpb.Metric: t-digest centroids merge, HLL registers fold with max,
counters add — PAPERS.md, Dunning t-digests), so a failed forward can be
held and merged into the NEXT interval's forward batch losslessly: the
receiving global tier imports metric-by-metric and merges by key, so
shipping interval N's sketches alongside interval N+1's reproduces the
exact state a never-failed run would have built.

The buffer is bounded by bytes and by age; when a cap is hit the OLDEST
payloads drop first and every drop is counted — degradation is
observable, never silent (veneur.forward.spill_bytes /
veneur.forward.spill.dropped_total in self-telemetry).
"""

from __future__ import annotations

import logging
import struct
import threading
import time
from collections import deque
from typing import Callable, List, Tuple

log = logging.getLogger("veneur_tpu.reliability.spill")

# wire format (persistence checkpoints): magic, then the caps + entry
# count, then per entry the ORIGINAL spill stamp and the metricpb blob —
# stamps survive a restart so max_age_s keeps bounding total staleness
_SPILL_MAGIC = b"VSPL1"
_SPILL_HEADER = struct.Struct("<qdI")   # max_bytes, max_age_s, count
_SPILL_ENTRY = struct.Struct("<dI")     # spilled_at, blob length


def parse_spill_bytes(data: bytes) -> Tuple[List, Tuple[int, float]]:
    """-> ([(spilled_at, metricpb.Metric), ...], (max_bytes, max_age_s)).
    Raises ValueError on malformed bytes (checkpoint CRCs catch rot; this
    catches format drift)."""
    from veneur_tpu.proto import metricpb_pb2 as mpb
    if data[:len(_SPILL_MAGIC)] != _SPILL_MAGIC:
        raise ValueError("bad spill magic")
    off = len(_SPILL_MAGIC)
    try:
        max_bytes, max_age_s, count = _SPILL_HEADER.unpack_from(data, off)
        off += _SPILL_HEADER.size
        entries = []
        for _ in range(count):
            spilled_at, blob_len = _SPILL_ENTRY.unpack_from(data, off)
            off += _SPILL_ENTRY.size
            blob = data[off:off + blob_len]
            if len(blob) != blob_len:
                raise ValueError("truncated spill entry")
            off += blob_len
            entries.append((spilled_at, mpb.Metric.FromString(blob)))
    except struct.error as e:
        raise ValueError(f"truncated spill buffer: {e}")
    return entries, (max_bytes, max_age_s)


class ForwardSpillBuffer:
    """Holds forwardable metricpb.Metric payloads across failed intervals.

    Thread-safe: forwards run on fire-and-forget aux threads and a slow
    failing forward can overlap the next interval's.
    """

    def __init__(self, max_bytes: int, max_age_s: float = 60.0,
                 clock: Callable[[], float] = time.time):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be > 0")
        self.max_bytes = int(max_bytes)
        self.max_age_s = float(max_age_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: deque = deque()   # (spilled_at, metric, nbytes)
        self._bytes = 0
        self.spilled_total = 0       # metrics ever spilled
        self.dropped_capacity = 0    # metrics evicted by the byte cap
        self.dropped_age = 0         # metrics expired by max_age_s

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def dropped_total(self) -> int:
        with self._lock:
            return self.dropped_capacity + self.dropped_age

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def add(self, metrics: List, now: float = None) -> None:
        """Spill a failed forward's payload, stamped with the CURRENT
        clock. Evicts oldest-first when the byte cap is exceeded (a
        single over-cap payload evicts itself — the cap is a hard bound,
        not a suggestion)."""
        if not metrics:
            return
        now = self._clock() if now is None else now
        with self._lock:
            self.spilled_total += len(metrics)
            evicted = self._extend_locked(
                (now, m, m.ByteSize()) for m in metrics)
        if evicted:
            log.warning("forward spill over %d bytes: dropped %d oldest "
                        "payloads", self.max_bytes, evicted)

    def readd(self, entries: List) -> None:
        """Return previously drained (spilled_at, metric) entries after a
        re-failed send, keeping their ORIGINAL spill timestamps — so
        max_age_s bounds total staleness since the first failure, not
        time since the last retry. Re-adds are not re-counted in
        spilled_total.

        Entries land at the LEFT of the deque: drained entries are older
        than anything add() appended while the retry was in flight, and
        the deque must stay oldest-first or the byte-cap eviction (and a
        later drain()'s pair ordering) would drop fresh payloads while
        keeping stale ones."""
        if not entries:
            return
        with self._lock:
            for ts, m in reversed(entries):
                nb = m.ByteSize()
                self._entries.appendleft((ts, m, nb))
                self._bytes += nb
            evicted = self._evict_locked()
        if evicted:
            log.warning("forward spill over %d bytes: dropped %d oldest "
                        "payloads", self.max_bytes, evicted)

    def _extend_locked(self, triples) -> int:
        """Append (spilled_at, metric, nbytes) triples and enforce the
        byte cap; returns the evicted count. Caller holds the lock and
        must keep appends time-ordered (newest at the right)."""
        for t in triples:
            self._entries.append(t)
            self._bytes += t[2]
        return self._evict_locked()

    def _evict_locked(self) -> int:
        evicted = 0
        while self._bytes > self.max_bytes and self._entries:
            _, _, nb = self._entries.popleft()
            self._bytes -= nb
            self.dropped_capacity += 1
            evicted += 1
        return evicted

    def drain(self, now: float = None) -> List:
        """Take everything still fresh as (spilled_at, metric) pairs for
        merging into the next forward batch; expired payloads are dropped
        and counted. The buffer is emptied either way — a re-failed send
        returns the pairs via readd(), preserving their timestamps."""
        now = self._clock() if now is None else now
        with self._lock:
            out, expired = [], 0
            for spilled_at, m, _nb in self._entries:
                if now - spilled_at > self.max_age_s:
                    expired += 1
                else:
                    out.append((spilled_at, m))
            self._entries.clear()
            self._bytes = 0
            self.dropped_age += expired
        if expired:
            log.warning("forward spill: dropped %d payloads older than "
                        "%.0fs", expired, self.max_age_s)
        return out

    # -- persistence (checkpoints; README §Durability) ----------------------
    def to_bytes(self) -> bytes:
        """Serialize contents + caps, preserving every entry's original
        spill stamp. Point-in-time consistent (one lock hold)."""
        with self._lock:
            triples = list(self._entries)
        parts = [_SPILL_MAGIC,
                 _SPILL_HEADER.pack(self.max_bytes, self.max_age_s,
                                    len(triples))]
        for spilled_at, m, _nb in triples:
            blob = m.SerializeToString()
            parts.append(_SPILL_ENTRY.pack(spilled_at, len(blob)))
            parts.append(blob)
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, data: bytes,
                   clock: Callable[[], float] = time.time
                   ) -> "ForwardSpillBuffer":
        """Rebuild a buffer with the SERIALIZED caps and stamps. Entries
        already past max_age_s still re-enter; the next drain() expires
        them into dropped_age, so the drop accounting that would have
        happened without the restart still happens."""
        entries, (max_bytes, max_age_s) = parse_spill_bytes(data)
        buf = cls(max_bytes, max_age_s, clock=clock)
        buf.readd(entries)
        return buf
