"""Forward spill buffer: the forward path's durable send queue.

Two operating modes share one bounded buffer:

LEGACY (dedup off — merge-on-retry): the Go reference drops a failed
forward's payload — one gRPC error loses an interval of sketch state.
Our forward payloads are MERGEABLE (metricpb.Metric: t-digest centroids
merge, HLL registers fold with max, counters add — PAPERS.md, Dunning
t-digests), so a failed forward is held via add() and merged into the
NEXT interval's forward batch. Lossless only if every payload folds
exactly once; an ambiguous failure (the receiver DID fold before the
deadline fired) re-sends a re-merged copy and double-counts the
additive kinds.

ACK-GATED (exactly-once, forward_dedup_window > 0): every forwarded
interval is staged as an immutable UNIT under its (epoch, seq) envelope
BEFORE the send — so the payload is inside any checkpoint taken that
interval — and evicted only by ack(epoch, seq) after the receiving tier
acknowledged the seq. A failed or ambiguous send leaves the unit in
place; the retry re-sends the SAME bytes under the SAME seq and the
receiver's dedup window suppresses the potential duplicate. See
forward/envelope.py and README §Exactly-once forwarding.

Either way the buffer is bounded by bytes and by age; when a cap is hit
the OLDEST payloads drop first and every drop is counted — degradation
is observable, never silent (veneur.forward.spill_bytes /
veneur.forward.spill.dropped_total in self-telemetry).
"""

from __future__ import annotations

import logging
import struct
import threading
import time
from collections import deque
from typing import Callable, List, NamedTuple, Optional, Tuple

log = logging.getLogger("veneur_tpu.reliability.spill")

# wire format (persistence checkpoints): magic, then the caps + entry
# count, then per entry the ORIGINAL spill stamp and the metricpb blob —
# stamps survive a restart so max_age_s keeps bounding total staleness.
# VSPL2 adds the envelope's (epoch, seq) per entry; -1/-1 marks a legacy
# (unenveloped) entry. VSPL1 checkpoints are still readable.
_SPILL_MAGIC_V1 = b"VSPL1"
_SPILL_MAGIC = b"VSPL2"
_SPILL_HEADER = struct.Struct("<qdI")     # max_bytes, max_age_s, count
_SPILL_ENTRY_V1 = struct.Struct("<dI")    # spilled_at, blob length
_SPILL_ENTRY = struct.Struct("<dqqI")     # spilled_at, epoch, seq, blob len

_NO_ENVELOPE = -1


class SpillUnit(NamedTuple):
    """One staged forward payload: the metrics exported for an interval,
    frozen under the (epoch, seq) they were first stamped with."""
    epoch: int
    seq: int
    staged_at: float
    metrics: List


def parse_spill_bytes(data: bytes, with_envelope: bool = False
                      ) -> Tuple[List, Tuple[int, float]]:
    """-> ([(spilled_at, metricpb.Metric), ...], (max_bytes, max_age_s)),
    or 4-tuples (spilled_at, metric, epoch, seq) with `with_envelope`
    (epoch/seq are -1 for entries spilled without one). Accepts both the
    VSPL1 and VSPL2 wire formats. Raises ValueError on malformed bytes
    (checkpoint CRCs catch rot; this catches format drift)."""
    from veneur_tpu.proto import metricpb_pb2 as mpb
    magic = data[:len(_SPILL_MAGIC)]
    if magic == _SPILL_MAGIC:
        entry_struct = _SPILL_ENTRY
    elif magic == _SPILL_MAGIC_V1:
        entry_struct = _SPILL_ENTRY_V1
    else:
        raise ValueError("bad spill magic")
    off = len(magic)
    try:
        max_bytes, max_age_s, count = _SPILL_HEADER.unpack_from(data, off)
        off += _SPILL_HEADER.size
        entries = []
        for _ in range(count):
            if entry_struct is _SPILL_ENTRY:
                spilled_at, epoch, seq, blob_len = entry_struct.unpack_from(
                    data, off)
            else:
                spilled_at, blob_len = entry_struct.unpack_from(data, off)
                epoch = seq = _NO_ENVELOPE
            off += entry_struct.size
            blob = data[off:off + blob_len]
            if len(blob) != blob_len:
                raise ValueError("truncated spill entry")
            off += blob_len
            m = mpb.Metric.FromString(blob)
            entries.append((spilled_at, m, epoch, seq) if with_envelope
                           else (spilled_at, m))
    except struct.error as e:
        raise ValueError(f"truncated spill buffer: {e}")
    return entries, (max_bytes, max_age_s)


class ForwardSpillBuffer:
    """Holds forwardable metricpb.Metric payloads across failed intervals.

    Thread-safe: forwards run on fire-and-forget aux threads and a slow
    failing forward can overlap the next interval's.
    """

    def __init__(self, max_bytes: int, max_age_s: float = 60.0,
                 clock: Callable[[], float] = time.time):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be > 0")
        self.max_bytes = int(max_bytes)
        self.max_age_s = float(max_age_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: deque = deque()   # legacy: (spilled_at, metric, nbytes)
        # ack-gated: [epoch, seq, staged_at, [(spilled_at, m, nb)...], nbytes]
        # oldest (lowest seq) first — retries replay in stamping order
        self._units: deque = deque()
        self._bytes = 0
        self.spilled_total = 0       # metrics ever spilled/staged
        self.dropped_capacity = 0    # metrics evicted by the byte cap
        self.dropped_age = 0         # metrics expired by max_age_s

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def dropped_total(self) -> int:
        with self._lock:
            return self.dropped_capacity + self.dropped_age

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries) + sum(len(u[3]) for u in self._units)

    # -- legacy merge-on-retry path (dedup off) ------------------------------
    def add(self, metrics: List, now: float = None) -> None:
        """Spill a failed forward's payload, stamped with the CURRENT
        clock. Evicts oldest-first when the byte cap is exceeded (a
        single over-cap payload evicts itself — the cap is a hard bound,
        not a suggestion)."""
        if not metrics:
            return
        now = self._clock() if now is None else now
        with self._lock:
            self.spilled_total += len(metrics)
            evicted = self._extend_locked(
                (now, m, m.ByteSize()) for m in metrics)
        if evicted:
            log.warning("forward spill over %d bytes: dropped %d oldest "
                        "payloads", self.max_bytes, evicted)

    def readd(self, entries: List) -> None:
        """Return previously drained (spilled_at, metric) entries after a
        re-failed send, keeping their ORIGINAL spill timestamps — so
        max_age_s bounds total staleness since the first failure, not
        time since the last retry. Re-adds are not re-counted in
        spilled_total. 4-tuple (spilled_at, metric, epoch, seq) entries
        are accepted and land as legacy entries (envelope dropped —
        re-adding is the merge-on-retry path).

        Entries land at the LEFT of the deque: drained entries are older
        than anything add() appended while the retry was in flight, and
        the deque must stay oldest-first or the byte-cap eviction (and a
        later drain()'s pair ordering) would drop fresh payloads while
        keeping stale ones."""
        if not entries:
            return
        with self._lock:
            for entry in reversed(entries):
                ts, m = entry[0], entry[1]
                nb = m.ByteSize()
                self._entries.appendleft((ts, m, nb))
                self._bytes += nb
            evicted = self._evict_locked()
        if evicted:
            log.warning("forward spill over %d bytes: dropped %d oldest "
                        "payloads", self.max_bytes, evicted)

    def _extend_locked(self, triples) -> int:
        """Append (spilled_at, metric, nbytes) triples and enforce the
        byte cap; returns the evicted count. Caller holds the lock and
        must keep appends time-ordered (newest at the right)."""
        for t in triples:
            self._entries.append(t)
            self._bytes += t[2]
        return self._evict_locked()

    def _evict_locked(self) -> int:
        """Enforce the byte cap, oldest first: legacy entries (which are
        never older than an ack-gated unit in the same buffer only by
        accident — both queues evict from their own left), then whole
        units. A unit evicts atomically: re-sending a subset under its
        original seq could lose the rest to the receiver's window."""
        evicted = 0
        while self._bytes > self.max_bytes and self._entries:
            _, _, nb = self._entries.popleft()
            self._bytes -= nb
            self.dropped_capacity += 1
            evicted += 1
        while self._bytes > self.max_bytes and self._units:
            unit = self._units.popleft()
            self._bytes -= unit[4]
            self.dropped_capacity += len(unit[3])
            evicted += len(unit[3])
        return evicted

    def drain(self, now: float = None) -> List:
        """Take everything still fresh as (spilled_at, metric) pairs for
        merging into the next forward batch; expired payloads are dropped
        and counted. The buffer is emptied either way — a re-failed send
        returns the pairs via readd(), preserving their timestamps.
        Staged units are drained too (their envelopes discarded): this
        only happens when a dedup-off server restores a checkpoint
        written by a dedup-on one, where merge-on-retry is the best the
        configuration can do."""
        now = self._clock() if now is None else now
        with self._lock:
            out, expired = [], 0
            for spilled_at, m, _nb in self._entries:
                if now - spilled_at > self.max_age_s:
                    expired += 1
                else:
                    out.append((spilled_at, m))
            for unit in self._units:
                for spilled_at, m, _nb in unit[3]:
                    if now - spilled_at > self.max_age_s:
                        expired += 1
                    else:
                        out.append((spilled_at, m))
            self._entries.clear()
            self._units.clear()
            self._bytes = 0
            self.dropped_age += expired
        if expired:
            log.warning("forward spill: dropped %d payloads older than "
                        "%.0fs", expired, self.max_age_s)
        return out

    # -- ack-gated exactly-once path (forward_dedup_window > 0) --------------
    def take_legacy(self, now: float = None) -> List:
        """Remove and return the fresh LEGACY (unenveloped) entries as
        (spilled_at, metric) pairs; expired ones are dropped and counted.
        The exactly-once sender folds these — restored from a
        pre-upgrade checkpoint, or left by a dedup-off run — into its
        next stamped unit so they forward under an envelope."""
        now = self._clock() if now is None else now
        with self._lock:
            out, expired = [], 0
            for spilled_at, m, nb in self._entries:
                self._bytes -= nb
                if now - spilled_at > self.max_age_s:
                    expired += 1
                else:
                    out.append((spilled_at, m))
            self._entries.clear()
            self.dropped_age += expired
        if expired:
            log.warning("forward spill: dropped %d payloads older than "
                        "%.0fs", expired, self.max_age_s)
        return out

    def add_unit(self, metrics: List, epoch: int, seq: int,
                 now: float = None) -> None:
        """Stage an interval's export as an immutable unit under its
        envelope BEFORE the send attempt. The unit leaves the buffer
        only via ack(), the byte cap, or max_age_s expiry — never
        because a send merely returned."""
        if not metrics:
            return
        now = self._clock() if now is None else now
        entries = [(now, m, m.ByteSize()) for m in metrics]
        nbytes = sum(nb for _, _, nb in entries)
        with self._lock:
            self.spilled_total += len(entries)
            self._units.append([int(epoch), int(seq), now, entries, nbytes])
            self._bytes += nbytes
            evicted = self._evict_locked()
        if evicted:
            log.warning("forward spill over %d bytes: dropped %d oldest "
                        "payloads", self.max_bytes, evicted)

    def pending_units(self, now: float = None) -> List[SpillUnit]:
        """Snapshot (NOT drain) the staged units oldest-first for a send
        pass; units older than max_age_s are dropped and counted first.
        Metrics lists are shared, not copied — callers must not mutate."""
        now = self._clock() if now is None else now
        with self._lock:
            expired = 0
            while self._units and now - self._units[0][2] > self.max_age_s:
                unit = self._units.popleft()
                self._bytes -= unit[4]
                self.dropped_age += len(unit[3])
                expired += len(unit[3])
            out = [SpillUnit(u[0], u[1], u[2], [m for _, m, _ in u[3]])
                   for u in self._units]
        if expired:
            log.warning("forward spill: dropped %d payloads older than "
                        "%.0fs", expired, self.max_age_s)
        return out

    def ack(self, epoch: int, seq: int) -> bool:
        """The receiving tier acknowledged (epoch, seq): evict the unit.
        Idempotent — a duplicate ack (or an ack for an already-expired
        unit) is a no-op returning False."""
        with self._lock:
            for i, unit in enumerate(self._units):
                if unit[0] == epoch and unit[1] == seq:
                    del self._units[i]
                    self._bytes -= unit[4]
                    return True
        return False

    # -- persistence (checkpoints; README §Durability) ----------------------
    def to_bytes(self) -> bytes:
        """Serialize contents + caps, preserving every entry's original
        spill stamp and (for staged units) envelope. Point-in-time
        consistent (one lock hold)."""
        with self._lock:
            rows = [(ts, m, _NO_ENVELOPE, _NO_ENVELOPE)
                    for ts, m, _nb in self._entries]
            for epoch, seq, _staged, entries, _nb in self._units:
                rows.extend((ts, m, epoch, seq) for ts, m, _ in entries)
        parts = [_SPILL_MAGIC,
                 _SPILL_HEADER.pack(self.max_bytes, self.max_age_s,
                                    len(rows))]
        for spilled_at, m, epoch, seq in rows:
            blob = m.SerializeToString()
            parts.append(_SPILL_ENTRY.pack(spilled_at, epoch, seq,
                                           len(blob)))
            parts.append(blob)
        return b"".join(parts)

    def restore_entries(self, entries: List) -> None:
        """Re-enter parse_spill_bytes(with_envelope=True) 4-tuples after
        a restart: enveloped rows regroup into their original units
        (original stamps AND seqs — the replay is what the receiver's
        dedup window suppresses), unenveloped rows land as legacy
        entries. Not re-counted in spilled_total."""
        legacy = [e for e in entries if len(e) < 4 or e[2] == _NO_ENVELOPE]
        enveloped = [e for e in entries if len(e) >= 4 and e[2] != _NO_ENVELOPE]
        if enveloped:
            groups: "dict[tuple, list]" = {}
            for ts, m, epoch, seq in enveloped:
                groups.setdefault((epoch, seq), []).append((ts, m, m.ByteSize()))
            with self._lock:
                for (epoch, seq), rows in sorted(groups.items()):
                    nbytes = sum(nb for _, _, nb in rows)
                    staged_at = min(ts for ts, _, _ in rows)
                    self._units.append([epoch, seq, staged_at, rows, nbytes])
                    self._bytes += nbytes
                evicted = self._evict_locked()
            if evicted:
                log.warning("forward spill over %d bytes: dropped %d oldest "
                            "payloads on restore", self.max_bytes, evicted)
        if legacy:
            self.readd(legacy)

    @classmethod
    def from_bytes(cls, data: bytes,
                   clock: Callable[[], float] = time.time
                   ) -> "ForwardSpillBuffer":
        """Rebuild a buffer with the SERIALIZED caps and stamps. Entries
        already past max_age_s still re-enter; the next drain() (or
        pending_units()) expires them into dropped_age, so the drop
        accounting that would have happened without the restart still
        happens."""
        entries, (max_bytes, max_age_s) = parse_spill_bytes(
            data, with_envelope=True)
        buf = cls(max_bytes, max_age_s, clock=clock)
        buf.restore_entries(entries)
        return buf
