"""veneur_tpu — a TPU-native metrics-aggregation framework.

A from-scratch re-design of the capabilities of stripe/veneur (the reference,
a distributed DogStatsD/SSF aggregation pipeline, pure Go) as a JAX/XLA/Pallas
framework:

- the per-key sampler state (counters, gauges, sets/HLL, timers/histograms/
  t-digest — reference ``samplers/samplers.go``) lives in fixed-capacity,
  hash-addressed device arrays (:mod:`veneur_tpu.aggregation.table`),
- ingest is a jitted batched scatter step (reference ``worker.go:344``
  ``Worker.ProcessMetric``) built on the TPU-friendly
  sort → segment-reduce → unique-scatter pattern,
- the two-tier local→global aggregation (reference ``flusher.go`` /
  ``importsrv/``) becomes XLA collectives over a device mesh
  (:mod:`veneur_tpu.parallel`),
- sketches (t-digest, HyperLogLog, count-min) are batched fixed-shape JAX
  kernels (:mod:`veneur_tpu.ops`).

The host pipeline (listeners, parsers, sinks, config, CLIs) mirrors the
reference's behavior with Python/C++ where the reference used Go.
"""

__version__ = "0.1.0"
