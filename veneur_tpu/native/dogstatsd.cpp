// Native DogStatsD ingest engine: parse + key table + batch staging.
//
// Replaces the Python host hot loop (samplers/parser.py parse_metric +
// aggregation/host.py KeyTable/Batcher) below the UDP socket with one C++
// pass per packet buffer. Semantics are bit-identical to the Python parser
// (itself mirroring reference samplers/parser.go:298 ParseMetric):
//   - `name:value|type[|@rate][|#tags]`, sections at most once
//   - type by first byte: c/g/d/h/m(s)/s (parser.go:331-344)
//   - tags sorted then joined with ","; first sorted tag with prefix
//     veneurlocalonly/veneurglobalonly stripped into the scope
//     (parser.go:397-407)
//   - 32-bit FNV-1a digest over name+type+joined-tags = shard key
//   - set members hashed MetroHash64 seed 1337 (utils/hashing.py
//     hll_reg_rho; the reference sketch's member hash)
//   - slot = shard*per_shard + next_free[shard], shard = digest % n_shards
//     (aggregation/host.py KeyTable.slot_for / _KindTable.alloc)
//
// Events (_e{) and service checks (_sc) are rare; they are handed back to
// Python verbatim (vt_next_special).
//
// Exposed as a C ABI for ctypes; see veneur_tpu/native/__init__.py.

#include <algorithm>
#include <array>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <pthread.h>
#include <sched.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr uint32_t FNV32_OFFSET = 0x811C9DC5u;
constexpr uint32_t FNV32_PRIME = 0x01000193u;
constexpr uint64_t FNV64_OFFSET = 0xCBF29CE484222325ull;
constexpr uint64_t FNV64_PRIME = 0x100000001B3ull;

inline uint32_t fnv32(const char* p, size_t n, uint32_t h) {
  for (size_t i = 0; i < n; i++) {
    h ^= (uint8_t)p[i];
    h *= FNV32_PRIME;
  }
  return h;
}

inline uint64_t fnv64(const char* p, size_t n) {
  uint64_t h = FNV64_OFFSET;
  for (size_t i = 0; i < n; i++) {
    h ^= (uint8_t)p[i];
    h *= FNV64_PRIME;
  }
  return h;
}

inline uint64_t rotr64(uint64_t x, int r) {
  return (x >> r) | (x << (64 - r));
}

inline uint64_t load64(const char* p) {
  uint64_t v;
  memcpy(&v, p, 8);  // little-endian host assumed (x86/arm LE)
  return v;
}
inline uint32_t load32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}
inline uint16_t load16(const char* p) {
  uint16_t v;
  memcpy(&v, p, 2);
  return v;
}

// MetroHash64 (J. Andrew Rogers, public domain), seed 1337 — the member
// hash of the reference's vendored HLL sketch; must match the Python
// utils/hashing.py metro_hash_64 bit-for-bit so both ingest paths place a
// member in the same register, and match the reference fleet for
// cross-implementation sketch unions.
inline uint64_t metro64(const char* p, size_t n, uint64_t seed = 1337) {
  const uint64_t k0 = 0xD6D018F5, k1 = 0xA2AA033B, k2 = 0x62992FC1,
                 k3 = 0x30BC5B29;
  const char* end = p + n;
  uint64_t h = (seed + k2) * k0;
  if (n >= 32) {
    uint64_t v0 = h, v1 = h, v2 = h, v3 = h;
    while (end - p >= 32) {
      v0 += load64(p) * k0; p += 8; v0 = rotr64(v0, 29) + v2;
      v1 += load64(p) * k1; p += 8; v1 = rotr64(v1, 29) + v3;
      v2 += load64(p) * k2; p += 8; v2 = rotr64(v2, 29) + v0;
      v3 += load64(p) * k3; p += 8; v3 = rotr64(v3, 29) + v1;
    }
    v2 ^= rotr64(((v0 + v3) * k0) + v1, 37) * k1;
    v3 ^= rotr64(((v1 + v2) * k1) + v0, 37) * k0;
    v0 ^= rotr64(((v0 + v2) * k0) + v3, 37) * k1;
    v1 ^= rotr64(((v1 + v3) * k1) + v2, 37) * k0;
    h += v0 ^ v1;
  }
  if (end - p >= 16) {
    uint64_t w0 = h + load64(p) * k2; p += 8; w0 = rotr64(w0, 29) * k3;
    uint64_t w1 = h + load64(p) * k2; p += 8; w1 = rotr64(w1, 29) * k3;
    w0 ^= rotr64(w0 * k0, 21) + w1;
    w1 ^= rotr64(w1 * k3, 21) + w0;
    h += w1;
  }
  if (end - p >= 8) {
    h += load64(p) * k3; p += 8;
    h ^= rotr64(h, 55) * k1;
  }
  if (end - p >= 4) {
    h += (uint64_t)load32(p) * k3; p += 4;
    h ^= rotr64(h, 26) * k1;
  }
  if (end - p >= 2) {
    h += (uint64_t)load16(p) * k3; p += 2;
    h ^= rotr64(h, 48) * k1;
  }
  if (end - p >= 1) {
    h += (uint64_t)(uint8_t)(*p) * k3;
    h ^= rotr64(h, 37) * k1;
  }
  h ^= rotr64(h, 28);
  h *= k0;
  h ^= rotr64(h, 29);
  return h;
}

enum Kind { K_COUNTER = 0, K_GAUGE = 1, K_HISTO = 2, K_SET = 3, K_TIMER = 4 };
enum Scope { S_MIXED = 0, S_LOCAL = 1, S_GLOBAL = 2 };

// ---------------------------------------------------------------------------
// Multi-tenant identity + fairness (reliability/tenancy.py mirror).
// One TenantTable lives on the MASTER parser and is shared by every ring:
// tenant ids are interned once, entry pointers are stable for the process
// lifetime (vector of unique_ptr, grown under mu), and the weighted token
// buckets are host-wide — SO_REUSEPORT flow hashing can concentrate one
// tenant on one ring, so splitting a tenant's budget per ring would let
// placement, not weight, decide its fair share.

constexpr size_t kTenantValueMax = 64;   // oversized values -> default
constexpr int32_t kMaxTenants = 4096;    // intern cap; overflow -> default

// strict UTF-8 validation: an invalid tenant value maps to the default
// tenant instead of interning arbitrary bytes as an identity
inline bool utf8_valid(const char* p, size_t n) {
  size_t i = 0;
  while (i < n) {
    uint8_t c = (uint8_t)p[i];
    size_t need;
    if (c < 0x80) { i++; continue; }
    if ((c & 0xE0) == 0xC0) { need = 1; if (c < 0xC2) return false; }
    else if ((c & 0xF0) == 0xE0) need = 2;
    else if ((c & 0xF8) == 0xF0) { need = 3; if (c > 0xF4) return false; }
    else return false;
    if (i + need >= n) return false;
    for (size_t k = 1; k <= need; k++)
      if (((uint8_t)p[i + k] & 0xC0) != 0x80) return false;
    i += need + 1;
  }
  return true;
}

struct TenantEntry {
  std::string name;
  double weight = 1.0;           // guarded by TenantTable.mu
  // weighted token bucket (guarded by TenantTable.mu)
  double tokens = 0.0;
  std::chrono::steady_clock::time_point last;
  bool primed = false;
  // tag-explosion detector: additive-error distinct-key estimate. The
  // per-window count is exact (every new-key alloc bumps it); the
  // carried estimate decays geometrically at each flush reset, so the
  // additive error vs the true live-key count is bounded by the decay
  // tail — the cheap end of the 2004.10332 counter family.
  std::atomic<uint64_t> window_keys{0};
  std::atomic<double> key_est{0.0};
  std::atomic<bool> demoted{false};
};

struct TenantTable {
  std::mutex mu;                       // entries growth, by_name, buckets
  std::atomic<bool> enabled{false};
  std::string tag;                     // e.g. "tenant:"; set once, pre-rings
  std::atomic<double> base_rate{0.0};  // admitted/s per unit weight
  double burst_mult = 2.0;             // guarded by mu
  uint32_t q_max_keys = 0;             // 0 = quarantine off; set once
  double q_decay = 0.5;                // guarded by mu
  double q_readmit_frac = 0.5;         // guarded by mu
  std::vector<std::unique_ptr<TenantEntry>> entries;  // id -> entry
  std::unordered_map<std::string, int32_t> by_name;
  std::vector<int32_t> fresh;          // interned since the last name drain
  TenantEntry* dflt = nullptr;         // entries[0], stable once created
};

// Locate a well-formed `tag` value inside the raw datagram's tag section
// (the occurrence must follow '#' or ','; first occurrence wins, so
// duplicate tags resolve deterministically). Returns false — mapping the
// datagram to the default tenant — for missing tags, tags split across a
// truncated datagram, and empty/oversized/invalid-UTF-8 values: every
// anomaly is still admitted-and-accounted, never silently dropped.
inline bool tenant_extract(const std::string& tag, const char* p, size_t n,
                           const char** v, size_t* vlen) {
  if (tag.empty() || n <= tag.size()) return false;
  const char* cur = p;
  size_t rem = n;
  while (rem >= tag.size()) {
    const char* hit =
        (const char*)memmem(cur, rem, tag.data(), tag.size());
    if (!hit) return false;
    if (hit > p && (hit[-1] == '#' || hit[-1] == ',')) {
      const char* val = hit + tag.size();
      size_t vmax = (size_t)(p + n - val);
      size_t len = 0;
      while (len < vmax && val[len] != ',' && val[len] != '|' &&
             val[len] != '\n')
        len++;
      if (len == 0 || len > kTenantValueMax || !utf8_valid(val, len))
        return false;
      *v = val;
      *vlen = len;
      return true;
    }
    cur = hit + 1;
    rem = (size_t)(p + n - cur);
  }
  return false;
}

// Intern (or look up) a tenant name; *te gets the stable entry pointer.
// At the kMaxTenants cap new names collapse onto the default tenant —
// identity cardinality must stay bounded even under a hostile name flood.
inline int32_t tenant_intern(TenantTable& tt, const char* name, size_t n,
                             TenantEntry** te) {
  std::lock_guard<std::mutex> lk(tt.mu);
  std::string key(name, n);
  auto it = tt.by_name.find(key);
  if (it != tt.by_name.end()) {
    *te = tt.entries[it->second].get();
    return it->second;
  }
  if ((int32_t)tt.entries.size() >= kMaxTenants) {
    *te = tt.dflt;
    return 0;
  }
  int32_t id = (int32_t)tt.entries.size();
  auto e = std::make_unique<TenantEntry>();
  e->name = key;
  *te = e.get();
  tt.entries.push_back(std::move(e));
  tt.by_name.emplace(std::move(key), id);
  tt.fresh.push_back(id);
  return id;
}

// TokenBucket.allow with rate = base_rate * weight (reliability/
// tenancy.py TenantFairness.allow). Host-wide: one bucket per tenant
// regardless of which ring the datagram landed on.
inline bool tenant_allow(TenantTable& tt, TenantEntry& e,
                         std::chrono::steady_clock::time_point now) {
  std::lock_guard<std::mutex> lk(tt.mu);
  double rate = tt.base_rate.load(std::memory_order_relaxed) * e.weight;
  if (rate <= 0.0) return true;
  double burst = rate * tt.burst_mult;
  if (burst < 1.0) burst = 1.0;
  if (!e.primed) {
    e.tokens = burst;
    e.last = now;
    e.primed = true;
  }
  double dt = std::chrono::duration<double>(now - e.last).count();
  e.last = now;
  double t = e.tokens + dt * rate;
  if (t > burst) t = burst;
  if (t >= 1.0) {
    e.tokens = t - 1.0;
    return true;
  }
  e.tokens = t;
  return false;
}

struct KindTable {
  uint32_t capacity = 0;
  uint32_t n_shards = 1;
  uint32_t per_shard = 0;
  std::unordered_map<std::string, int32_t> by_key;
  std::vector<uint32_t> next_free;
  uint64_t dropped = 0;

  void init(uint32_t cap, uint32_t shards) {
    capacity = cap;
    n_shards = shards;
    per_shard = cap / shards;
    next_free.assign(shards, 0);
  }
  void reset() {
    by_key.clear();
    next_free.assign(n_shards, 0);
  }
};

// serialized record of a newly-allocated slot, drained by Python for
// flush-time labeling (SlotMeta)
struct NewKey {
  uint8_t kind;
  int32_t slot;
  uint8_t scope;
  uint8_t imported;  // slot first created by the import path
  std::string name;
  std::string joined_tags;
};

// per-imported-histogram scalar stats (min/max/reciprocal-sum
// correction), drained by Python into the histo_stat batch lane
struct ImportStat {
  int32_t slot;
  float mn, mx, recip_corr;
};

struct Parser {
  // tables: counter, gauge, set, histo (histogram+timer share, key
  // prefixed with the kind byte like Python's ("timer", name, tags) keys)
  KindTable counters, gauges, sets, histos;
  int hll_precision = 14;
  // staged shard-map change (live resharding): set under a unique
  // key_mu lock by vt_shard_map_set, applied by vt_reset at the next
  // buffer-swap boundary so no packed batch ever straddles two maps.
  // 0 = nothing staged.
  uint32_t pending_shards = 0;
  // staged per-kind capacity change (live key-table growth,
  // veneur_tpu/tables/growth.py): counter/gauge/set/histo, 0 = nothing
  // staged. Same discipline as pending_shards — set under key_mu by
  // vt_capacity_set, applied by vt_reset while the tables are empty, so
  // no slot ever straddles two capacities and the per-shard slot
  // rebase (slot = shard * per_shard + local) changes only between
  // intervals.
  uint32_t pending_caps[4] = {0, 0, 0, 0};

  // Multi-ring sharing: ring parsers keep their own staging lanes and
  // scratch but route every key-table/new-key/special access to the
  // master parser so all rings share ONE slot space. Steady-state lookups
  // are served from a ring-local replica cache with no lock at all; the
  // shared table is touched only on cache miss (shared lock) and on
  // first-allocation (unique lock, once per key per flush interval).
  Parser* master = nullptr;
  std::shared_mutex key_mu;                          // tables + new_keys
  std::mutex specials_mu;                            // specials deque
  std::unordered_map<std::string, int32_t> local_cache;

  Parser& rt() { return master ? *master : *this; }

  // Multi-tenant identity (master only; rings route via rt()). The
  // cur_* fields are per-parser parse context: set before each vt_feed
  // (by the ring worker under stage_mu, or by vt_set_tenant on the
  // Python feed path) and read only inside parse_line/slot_for.
  std::unique_ptr<TenantTable> tenants;
  int32_t cur_tenant = 0;
  TenantEntry* cur_entry = nullptr;
  bool cur_demoted = false;
  // demoted-row accounting per tenant id; written during parse (under
  // stage_mu in the ring engine, under the GIL on the Python feed
  // path), drained by vrm_tenant_counters / vt_tenant_rows
  std::unordered_map<int32_t, uint64_t> demoted_rows;

  // staging (fixed batch capacities; slot sentinel fill done by Python)
  uint32_t bc, bg, bs, bh;
  std::vector<int32_t> c_slot;  std::vector<float> c_inc;
  std::vector<int32_t> g_slot;  std::vector<float> g_val;
  std::vector<int32_t> s_slot;  std::vector<int32_t> s_reg;
  std::vector<uint8_t> s_rho;
  std::vector<int32_t> h_slot;  std::vector<float> h_val;
  std::vector<float> h_wt;
  uint32_t nc = 0, ng = 0, ns = 0, nh = 0;

  std::vector<NewKey> new_keys;
  std::deque<std::string> specials;  // _e{ / _sc lines for Python

  // import path (vi_import): per-histogram stats + alloc marking
  std::vector<ImportStat> import_stats;
  bool alloc_imported = false;

  // atomics: ring workers bump these off-GIL while vt_stats/vrm_stats
  // snapshot from the pipeline thread
  std::atomic<uint64_t> processed{0};
  std::atomic<uint64_t> parse_errors{0};

  // emit_packed timing: atomics because the poll thread snapshots
  // (vr_stats) while the pipeline thread emits; relaxed is enough for a
  // monotonic telemetry pair read independently.
  std::atomic<uint64_t> emit_packed_calls{0};
  std::atomic<uint64_t> emit_packed_ns{0};

  // scratch
  std::vector<std::pair<const char*, size_t>> tag_views;
  std::string keybuf, joined;
  // shard counting-sort scratch (vt_emit_sharded); grown once, reused
  std::vector<uint32_t> ss_cnt, ss_pos, ss_order;

  void init(uint32_t cc, uint32_t gc, uint32_t sc, uint32_t hc,
            uint32_t shards, int precision, uint32_t bc_, uint32_t bg_,
            uint32_t bs_, uint32_t bh_) {
    counters.init(cc, shards);
    gauges.init(gc, shards);
    sets.init(sc, shards);
    histos.init(hc, shards);
    hll_precision = precision;
    bc = bc_; bg = bg_; bs = bs_; bh = bh_;
    c_slot.resize(bc); c_inc.resize(bc);
    g_slot.resize(bg); g_val.resize(bg);
    s_slot.resize(bs); s_reg.resize(bs); s_rho.resize(bs);
    h_slot.resize(bh); h_val.resize(bh); h_wt.resize(bh);
  }

  bool any_full() const {
    return nc >= bc || ng >= bg || ns >= bs || nh >= bh;
  }

  // `t` must be a table of rt() — callers route through rt().counters etc.
  int32_t slot_for(KindTable& t, uint8_t kind, uint8_t scope,
                   const char* name, size_t name_len, uint32_t digest) {
    // key = kind byte + name + '\x1f' + joined tags (joined is in `joined`)
    keybuf.clear();
    keybuf.push_back((char)kind);
    keybuf.append(name, name_len);
    keybuf.push_back('\x1f');
    keybuf.append(joined);
    if (master) {
      // lock-free hot path: the ring-local replica (slots are stable
      // within a flush interval; vrm_reset clears these under quiesce)
      auto cit = local_cache.find(keybuf);
      if (cit != local_cache.end()) return cit->second;
    }
    Parser& m = rt();
    {
      std::shared_lock<std::shared_mutex> lk(m.key_mu);
      auto it = t.by_key.find(keybuf);
      if (it != t.by_key.end()) {
        int32_t slot = it->second;
        lk.unlock();
        if (master) local_cache.emplace(keybuf, slot);
        return slot;
      }
    }
    std::unique_lock<std::shared_mutex> lk(m.key_mu);
    auto it = t.by_key.find(keybuf);
    if (it == t.by_key.end()) {
      uint32_t shard = digest % t.n_shards;
      uint32_t nxt = t.next_free[shard];
      if (nxt >= t.per_shard) {
        t.dropped++;
        return -1;
      }
      t.next_free[shard] = nxt + 1;
      int32_t slot = (int32_t)(shard * t.per_shard + nxt);
      it = t.by_key.emplace(keybuf, slot).first;
      m.new_keys.push_back(NewKey{kind, slot, scope,
                                  (uint8_t)(alloc_imported ? 1 : 0),
                                  std::string(name, name_len), joined});
      // tag-explosion detector: every distinct-key allocation charges
      // the owning tenant's window counter; crossing the budget demotes
      // it (subsequent datagrams collapse onto rollup keys instead of
      // evicting healthy tenants' hot keys out of shard capacity)
      if (cur_entry) {
        uint64_t w =
            cur_entry->window_keys.fetch_add(1, std::memory_order_relaxed)
            + 1;
        TenantTable* tt = m.tenants.get();
        if (tt && tt->q_max_keys &&
            !cur_entry->demoted.load(std::memory_order_relaxed) &&
            cur_entry->key_est.load(std::memory_order_relaxed) +
                    (double)w > (double)tt->q_max_keys)
          cur_entry->demoted.store(true, std::memory_order_relaxed);
      }
    }
    int32_t slot = it->second;
    lk.unlock();
    if (master) local_cache.emplace(keybuf, slot);
    return slot;
  }

  // strict float parse: Go strconv.ParseFloat-alike (no surrounding
  // whitespace, full consumption, finite)
  static bool parse_value(const char* p, size_t n, double* out) {
    if (n == 0) return false;
    if (isspace((unsigned char)p[0])) return false;
    char buf[64];
    if (n >= sizeof(buf)) return false;
    // strtod accepts C99 hex floats; Python float() / the wire format do not
    if (memchr(p, 'x', n) || memchr(p, 'X', n)) return false;
    memcpy(buf, p, n);
    buf[n] = 0;
    char* end = nullptr;
    double v = strtod(buf, &end);
    if (end != buf + n) return false;
    if (!std::isfinite(v)) return false;
    return *out = v, true;
  }

  // returns 0 ok, 1 parse error, 2 special (event/service check), 3 full
  int parse_line(const char* line, size_t len) {
    if (len == 0) return 0;
    if (len >= 3 && line[0] == '_' &&
        ((line[1] == 'e' && line[2] == '{') ||
         (line[1] == 's' && line[2] == 'c'))) {
      Parser& m = rt();
      std::lock_guard<std::mutex> lk(m.specials_mu);
      m.specials.emplace_back(line, len);
      return 2;
    }
    // split into pipe chunks
    const char* colon = (const char*)memchr(line, ':', len);
    const char* pipe1 = (const char*)memchr(line, '|', len);
    if (!colon || !pipe1 || colon > pipe1) return 1;
    const char* name = line;
    size_t name_len = colon - line;
    if (name_len == 0) return 1;
    const char* value = colon + 1;
    size_t value_len = pipe1 - value;

    const char* rest = pipe1 + 1;
    size_t rest_len = len - (rest - line);
    // type chunk
    const char* pipe2 = (const char*)memchr(rest, '|', rest_len);
    size_t type_len = pipe2 ? (size_t)(pipe2 - rest) : rest_len;
    if (type_len == 0) return 1;

    uint8_t kind;
    const char* kind_str;
    size_t kind_str_len;
    switch (rest[0]) {
      case 'c': kind = K_COUNTER; kind_str = "counter"; kind_str_len = 7; break;
      case 'g': kind = K_GAUGE;   kind_str = "gauge";   kind_str_len = 5; break;
      case 'd':
      case 'h': kind = K_HISTO;   kind_str = "histogram"; kind_str_len = 9; break;
      case 'm': kind = K_TIMER;   kind_str = "timer";   kind_str_len = 5; break;
      case 's': kind = K_SET;     kind_str = "set";     kind_str_len = 3; break;
      default: return 1;
    }

    uint32_t h = fnv32(name, name_len, FNV32_OFFSET);
    h = fnv32(kind_str, kind_str_len, h);

    double value_f = 0;
    if (kind != K_SET) {
      // reject '_' (Python/Go reject digit separators, strtod would too
      // via full-consumption, but be explicit for e.g. "1_0")
      if (!parse_value(value, value_len, &value_f)) return 1;
    }

    // optional sections
    double rate = 1.0;
    bool found_rate = false, found_tags = false;
    uint8_t scope = S_MIXED;
    joined.clear();
    const char* p = pipe2 ? pipe2 : rest + rest_len;
    while (p < line + len) {
      p++;  // skip '|'
      size_t remain = len - (p - line);
      const char* next = (const char*)memchr(p, '|', remain);
      size_t clen = next ? (size_t)(next - p) : remain;
      if (clen == 0) return 1;
      if (p[0] == '@') {
        if (found_rate) return 1;
        double r;
        if (!parse_value(p + 1, clen - 1, &r)) return 1;
        if (r <= 0.0 || r > 1.0) return 1;
        rate = r;
        found_rate = true;
      } else if (p[0] == '#') {
        if (found_tags) return 1;
        found_tags = true;
        // split tags on ',', sort, strip first magic, join
        tag_views.clear();
        const char* t = p + 1;
        const char* tag_end = p + clen;
        while (t <= tag_end) {
          const char* comma =
              (const char*)memchr(t, ',', tag_end - t);
          size_t tl = comma ? (size_t)(comma - t) : (size_t)(tag_end - t);
          tag_views.emplace_back(t, tl);
          if (!comma) break;
          t = comma + 1;
        }
        std::sort(tag_views.begin(), tag_views.end(),
                  [](const auto& a, const auto& b) {
                    int c = memcmp(a.first, b.first,
                                   std::min(a.second, b.second));
                    if (c != 0) return c < 0;
                    return a.second < b.second;
                  });
        // first sorted tag with a magic prefix is stripped into the scope
        static const char LOCALONLY[] = "veneurlocalonly";
        static const char GLOBALONLY[] = "veneurglobalonly";
        size_t strip = SIZE_MAX;
        for (size_t i = 0; i < tag_views.size(); i++) {
          const auto& tv = tag_views[i];
          if (tv.second >= 15 && memcmp(tv.first, LOCALONLY, 15) == 0) {
            scope = S_LOCAL;
            strip = i;
            break;
          }
          if (tv.second >= 16 && memcmp(tv.first, GLOBALONLY, 16) == 0) {
            scope = S_GLOBAL;
            strip = i;
            break;
          }
        }
        bool first = true;
        for (size_t i = 0; i < tag_views.size(); i++) {
          if (i == strip) continue;
          if (!first) joined.push_back(',');
          joined.append(tag_views[i].first, tag_views[i].second);
          first = false;
        }
        h = fnv32(joined.data(), joined.size(), h);
      } else {
        return 1;
      }
      if (!next) break;
      p = next;
    }
    if (!found_tags) joined.clear();

    // quarantine demotion: a demoted tenant's rows collapse onto ONE
    // rollup key per kind — name, tags, and route digest all rewritten
    // so the slot space this tenant can touch is bounded while its
    // traffic stays measured (demoted_rows is the exact row count)
    if (cur_demoted && cur_entry) {
      static const char kRollup[] = "veneur.tenant.rollup";
      name = kRollup;
      name_len = sizeof(kRollup) - 1;
      scope = S_MIXED;
      joined.clear();
      TenantTable* tt = rt().tenants.get();
      if (tt) joined.append(tt->tag);
      joined.append(cur_entry->name);
      h = fnv32(name, name_len, FNV32_OFFSET);
      h = fnv32(kind_str, kind_str_len, h);
      h = fnv32(joined.data(), joined.size(), h);
      demoted_rows[cur_tenant]++;
    }

    switch (kind) {
      case K_COUNTER: {
        int32_t slot = slot_for(rt().counters, kind, scope, name, name_len, h);
        if (slot < 0) return 0;
        c_slot[nc] = slot;
        c_inc[nc] = (float)(value_f * (1.0 / rate));
        nc++;
        break;
      }
      case K_GAUGE: {
        int32_t slot = slot_for(rt().gauges, kind, scope, name, name_len, h);
        if (slot < 0) return 0;
        g_slot[ng] = slot;
        g_val[ng] = (float)value_f;
        ng++;
        break;
      }
      case K_SET: {
        int32_t slot = slot_for(rt().sets, kind, scope, name, name_len, h);
        if (slot < 0) return 0;
        uint64_t mh = metro64(value, value_len);
        uint32_t reg = (uint32_t)(mh >> (64 - hll_precision));
        uint64_t restbits = mh << hll_precision;
        int rho;
        if (restbits == 0) {
          rho = 64 - hll_precision + 1;
        } else {
          int lz = __builtin_clzll(restbits);
          rho = std::min(lz, 64 - hll_precision) + 1;
        }
        s_slot[ns] = slot;
        s_reg[ns] = (int32_t)reg;
        s_rho[ns] = (uint8_t)rho;
        ns++;
        break;
      }
      case K_HISTO:
      case K_TIMER: {
        int32_t slot = slot_for(rt().histos, kind, scope, name, name_len, h);
        if (slot < 0) return 0;
        h_slot[nh] = slot;
        h_val[nh] = (float)value_f;
        h_wt[nh] = (float)(1.0 / rate);
        nh++;
        break;
      }
    }
    processed++;
    return 0;
  }
};

}  // namespace

extern "C" {

void* vt_new(uint32_t counter_cap, uint32_t gauge_cap, uint32_t set_cap,
             uint32_t histo_cap, uint32_t n_shards, int hll_precision,
             uint32_t bc, uint32_t bg, uint32_t bs, uint32_t bh) {
  auto* p = new Parser();
  p->init(counter_cap, gauge_cap, set_cap, histo_cap,
          n_shards ? n_shards : 1, hll_precision, bc, bg, bs, bh);
  return p;
}

void vt_free(void* h) { delete (Parser*)h; }

// Feed a newline-separated packet buffer starting at byte `start` (so a
// caller resuming after a full-lane stop passes the same buffer back with
// the previous *consumed — no remainder slice/copy, mirroring vi_import's
// offset). Stops early if a staging area fills; *consumed reports the
// absolute offset of the first unhandled byte. Returns 1 if
// stopped-for-full, else 0.
int vt_feed(void* hp, const char* data, int len, int start, int* consumed) {
  auto* p = (Parser*)hp;
  int off = start < 0 ? 0 : start;
  while (off < len) {
    if (p->any_full()) {
      *consumed = off;
      return 1;
    }
    const char* nl = (const char*)memchr(data + off, '\n', len - off);
    int line_len = nl ? (int)(nl - (data + off)) : (len - off);
    int rc = p->parse_line(data + off, line_len);
    if (rc == 1) p->parse_errors++;
    off += line_len + (nl ? 1 : 0);
  }
  *consumed = off;
  return 0;
}

// Copy staged samples into caller-provided buffers (caller pre-fills slot
// buffers with sentinels) and reset staging. counts_out: [nc, ng, ns, nh].
void vt_emit(void* hp, int32_t* c_slot, float* c_inc, int32_t* g_slot,
             float* g_val, int32_t* s_slot, int32_t* s_reg, uint8_t* s_rho,
             int32_t* h_slot, float* h_val, float* h_wt,
             uint32_t* counts_out) {
  auto* p = (Parser*)hp;
  memcpy(c_slot, p->c_slot.data(), p->nc * sizeof(int32_t));
  memcpy(c_inc, p->c_inc.data(), p->nc * sizeof(float));
  memcpy(g_slot, p->g_slot.data(), p->ng * sizeof(int32_t));
  memcpy(g_val, p->g_val.data(), p->ng * sizeof(float));
  memcpy(s_slot, p->s_slot.data(), p->ns * sizeof(int32_t));
  memcpy(s_reg, p->s_reg.data(), p->ns * sizeof(int32_t));
  memcpy(s_rho, p->s_rho.data(), p->ns * sizeof(uint8_t));
  memcpy(h_slot, p->h_slot.data(), p->nh * sizeof(int32_t));
  memcpy(h_val, p->h_val.data(), p->nh * sizeof(float));
  memcpy(h_wt, p->h_wt.data(), p->nh * sizeof(float));
  counts_out[0] = p->nc;
  counts_out[1] = p->ng;
  counts_out[2] = p->ns;
  counts_out[3] = p->nh;
  p->nc = p->ng = p->ns = p->nh = 0;
}

// Zero-copy emit: write staged lanes straight into a caller-owned flat
// i32 buffer laid out exactly like aggregation/step.py pack_batch (word 0
// is the control word, then lanes in Batch._fields order; f32 lanes bit-
// cast, set_rho as packed bytes). `off` gives the word offset of each of
// the ten native lanes in that buffer (c_slot, c_inc, g_slot, g_val,
// s_slot, s_reg, s_rho, h_slot, h_val, h_wt — Python computes these once
// since it alone knows the status/histo_stat lane sizes interleaved
// between them; those regions are Python-initialized constants we never
// touch). Sentinel tails are maintained INCREMENTALLY: `prev` carries the
// row counts this buffer held after ITS previous emit (in/out, [4]), and
// only rows [n_new, prev_n) are re-sentineled — the rest of the buffer is
// already in the padded state Batcher.emit would have produced, so the
// flat bytes stay byte-identical to pack_batch(batch) of the old copy
// path (including harmlessly-stale value-lane rows past the counts,
// which the slot sentinels make the scatter drop — same contract as
// aggregation/host.py Batcher.emit's partial reset). counts_out: [nc,
// ng, ns, nh]; staging is reset like vt_emit.
void vt_emit_packed(void* hp, int32_t* buf, const int32_t* off,
                    uint32_t* prev, uint32_t* counts_out) {
  auto* p = (Parser*)hp;
  auto t0 = std::chrono::steady_clock::now();
  int32_t* c_slot = buf + off[0];
  float*   c_inc  = (float*)(buf + off[1]);
  int32_t* g_slot = buf + off[2];
  float*   g_val  = (float*)(buf + off[3]);
  int32_t* s_slot = buf + off[4];
  int32_t* s_reg  = buf + off[5];
  uint8_t* s_rho  = (uint8_t*)(buf + off[6]);
  int32_t* h_slot = buf + off[7];
  float*   h_val  = (float*)(buf + off[8]);
  float*   h_wt   = (float*)(buf + off[9]);
  const int32_t c_cap = (int32_t)p->counters.capacity;
  const int32_t g_cap = (int32_t)p->gauges.capacity;
  const int32_t s_cap = (int32_t)p->sets.capacity;
  const int32_t h_cap = (int32_t)p->histos.capacity;
  for (uint32_t i = p->nc; i < prev[0]; i++) { c_slot[i] = c_cap; c_inc[i] = 0.0f; }
  for (uint32_t i = p->ng; i < prev[1]; i++) g_slot[i] = g_cap;
  for (uint32_t i = p->ns; i < prev[2]; i++) s_slot[i] = s_cap;
  for (uint32_t i = p->nh; i < prev[3]; i++) { h_slot[i] = h_cap; h_wt[i] = 0.0f; }
  memcpy(c_slot, p->c_slot.data(), p->nc * sizeof(int32_t));
  memcpy(c_inc, p->c_inc.data(), p->nc * sizeof(float));
  memcpy(g_slot, p->g_slot.data(), p->ng * sizeof(int32_t));
  memcpy(g_val, p->g_val.data(), p->ng * sizeof(float));
  memcpy(s_slot, p->s_slot.data(), p->ns * sizeof(int32_t));
  memcpy(s_reg, p->s_reg.data(), p->ns * sizeof(int32_t));
  memcpy(s_rho, p->s_rho.data(), p->ns * sizeof(uint8_t));
  memcpy(h_slot, p->h_slot.data(), p->nh * sizeof(int32_t));
  memcpy(h_val, p->h_val.data(), p->nh * sizeof(float));
  memcpy(h_wt, p->h_wt.data(), p->nh * sizeof(float));
  counts_out[0] = p->nc; prev[0] = p->nc;
  counts_out[1] = p->ng; prev[1] = p->ng;
  counts_out[2] = p->ns; prev[2] = p->ns;
  counts_out[3] = p->nh; prev[3] = p->nh;
  p->nc = p->ng = p->ns = p->nh = 0;
  p->emit_packed_calls.fetch_add(1, std::memory_order_relaxed);
  p->emit_packed_ns.fetch_add(
      (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now() - t0)
          .count(),
      std::memory_order_relaxed);
}

int vt_pending(void* hp) {
  auto* p = (Parser*)hp;
  return (int)(p->nc + p->ng + p->ns + p->nh);
}

namespace {

// Stable counting sort of a staged slot lane by owner shard. Slots already
// encode the route: slot = shard*per_shard + local with shard =
// route_digest % n_shards (KindTable alloc), so grouping by slot/per_shard
// IS grouping by route_digest — no rehash. Stability preserves arrival
// order within each shard (gauge last-write-wins exactness). `bnd` gets
// n_shards+1 prefix bounds; `order` maps output row -> staged row.
void shard_order(Parser* p, const std::vector<int32_t>& sv, uint32_t n,
                 uint32_t per_shard, uint32_t n_shards, int32_t* bnd) {
  uint32_t ps = per_shard ? per_shard : 1;
  p->ss_cnt.assign(n_shards + 1, 0);
  for (uint32_t i = 0; i < n; i++) p->ss_cnt[(uint32_t)sv[i] / ps + 1]++;
  for (uint32_t s = 0; s < n_shards; s++) p->ss_cnt[s + 1] += p->ss_cnt[s];
  for (uint32_t s = 0; s <= n_shards; s++) bnd[s] = (int32_t)p->ss_cnt[s];
  p->ss_pos.assign(p->ss_cnt.begin(), p->ss_cnt.end());
  if (p->ss_order.size() < n) p->ss_order.resize(n);
  for (uint32_t i = 0; i < n; i++)
    p->ss_order[p->ss_pos[(uint32_t)sv[i] / ps]++] = i;
}

}  // namespace

// Pre-sharded emit: like vt_emit but rows arrive grouped by owner shard
// with slots rebased shard-local, plus a per-kind bounds table
// (int32[4*(n_shards+1)], kinds in counter/gauge/set/histo order) so the
// sharded aggregator feeds per-shard batchers with contiguous slices —
// no argsort, no slot subtraction, and the collective all_to_all shuffle
// sees rows already in owner order. counts_out like vt_emit; staging is
// reset.
void vt_emit_sharded(void* hp, int32_t* c_slot, float* c_inc,
                     int32_t* g_slot, float* g_val, int32_t* s_slot,
                     int32_t* s_reg, uint8_t* s_rho, int32_t* h_slot,
                     float* h_val, float* h_wt, int32_t* bounds,
                     uint32_t* counts_out) {
  auto* p = (Parser*)hp;
  const uint32_t S = p->counters.n_shards;  // all tables share n_shards
  uint32_t ps;

  ps = p->counters.per_shard ? p->counters.per_shard : 1;
  shard_order(p, p->c_slot, p->nc, ps, S, bounds);
  for (uint32_t k = 0; k < p->nc; k++) {
    uint32_t j = p->ss_order[k];
    int32_t sl = p->c_slot[j];
    c_slot[k] = sl - (int32_t)((uint32_t)sl / ps * ps);
    c_inc[k] = p->c_inc[j];
  }
  ps = p->gauges.per_shard ? p->gauges.per_shard : 1;
  shard_order(p, p->g_slot, p->ng, ps, S, bounds + (S + 1));
  for (uint32_t k = 0; k < p->ng; k++) {
    uint32_t j = p->ss_order[k];
    int32_t sl = p->g_slot[j];
    g_slot[k] = sl - (int32_t)((uint32_t)sl / ps * ps);
    g_val[k] = p->g_val[j];
  }
  ps = p->sets.per_shard ? p->sets.per_shard : 1;
  shard_order(p, p->s_slot, p->ns, ps, S, bounds + 2 * (S + 1));
  for (uint32_t k = 0; k < p->ns; k++) {
    uint32_t j = p->ss_order[k];
    int32_t sl = p->s_slot[j];
    s_slot[k] = sl - (int32_t)((uint32_t)sl / ps * ps);
    s_reg[k] = p->s_reg[j];
    s_rho[k] = p->s_rho[j];
  }
  ps = p->histos.per_shard ? p->histos.per_shard : 1;
  shard_order(p, p->h_slot, p->nh, ps, S, bounds + 3 * (S + 1));
  for (uint32_t k = 0; k < p->nh; k++) {
    uint32_t j = p->ss_order[k];
    int32_t sl = p->h_slot[j];
    h_slot[k] = sl - (int32_t)((uint32_t)sl / ps * ps);
    h_val[k] = p->h_val[j];
    h_wt[k] = p->h_wt[j];
  }
  counts_out[0] = p->nc;
  counts_out[1] = p->ng;
  counts_out[2] = p->ns;
  counts_out[3] = p->nh;
  p->nc = p->ng = p->ns = p->nh = 0;
  p->emit_packed_calls.fetch_add(1, std::memory_order_relaxed);
}

// Drain new-key records into buf as
// [u8 kind][i32 slot][u8 scope][u16 name_len][name][u16 tags_len][tags]*.
// Returns bytes written, or -needed when cap is too small (nothing
// consumed in that case).
int vt_new_keys(void* hp, char* buf, int cap) {
  auto* p = (Parser*)hp;
  std::unique_lock<std::shared_mutex> lk(p->key_mu);
  int need = 0;
  for (const auto& k : p->new_keys)
    need += 1 + 4 + 1 + 2 + (int)k.name.size() + 2 + (int)k.joined_tags.size();
  if (need > cap) return -need;
  char* w = buf;
  for (const auto& k : p->new_keys) {
    *w++ = (char)k.kind;
    memcpy(w, &k.slot, 4); w += 4;
    // scope rides the low bits; bit 7 marks import-created slots
    // (imported_only flush semantics, aggregation/host.py alloc)
    *w++ = (char)(k.scope | (k.imported ? 0x80 : 0));
    uint16_t nl = (uint16_t)k.name.size();
    memcpy(w, &nl, 2); w += 2;
    memcpy(w, k.name.data(), nl); w += nl;
    uint16_t tl = (uint16_t)k.joined_tags.size();
    memcpy(w, &tl, 2); w += 2;
    memcpy(w, k.joined_tags.data(), tl); w += tl;
  }
  p->new_keys.clear();
  return (int)(w - buf);
}

// Pop one escalated (_e{ / _sc) line; returns its length, 0 if none,
// -needed if cap too small (line stays queued).
int vt_next_special(void* hp, char* buf, int cap) {
  auto* p = (Parser*)hp;
  std::lock_guard<std::mutex> slk(p->specials_mu);
  if (p->specials.empty()) return 0;
  const std::string& s = p->specials.front();
  if ((int)s.size() > cap) return -(int)s.size();
  memcpy(buf, s.data(), s.size());
  int n = (int)s.size();
  p->specials.pop_front();
  return n;
}

// Slot allocation for Python-side callers (imports, span-extracted
// metrics) so native wire ingest and the Python paths share one slot
// space. kind: 0=counter 1=gauge 2=histogram 3=set 4=timer. *was_new is
// set to 1 when this call allocated the slot. Returns -1 when the shard
// is at capacity.
int32_t vt_slot_for(void* hp, int kind, int scope, const char* name,
                    int name_len, const char* tags, int tags_len,
                    uint32_t digest, int* was_new) {
  auto* p = (Parser*)hp;
  KindTable* t;
  switch (kind) {
    case K_COUNTER: t = &p->counters; break;
    case K_GAUGE: t = &p->gauges; break;
    case K_SET: t = &p->sets; break;
    case K_HISTO:
    case K_TIMER: t = &p->histos; break;
    default: return -1;
  }
  p->joined.assign(tags, tags_len);
  size_t before = p->new_keys.size();
  int32_t slot = p->slot_for(*t, (uint8_t)kind, (uint8_t)scope, name,
                             name_len, digest);
  *was_new = p->new_keys.size() > before ? 1 : 0;
  return slot;
}

// Flush boundary: clear key maps (state is flush-scoped, worker.go:498).
// A staged shard map (vt_shard_map_set) is applied HERE — tables are
// empty at this instant, so re-deriving per_shard/next_free under the
// new count re-keys nothing and no packed batch straddles two maps.
void vt_reset(void* hp) {
  auto* p = (Parser*)hp;
  std::unique_lock<std::shared_mutex> lk(p->key_mu);
  p->counters.reset();
  p->gauges.reset();
  p->sets.reset();
  p->histos.reset();
  p->new_keys.clear();
  if (p->pending_shards) {
    uint32_t n = p->pending_shards;
    p->pending_shards = 0;
    p->counters.init(p->counters.capacity, n);
    p->gauges.init(p->gauges.capacity, n);
    p->sets.init(p->sets.capacity, n);
    p->histos.init(p->histos.capacity, n);
  }
  // staged per-kind growth applies after any shard-map change so a
  // combined stage lands as (new shards, new caps) in one quiesce
  if (p->pending_caps[0] | p->pending_caps[1] | p->pending_caps[2] |
      p->pending_caps[3]) {
    KindTable* ts[4] = {&p->counters, &p->gauges, &p->sets, &p->histos};
    for (int i = 0; i < 4; i++) {
      if (p->pending_caps[i])
        ts[i]->init(p->pending_caps[i], ts[i]->n_shards);
      p->pending_caps[i] = 0;
    }
  }
  // tenant quarantine decay: fold this window's exact distinct-key count
  // into the carried estimate (est = est*decay + window) and re-admit a
  // demoted tenant once its estimate has decayed under the re-admission
  // fraction of the budget — the flush boundary is the detector's clock
  if (p->tenants) {
    TenantTable& tt = *p->tenants;
    std::lock_guard<std::mutex> tlk(tt.mu);
    for (auto& e : tt.entries) {
      uint64_t w = e->window_keys.exchange(0, std::memory_order_relaxed);
      double est =
          e->key_est.load(std::memory_order_relaxed) * tt.q_decay +
          (double)w;
      e->key_est.store(est, std::memory_order_relaxed);
      if (tt.q_max_keys && e->demoted.load(std::memory_order_relaxed) &&
          est <= tt.q_readmit_frac * (double)tt.q_max_keys)
        e->demoted.store(false, std::memory_order_relaxed);
    }
  }
}

// Stage a new shard count for the tables (all tables share n_shards).
// Takes effect at the next vt_reset — i.e. inside the caller's swap
// quiesce — never immediately. The swap-boundary sequencing lives in
// veneur_tpu/reshard/quiesce.py; call it from there only.
void vt_shard_map_set(void* hp, uint32_t n_shards) {
  auto* p = (Parser*)hp;
  std::unique_lock<std::shared_mutex> lk(p->key_mu);
  p->pending_shards = n_shards ? n_shards : 1;
}

// Stage new per-kind capacities (0 = keep current). Takes effect at the
// next vt_reset — i.e. inside the caller's swap quiesce — never
// immediately. The swap-boundary sequencing lives in
// veneur_tpu/tables/growth.py; call it from there only (the
// table-grow-quiesce vtlint pass enforces this).
void vt_capacity_set(void* hp, uint32_t cc, uint32_t gc, uint32_t sc,
                     uint32_t hc) {
  auto* p = (Parser*)hp;
  std::unique_lock<std::shared_mutex> lk(p->key_mu);
  p->pending_caps[0] = cc;
  p->pending_caps[1] = gc;
  p->pending_caps[2] = sc;
  p->pending_caps[3] = hc;
}

// Per-kind occupancy snapshot for the growth planner: 3 u64 per kind in
// counter/gauge/set/histo order — [allocated slots, cumulative dropped,
// capacity]. Takes key_mu shared; safe to call from the pipeline thread
// while ring workers parse.
void vt_table_stats(void* hp, uint64_t* out) {
  auto* p = (Parser*)hp;
  std::shared_lock<std::shared_mutex> lk(p->key_mu);
  const KindTable* ts[4] = {&p->counters, &p->gauges, &p->sets,
                            &p->histos};
  for (int i = 0; i < 4; i++) {
    uint64_t used = 0;
    for (uint32_t nf : ts[i]->next_free) used += nf;
    out[i * 3 + 0] = used;
    out[i * 3 + 1] = ts[i]->dropped;
    out[i * 3 + 2] = ts[i]->capacity;
  }
}

// Batch FNV-1a 64 over concatenated byte strings (offsets has n+1
// entries). Standalone — no parser handle; used for count-min member
// hashing where a per-member Python byte loop dominated the sketch path.
void vt_hash64_batch(const char* buf, const int64_t* offsets, int n,
                     uint64_t* out) {
  for (int i = 0; i < n; i++)
    out[i] = fnv64(buf + offsets[i], (size_t)(offsets[i + 1] - offsets[i]));
}

void vt_stats(void* hp, uint64_t* out) {
  auto* p = (Parser*)hp;
  out[0] = p->processed.load(std::memory_order_relaxed);
  out[1] = p->parse_errors.load(std::memory_order_relaxed);
  std::shared_lock<std::shared_mutex> lk(p->key_mu);
  out[2] = p->counters.dropped + p->gauges.dropped + p->sets.dropped +
           p->histos.dropped;
}

// The routing digest the collective key table shards on
// (collective/keytable.py route_digest): fnv1a-32 over name, then the
// lowercase kind string, then the joined tags — exactly the running `h`
// parse_line feeds slot_for, exported so a test can pin C++/Python
// byte-parity over raw (surrogateescape) corpora.
uint32_t vt_route_digest(const char* name, int name_len, const char* kind,
                         int kind_len, const char* tags, int tags_len) {
  uint32_t h = fnv32(name, (size_t)name_len, FNV32_OFFSET);
  h = fnv32(kind, (size_t)kind_len, h);
  return fnv32(tags, (size_t)tags_len, h);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native metricpb import decoder (vi_import): the global tier's gRPC
// /forwardrpc.Forward/SendMetrics payload (a serialized
// forwardrpc.MetricList — veneur_tpu/proto/{forwardrpc,metricpb,
// tdigestpb}.proto, wire-compatible with the reference's
// forwardrpc/forward.proto) decoded with a hand-rolled proto3 walker and
// staged STRAIGHT into the batch lanes, the import-path mirror of the
// wire parse path (reference importsrv/server.go:97 SendMetrics →
// worker.go:438 ImportMetricGRPC). Counters, gauges, and
// histogram/timer digests (the fleet bulk) stage natively; sets,
// valueless metrics, and any type/value oneof mismatch are handed back
// as (offset, length) spans for the Python slow path, which preserves
// the reference's per-metric error accounting exactly.

namespace {

inline bool rd_varint(const char* p, int len, int* off, uint64_t* v) {
  uint64_t out = 0;
  int shift = 0;
  while (*off < len && shift < 64) {
    uint8_t b = (uint8_t)p[(*off)++];
    out |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *v = out;
      return true;
    }
    shift += 7;
  }
  return false;
}

inline bool skip_field(const char* p, int len, int* off, int wt) {
  uint64_t v;
  switch (wt) {
    case 0: return rd_varint(p, len, off, &v);
    case 1: if (*off + 8 > len) return false; *off += 8; return true;
    case 2:
      if (!rd_varint(p, len, off, &v)) return false;
      if (v > (uint64_t)(len - *off)) return false;
      *off += (int)v;
      return true;
    case 5: if (*off + 4 > len) return false; *off += 4; return true;
    default: return false;
  }
}

inline double rd_double_fixed(const char* p) {
  double d;
  memcpy(&d, p, 8);
  return d;
}

// enum Type names, capitalized — the digest hashes Type.String()
// (reference importsrv/server.go:141-148 hashMetric)
constexpr const char* kTypeNames[5] = {"Counter", "Gauge", "Histogram",
                                       "Set", "Timer"};
constexpr int kTypeNameLen[5] = {7, 5, 9, 3, 5};
// metricpb.Type enum -> engine kind byte (convert.py _TYPE_NAMES)
constexpr int kTypeKind[5] = {K_COUNTER, K_GAUGE, K_HISTO, K_SET, K_TIMER};

struct MetricView {
  const char* name = nullptr;
  int name_len = 0;
  uint64_t type = 0;
  uint64_t scope = 0;
  int which = 0;        // last value-oneof field seen (proto3: last wins)
  const char* val = nullptr;
  int val_len = 0;
};

// parse one metricpb.Metric submessage; tags collected into `tags`
inline bool parse_metric_view(const char* p, int len, MetricView* m,
                              std::vector<std::pair<const char*, size_t>>*
                                  tags) {
  int off = 0;
  tags->clear();
  while (off < len) {
    uint64_t key;
    if (!rd_varint(p, len, &off, &key)) return false;
    int field = (int)(key >> 3), wt = (int)(key & 7);
    if (wt == 2) {
      uint64_t n;
      if (!rd_varint(p, len, &off, &n)) return false;
      if (n > (uint64_t)(len - off)) return false;
      const char* body = p + off;
      off += (int)n;
      switch (field) {
        case 1: m->name = body; m->name_len = (int)n; break;
        case 2: tags->emplace_back(body, (size_t)n); break;
        case 5: case 6: case 7: case 8:
          m->which = field; m->val = body; m->val_len = (int)n; break;
        default: break;
      }
    } else {
      uint64_t v;
      if (wt == 0) {
        if (!rd_varint(p, len, &off, &v)) return false;
        if (field == 3) m->type = v;
        else if (field == 9) m->scope = v;
      } else if (!skip_field(p, len, &off, wt)) {
        return false;
      }
    }
  }
  return true;
}

}  // namespace

extern "C" {

// Decode + stage a serialized forwardrpc.MetricList. Returns the number
// of metrics staged natively; *consumed reports how many input bytes
// were fully handled (always a top-level field boundary — re-enter with
// data+consumed after emitting when staging filled). Fallback spans
// (Python slow path) are (offset-within-data, length) pairs of Metric
// submessages; if fb_cap would overflow, decoding stops early.
int vi_import(void* hp, const char* data, int len, int start,
              int* consumed, int32_t* fb_off, int32_t* fb_len, int fb_cap,
              int* n_fb, int* full_stop) {
  auto* p = (Parser*)hp;
  p->alloc_imported = true;
  int staged = 0;   // metrics HANDLED natively (capacity drops included,
                    // matching the Python path's imported_total)
  *n_fb = 0;
  *full_stop = 0;
  int off = start;
  *consumed = start;
  while (off < len) {
    int metric_start = off;
    uint64_t key;
    if (!rd_varint(data, len, &off, &key)) break;  // truncated tail
    int field = (int)(key >> 3), wt = (int)(key & 7);
    if (field != 1 || wt != 2) {      // unknown top-level field: skip
      if (!skip_field(data, len, &off, wt)) break;
      *consumed = off;
      continue;
    }
    uint64_t n;
    if (!rd_varint(data, len, &off, &n)) break;
    if (n > (uint64_t)(len - off)) break;
    const char* body = data + off;
    int body_off = off;
    off += (int)n;

    MetricView m;
    bool ok = parse_metric_view(body, (int)n, &m, &p->tag_views);
    bool native = ok && m.name && m.type < 5 &&
                  ((m.type == 0 && m.which == 5) ||     // Counter
                   (m.type == 1 && m.which == 6) ||     // Gauge
                   ((m.type == 2 || m.type == 4) && m.which == 7));
    if (!native) {
      if (*n_fb >= fb_cap) {    // drain fallbacks first, then re-enter
        p->alloc_imported = false;
        return staged;
      }
      fb_off[*n_fb] = body_off;
      fb_len[(*n_fb)++] = (int)n;
      *consumed = off;
      continue;
    }

    // capacity check BEFORE staging so a metric never half-stages;
    // histograms need one histo-lane row per centroid (count them)
    int need_h = 0;
    if (m.which == 7) {
      // HistogramValue { tdigest.MergingDigestData t_digest = 1 }
      int o2 = 0;
      const char* hv = m.val;
      uint64_t k2, n2;
      const char* td = nullptr;
      int td_len = 0;
      while (o2 < m.val_len) {
        if (!rd_varint(hv, m.val_len, &o2, &k2)) { td = nullptr; break; }
        if ((k2 >> 3) == 1 && (k2 & 7) == 2) {
          if (!rd_varint(hv, m.val_len, &o2, &n2) ||
              n2 > (uint64_t)(m.val_len - o2)) { td = nullptr; break; }
          td = hv + o2;
          td_len = (int)n2;
          o2 += (int)n2;
        } else if (!skip_field(hv, m.val_len, &o2, (int)(k2 & 7))) {
          td = nullptr;
          break;
        }
      }
      if (!td) {   // malformed digest wrapper -> Python (error counting)
        if (*n_fb >= fb_cap) {
          p->alloc_imported = false;
          return staged;
        }
        fb_off[*n_fb] = body_off;
        fb_len[(*n_fb)++] = (int)n;
        *consumed = off;
        continue;
      }
      m.val = td;             // walk the MergingDigestData directly
      m.val_len = td_len;
      int o3 = 0;
      uint64_t k3, n3;
      while (o3 < td_len) {
        if (!rd_varint(td, td_len, &o3, &k3)) break;
        if ((k3 >> 3) == 1 && (k3 & 7) == 2) {
          if (!rd_varint(td, td_len, &o3, &n3) ||
              n3 > (uint64_t)(td_len - o3)) break;
          o3 += (int)n3;
          need_h++;
        } else if (!skip_field(td, td_len, &o3, (int)(k3 & 7))) {
          break;
        }
      }
      if ((uint32_t)need_h > p->bh) {  // digest larger than a whole
        if (*n_fb >= fb_cap) {          // batch: Python path
          p->alloc_imported = false;
          return staged;
        }
        fb_off[*n_fb] = body_off;
        fb_len[(*n_fb)++] = (int)n;
        *consumed = off;
        continue;
      }
    }
    bool full = (m.which == 5 && p->nc >= p->bc) ||
                (m.which == 6 && p->ng >= p->bg) ||
                (m.which == 7 && p->nh + need_h > p->bh);
    if (full) {
      *consumed = metric_start;   // emit, then re-enter at this metric
      *full_stop = 1;             // distinguishes from an undecodable
      p->alloc_imported = false;  // boundary (which makes no progress
      return staged;              // AND isn't a lane stop)
    }

    // digest: fnv1a-32 over name, Type.String(), then each tag
    // (reference importsrv/server.go:141-148; convert.py metric_digest)
    uint32_t digest = fnv32(m.name, (size_t)m.name_len, FNV32_OFFSET);
    digest = fnv32(kTypeNames[m.type], (size_t)kTypeNameLen[m.type],
                   digest);
    p->joined.clear();
    for (size_t i = 0; i < p->tag_views.size(); i++) {
      digest = fnv32(p->tag_views[i].first, p->tag_views[i].second,
                     digest);
      if (i) p->joined.push_back(',');
      p->joined.append(p->tag_views[i].first, p->tag_views[i].second);
    }

    int kind = kTypeKind[m.type];
    // scope coercion (convert.py import_into / worker.go:442-447):
    // counters/gauges arriving via import are global by definition;
    // histos keep Global else collapse to mixed
    uint8_t scope = (kind == K_COUNTER || kind == K_GAUGE)
                        ? 2 : (m.scope == 2 ? 2 : 0);
    KindTable* t = (kind == K_COUNTER) ? &p->counters
                   : (kind == K_GAUGE) ? &p->gauges : &p->histos;
    int32_t slot = p->slot_for(*t, (uint8_t)kind, scope, m.name,
                               (size_t)m.name_len, digest);
    if (slot < 0) {   // capacity drop, counted in t->dropped —
      staged++;       // still a HANDLED metric (imported_total parity
      p->processed++; // with the Python path, which counts before drops)
      *consumed = off;
      continue;
    }

    if (m.which == 5) {            // CounterValue { int64 value = 1 }
      int o2 = 0;
      uint64_t k2, v2 = 0;
      while (o2 < m.val_len) {
        if (!rd_varint(m.val, m.val_len, &o2, &k2)) break;
        if ((k2 >> 3) == 1 && (k2 & 7) == 0) {
          if (!rd_varint(m.val, m.val_len, &o2, &v2)) break;
        } else if (!skip_field(m.val, m.val_len, &o2, (int)(k2 & 7))) {
          break;
        }
      }
      p->c_slot[p->nc] = slot;
      p->c_inc[p->nc++] = (float)(double)(int64_t)v2;
    } else if (m.which == 6) {     // GaugeValue { double value = 1 }
      int o2 = 0;
      uint64_t k2;
      double v2 = 0;
      while (o2 < m.val_len) {
        if (!rd_varint(m.val, m.val_len, &o2, &k2)) break;
        if ((k2 >> 3) == 1 && (k2 & 7) == 1) {
          if (o2 + 8 > m.val_len) break;
          v2 = rd_double_fixed(m.val + o2);
          o2 += 8;
        } else if (!skip_field(m.val, m.val_len, &o2, (int)(k2 & 7))) {
          break;
        }
      }
      p->g_slot[p->ng] = slot;
      p->g_val[p->ng++] = (float)v2;
    } else {                       // MergingDigestData (unwrapped above)
      // proto3 elides default fields: absent min/max/reciprocalSum
      // mean 0.0 on the wire, and the Python path stages exactly that
      // (convert.py reads td.min etc., getting the proto3 default) —
      // +-inf sentinels here would silently no-op the scatter-min/max
      double mn = 0.0, mx = 0.0, recip = 0;
      double readd_recip = 0;      // f32-cast sum like the Python path
      bool all_nonzero = true;
      int o3 = 0;
      uint64_t k3, n3;
      while (o3 < m.val_len) {
        if (!rd_varint(m.val, m.val_len, &o3, &k3)) break;
        int f3 = (int)(k3 >> 3), w3 = (int)(k3 & 7);
        if (f3 == 1 && w3 == 2) {  // Centroid { mean=1 weight=2 }
          if (!rd_varint(m.val, m.val_len, &o3, &n3) ||
              n3 > (uint64_t)(m.val_len - o3)) break;
          const char* c = m.val + o3;
          o3 += (int)n3;
          double mean = 0, weight = 0;
          int oc = 0;
          uint64_t kc;
          while (oc < (int)n3) {
            if (!rd_varint(c, (int)n3, &oc, &kc)) break;
            int fc = (int)(kc >> 3);
            if ((kc & 7) == 1 && oc + 8 <= (int)n3) {
              double d = rd_double_fixed(c + oc);
              oc += 8;
              if (fc == 1) mean = d;
              else if (fc == 2) weight = d;
            } else if (!skip_field(c, (int)n3, &oc, (int)(kc & 7))) {
              break;
            }
          }
          float fm = (float)mean, fw = (float)weight;
          if (fw > 0) {            // live-centroid filter (import_metric)
            p->h_slot[p->nh] = slot;
            p->h_val[p->nh] = fm;
            p->h_wt[p->nh++] = fw;
            if (fm == 0.0f) all_nonzero = false;
            else readd_recip += (double)(fw / fm);
          }
        } else if (w3 == 1 && o3 + 8 <= m.val_len) {
          double d = rd_double_fixed(m.val + o3);
          o3 += 8;
          if (f3 == 3) mn = d;
          else if (f3 == 4) mx = d;
          else if (f3 == 5) recip = d;
        } else if (!skip_field(m.val, m.val_len, &o3, w3)) {
          break;
        }
      }
      double corr = all_nonzero ? recip - readd_recip : 0;
      p->import_stats.push_back(ImportStat{slot, (float)mn, (float)mx,
                                           (float)corr});
    }
    staged++;
    p->processed++;
    *consumed = off;
  }
  p->alloc_imported = false;
  return staged;
}

// Drain the per-imported-histogram stats staged by vi_import. Returns
// the count written (≤ cap); remaining entries stay queued.
int vi_stats(void* hp, int32_t* slot, float* mn, float* mx, float* recip,
             int cap) {
  auto* p = (Parser*)hp;
  int n = (int)p->import_stats.size();
  if (n > cap) n = cap;
  for (int i = 0; i < n; i++) {
    const auto& s = p->import_stats[i];
    slot[i] = s.slot;
    mn[i] = s.mn;
    mx[i] = s.mx;
    recip[i] = s.recip_corr;
  }
  p->import_stats.erase(p->import_stats.begin(),
                        p->import_stats.begin() + n);
  return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native UDP reader group: N C++ threads recvmmsg into a shared datagram
// ring; the pipeline thread drains it via vr_pump (GIL released during the
// ctypes call), so neither the socket reads nor the parse hold the GIL.
// This replaces the Python per-datagram recv -> queue.put loop, whose
// interpreter overhead capped ingest around 6k datagrams/s and produced
// the 31% drop fraction in BASELINE config 1. The reference gets the same
// effect with N reader goroutines (networking.go:41-91); goroutines are
// free, Python threads are not, hence the native group.

namespace {

// In-ring admission control: the OverloadController's statsd-source
// admission decision (reliability/overload.py OverloadController.admit)
// replicated at the ring boundary so the native path honors the same
// shedding guarantees as _process_packets instead of bypassing them.
// State is pushed down on every controller poll (vr_admission_set) and
// exact per-class counts are drained back (vr_admission_counters), so
// sent == admitted + shed stays exact with the decision running off-GIL.
struct Admission {
  bool enabled = false;
  int state = 0;                      // 0 HEALTHY .. 3 CRITICAL
  double rate = 0.0, burst = 0.0;     // token bucket params (rate<=0: allow)
  std::vector<std::string> high_tags; // shed_priority_tags substrings
  // token buckets: [0] = "statsd" (low), [1] = "statsd/high"
  double tokens[2] = {0.0, 0.0};
  std::chrono::steady_clock::time_point last[2];
  bool primed = false;
  // exact per-class accounting: [self, high, low]
  uint64_t admitted[3] = {0, 0, 0};
  uint64_t shed[3] = {0, 0, 0};
  // exact per-(tenant, class) accounting (guarded by the owning mutex):
  // [admitted self/high/low, shed self/high/low]. Populated whenever the
  // tenant table is enabled — tenant accounting stays exact even with
  // class admission off.
  std::unordered_map<int32_t, std::array<uint64_t, 6>> per_tenant;
};

struct ReaderGroup {
  void* parser = nullptr;
  std::vector<std::thread> threads;
  std::vector<int> owned_fds;  // dup()s — closed in vr_stop after join
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::string> ring;   // one entry per datagram
  size_t ring_cap = 0;
  uint64_t ring_dropped = 0;      // guarded by mu
  uint64_t datagrams = 0;         // guarded by mu
  uint64_t toolong = 0;           // guarded by mu; MSG_TRUNC drops
  uint64_t ring_highwater = 0;    // guarded by mu; max depth ever seen
  uint64_t pump_batches = 0;      // guarded by mu; vr_pump calls that parsed
  uint64_t pump_stalls = 0;       // guarded by mu; vr_pump forced a swap
  Admission adm;                  // guarded by mu
  // datagram whose parse hit a full lane, parked whole with a resume
  // offset (no remainder copy)
  std::string tail;
  size_t tail_off = 0;
};

// Priority classes mirror reliability/overload.py PriorityClassifier:
// self-metrics (never shed) / high (shed last) / low.
enum { CLS_SELF = 0, CLS_HIGH = 1, CLS_LOW = 2 };

int classify_datagram(const Admission& a, const char* p, size_t n) {
  static const char kSelf1[] = "veneur.";
  static const char kSelf2[] = "veneur_tpu.";
  if ((n >= sizeof(kSelf1) - 1 && !memcmp(p, kSelf1, sizeof(kSelf1) - 1)) ||
      (n >= sizeof(kSelf2) - 1 && !memcmp(p, kSelf2, sizeof(kSelf2) - 1)))
    return CLS_SELF;
  for (const auto& tag : a.high_tags) {
    if (tag.empty() || tag.size() > n) continue;
    if (memmem(p, n, tag.data(), tag.size()) != nullptr) return CLS_HIGH;
  }
  return CLS_LOW;
}

// TokenBucket.allow (overload.py:63-84) under the ring mutex. rate<=0
// means the bucket is disabled (always admit), matching _bucket_allow.
bool bucket_allow(Admission& a, int which,
                  std::chrono::steady_clock::time_point now) {
  if (a.rate <= 0.0) return true;
  double burst = a.burst > 0.0 ? a.burst : a.rate;
  if (!a.primed) {
    a.tokens[0] = a.tokens[1] = burst;
    a.last[0] = a.last[1] = now;
    a.primed = true;
  }
  double dt = std::chrono::duration<double>(now - a.last[which]).count();
  a.last[which] = now;
  double t = a.tokens[which] + dt * a.rate;
  if (t > burst) t = burst;
  if (t >= 1.0) {
    a.tokens[which] = t - 1.0;
    return true;
  }
  a.tokens[which] = t;
  return false;
}

// OverloadController.admit for source="statsd", states per overload.py:
// HEALTHY(0) admits all; self never shed; high-priority admits until
// CRITICAL(3) then runs the "statsd/high" bucket; low is shed outright
// at SHEDDING(2)+ and bucketed at PRESSURED(1). Returns true to admit;
// counts either way.
// Apply pushed-down controller knobs to one Admission (caller holds the
// owning mutex). Rate/burst changes re-prime the buckets on the next
// decision.
void apply_admission(Admission& a, int enabled, int state, double rate,
                     double burst, const char* tags, int tags_len) {
  if (a.rate != rate || a.burst != burst) a.primed = false;
  a.enabled = enabled != 0;
  a.state = state;
  a.rate = rate;
  a.burst = burst;
  a.high_tags.clear();
  const char* p = tags;
  const char* end = tags + (tags_len > 0 ? tags_len : 0);
  while (p && p < end) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
    size_t n = nl ? (size_t)(nl - p) : (size_t)(end - p);
    if (n) a.high_tags.emplace_back(p, n);
    p += n + 1;
  }
}

bool admit_datagram(Admission& a, const char* p, size_t n,
                    std::chrono::steady_clock::time_point now) {
  int cls = classify_datagram(a, p, n);
  bool ok;
  if (a.state <= 0 || cls == CLS_SELF) {
    ok = true;
  } else if (cls == CLS_HIGH) {
    ok = a.state < 3 || bucket_allow(a, 1, now);
  } else if (a.state >= 2) {
    ok = false;
  } else {
    ok = bucket_allow(a, 0, now);
  }
  if (ok) a.admitted[cls]++; else a.shed[cls]++;
  return ok;
}

// Tenant-aware admission ladder: the per-class decision above, with the
// tenant's weighted bucket layered under it at SHEDDING(2)+ — a tenant
// over its fair share is throttled to its bucket while isolated tenants
// keep their full budget (low-class traffic that the class ladder would
// shed outright at SHEDDING+ instead runs the tenant bucket). Per-class
// counters bump only when class admission is enabled (preserving the
// pre-tenant counter contract); per-(tenant, class) counters bump
// whenever a tenant entry is attached.
bool admit_datagram2(Admission& a, TenantTable* tt, TenantEntry* te,
                     int32_t tenant, const char* p, size_t n,
                     std::chrono::steady_clock::time_point now) {
  int cls = classify_datagram(a, p, n);
  bool fair =
      tt && te && tt->base_rate.load(std::memory_order_relaxed) > 0.0;
  bool ok;
  if (!a.enabled || a.state <= 0 || cls == CLS_SELF) {
    ok = true;
  } else if (cls == CLS_HIGH) {
    ok = a.state < 3 || bucket_allow(a, 1, now);
    if (ok && fair && a.state >= 2) ok = tenant_allow(*tt, *te, now);
  } else if (a.state >= 2) {
    ok = fair ? tenant_allow(*tt, *te, now) : false;
  } else {
    ok = bucket_allow(a, 0, now);
  }
  if (a.enabled) {
    if (ok) a.admitted[cls]++; else a.shed[cls]++;
  }
  if (te) a.per_tenant[tenant][(ok ? 0 : 3) + cls]++;
  return ok;
}

void reader_main(ReaderGroup* g, int fd, int max_len) {
  constexpr int VLEN = 64;
  std::vector<std::vector<char>> bufs(VLEN, std::vector<char>(max_len));
  mmsghdr msgs[VLEN];
  iovec iovs[VLEN];
  // a receive timeout lets the thread observe the stop flag; fd is our
  // own dup (vr_start), closed in vr_stop after this thread joins
  struct timeval tv;
  tv.tv_sec = 0;
  tv.tv_usec = 200 * 1000;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  while (!g->stop.load(std::memory_order_relaxed)) {
    for (int i = 0; i < VLEN; i++) {
      iovs[i].iov_base = bufs[i].data();
      iovs[i].iov_len = (size_t)max_len;
      memset(&msgs[i], 0, sizeof(msgs[i]));
      msgs[i].msg_hdr.msg_iov = &iovs[i];
      msgs[i].msg_hdr.msg_iovlen = 1;
    }
    int n = recvmmsg(fd, msgs, VLEN, MSG_WAITFORONE, nullptr);
    if (n <= 0) {
      // rcvtimeo/EINTR: just recheck stop. A persistent error (EBADF —
      // shutdown closed the fd before we were joined) must not busy-spin.
      if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
          errno != EINTR)
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
      continue;
    }
    {
      std::lock_guard<std::mutex> lk(g->mu);
      for (int i = 0; i < n; i++) {
        g->datagrams++;
        // buffers are sized metric_max_length+1: a datagram the kernel
        // truncated (MSG_TRUNC) exceeded the configured limit — drop
        // the whole packet and count it, like the reference's
        // processMetricPacket "toolong" guard (server.go:1082)
        // MSG_TRUNC only fires when the datagram EXCEEDS the buffer; a
        // datagram of exactly max_len (= limit+1) bytes fits, so the
        // length check catches the boundary case the flag misses —
        // keeping this path byte-identical to the Python reader's
        // `len(data) > limit`
        if ((msgs[i].msg_hdr.msg_flags & MSG_TRUNC) ||
            msgs[i].msg_len >= (unsigned)max_len) {
          g->toolong++;
          continue;
        }
        // admission runs here — before the ring, off the GIL — so a shed
        // datagram costs one classify, not a parse + Python round-trip.
        // Every under-limit datagram is counted exactly once as admitted
        // or shed (ring-full drops below are post-admission and counted
        // separately), preserving sent == admitted + shed.
        if (g->adm.enabled &&
            !admit_datagram(g->adm, bufs[i].data(), (size_t)msgs[i].msg_len,
                            std::chrono::steady_clock::now()))
          continue;
        if (g->ring.size() >= g->ring_cap) {
          g->ring_dropped++;  // kernel-rcvbuf-overflow analogue, counted
          continue;
        }
        g->ring.emplace_back(bufs[i].data(), (size_t)msgs[i].msg_len);
        if ((uint64_t)g->ring.size() > g->ring_highwater)
          g->ring_highwater = (uint64_t)g->ring.size();
      }
    }
    g->cv.notify_one();
  }
}

}  // namespace

extern "C" {

// Start n_fds reader threads (one per SO_REUSEPORT socket). Each fd is
// dup()ed into C++ ownership, so Python may close its socket objects at
// any point during shutdown without racing a reader's recvmmsg onto a
// recycled fd number; the dups are closed in vr_stop after the join.
void* vr_start(void* parser, const int* fds, int n_fds, int max_len,
               int ring_cap) {
  auto* g = new ReaderGroup();
  g->parser = parser;
  g->ring_cap = (size_t)(ring_cap > 0 ? ring_cap : 65536);
  for (int i = 0; i < n_fds; i++) {
    int own = dup(fds[i]);
    if (own < 0) continue;  // fd table exhausted; skip this reader
    g->owned_fds.push_back(own);
    g->threads.emplace_back(reader_main, g, own,
                            max_len > 0 ? max_len : 65536);
  }
  return g;
}

// Drain ring -> parser staging. Blocks up to max_wait_ms while the ring is
// empty (GIL is released for the whole call). Returns 1 when a staging
// lane filled — the caller must emit a batch and call again — else 0.
// out: [0]=datagrams parsed this call, [1]=ring depth now,
//      [2]=ring_dropped total, [3]=datagrams received total.
int vr_pump(void* gp, int max_wait_ms, uint64_t* out) {
  auto* g = (ReaderGroup*)gp;
  uint64_t parsed_dg = 0;
  int full = 0;
  int consumed = 0;
  if (g->tail_off < g->tail.size()) {
    full = vt_feed(g->parser, g->tail.data(), (int)g->tail.size(),
                   (int)g->tail_off, &consumed);
    g->tail_off = (size_t)consumed;
    if (!full) {
      g->tail.clear();
      g->tail_off = 0;
    }
  }
  std::string local;
  while (!full) {
    {
      std::unique_lock<std::mutex> lk(g->mu);
      if (g->ring.empty() && parsed_dg == 0 && max_wait_ms > 0)
        g->cv.wait_for(lk, std::chrono::milliseconds(max_wait_ms));
      if (g->ring.empty()) break;
      local = std::move(g->ring.front());
      g->ring.pop_front();
    }
    parsed_dg++;
    full = vt_feed(g->parser, local.data(), (int)local.size(), 0, &consumed);
    if (full) {
      // park the whole datagram with a resume offset — no remainder copy
      g->tail = std::move(local);
      g->tail_off = (size_t)consumed;
    }
  }
  {
    std::lock_guard<std::mutex> lk(g->mu);
    out[1] = (uint64_t)g->ring.size();
    out[2] = g->ring_dropped;
    out[3] = g->datagrams;
    if (parsed_dg > 0) g->pump_batches++;
    if (full) g->pump_stalls++;  // staging lane filled: forced buffer swap
  }
  out[0] = parsed_dg;
  return full;
}

// Push the OverloadController's current admission knobs down into the
// ring (called from the controller's poll thread and at reader start).
// `tags` is a '\n'-joined shed_priority_tags list (tags_len bytes; may be
// empty). Rate/burst changes re-prime the buckets on the next decision.
void vr_admission_set(void* gp, int enabled, int state, double rate,
                      double burst, const char* tags, int tags_len) {
  auto* g = (ReaderGroup*)gp;
  std::lock_guard<std::mutex> lk(g->mu);
  apply_admission(g->adm, enabled, state, rate, burst, tags, tags_len);
}

// Drain-and-reset the exact per-class admission deltas so the controller
// can fold them into its registry counters: out = [admitted_self,
// admitted_high, admitted_low, shed_self, shed_high, shed_low].
void vr_admission_counters(void* gp, uint64_t* out) {
  auto* g = (ReaderGroup*)gp;
  std::lock_guard<std::mutex> lk(g->mu);
  for (int i = 0; i < 3; i++) {
    out[i] = g->adm.admitted[i];
    out[3 + i] = g->adm.shed[i];
    g->adm.admitted[i] = 0;
    g->adm.shed[i] = 0;
  }
}

// Thread-safe counter snapshot (any thread): [0]=datagrams received,
// [1]=ring_dropped, [2]=ring depth, [3]=toolong drops.
void vr_counters(void* gp, uint64_t* out) {
  auto* g = (ReaderGroup*)gp;
  std::lock_guard<std::mutex> lk(g->mu);
  out[0] = g->datagrams;
  out[1] = g->ring_dropped;
  out[2] = (uint64_t)g->ring.size();
  out[3] = g->toolong;
}

// Deep ring/emit telemetry snapshot (any thread, one lock, no allocation):
// [0]=ring depth now, [1]=ring depth high-water, [2]=pump batches (vr_pump
// calls that parsed >=1 datagram), [3]=buffer-swap stalls (vr_pump returned
// full), [4]=emit_packed calls, [5]=emit_packed ns total, [6]=datagrams
// received, [7]=ring_dropped. Per-class admission is NOT repeated here —
// vr_admission_counters already drains it exactly.
void vr_stats(void* gp, uint64_t* out) {
  auto* g = (ReaderGroup*)gp;
  {
    std::lock_guard<std::mutex> lk(g->mu);
    out[0] = (uint64_t)g->ring.size();
    out[1] = g->ring_highwater;
    out[2] = g->pump_batches;
    out[3] = g->pump_stalls;
    out[6] = g->datagrams;
    out[7] = g->ring_dropped;
  }
  auto* p = (Parser*)g->parser;
  out[4] = p->emit_packed_calls.load(std::memory_order_relaxed);
  out[5] = p->emit_packed_ns.load(std::memory_order_relaxed);
}

void vr_stop(void* gp) {
  auto* g = (ReaderGroup*)gp;
  g->stop.store(true);
  for (auto& t : g->threads)
    if (t.joinable()) t.join();
  for (int fd : g->owned_fds) close(fd);
  delete g;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Multi-ring reader groups (vrm_*): one ring + parser + staging pair per
// reader core. The single-ring design above parses on the pipeline thread
// (vr_pump), which caps the host at one core of parse; here each ring owns
// a reader thread (recvmmsg -> ring, optional) AND a worker thread (ring ->
// parse -> staging), so N rings parse on N cores concurrently while the
// pipeline thread only memcpys staged lanes into its packed arena rows and
// steps the device. All rings share the master parser's key tables (see
// Parser::slot_for: ring-local replica cache, shared lock on miss), so a
// flow-hashed key landing on any ring maps to the same device slot.
// Admission, toolong, and ring-cap accounting run per ring with the same
// datagrams == toolong + admitted + shed invariant, summed by Python.

namespace {

struct MultiRing;

// One queued datagram plus the tenant identity resolved at admission time
// (ring_push), so the worker parses under the same identity the admission
// decision was charged to — re-extracting at parse time could disagree
// after a weights push or intern-cap overflow.
struct Dgram {
  std::string data;
  TenantEntry* te = nullptr;
  int32_t tenant = 0;
};

struct Ring {
  Parser parser;                 // staging + key cache; tables -> master
  int fd = -1;                   // dup()ed socket; -1 = inject-only ring
  int max_len = 65536;
  int pin_core = -1;
  std::thread reader;
  std::thread worker;
  std::mutex mu;                 // ring deque + counters + admission
  std::condition_variable cv;        // ring became non-empty
  std::condition_variable space_cv;  // staging emitted / resumed
  std::deque<Dgram> ring;
  size_t ring_cap = 65536;
  // ring-local tenant-id replica (guarded by mu): hits skip the shared
  // intern table's mutex, mirroring the key-table local_cache pattern
  std::unordered_map<std::string, std::pair<int32_t, TenantEntry*>> tcache;
  uint64_t datagrams = 0;        // guarded by mu
  uint64_t toolong = 0;          // guarded by mu
  uint64_t ring_dropped = 0;     // guarded by mu
  uint64_t ring_highwater = 0;   // guarded by mu
  uint64_t parse_batches = 0;    // guarded by mu; datagrams parsed
  uint64_t stalls = 0;           // guarded by mu; staging filled mid-parse
  Admission adm;                 // guarded by mu
  std::atomic<bool> stalled{false};
  std::mutex stage_mu;           // staging lanes: worker parse vs emit
};

struct MultiRing {
  Parser* master = nullptr;
  std::vector<std::unique_ptr<Ring>> rings;
  std::atomic<bool> stop{false};
  std::atomic<bool> pause{false};        // swap-boundary quiesce
  std::mutex wait_mu;
  std::condition_variable wait_cv;       // pipeline wakeup
};

void pin_self(int core) {
  if (core < 0) return;
  cpu_set_t set;
  CPU_ZERO(&set);
  CPU_SET(core, &set);
  pthread_setaffinity_np(pthread_self(), sizeof(set), &set);
}

// Shared push for the socket reader and the inject path so bench traffic
// hits the same invariant: every arriving datagram is counted exactly once
// as toolong, admitted, or shed (ring-full drops are post-admission and
// counted separately). Returns 1 when queued, 0 when counted-and-
// rejected (toolong / admission shed / ring-full drop).
//
// With `backpressure` (the inject path), a full ring returns -1 with NO
// counting at all: the caller holds the datagram and retries, and
// counting here would double-count it on the retry (the PR 19 footgun).
// The socket reader never passes backpressure — a kernel-delivered
// datagram cannot be retried, so a full ring must count it dropped.
int ring_push2(Ring* r, const char* data, size_t n, bool kernel_trunc,
               bool backpressure) {
  {
    std::lock_guard<std::mutex> lk(r->mu);
    // only the worker pops, so under r->mu the ring can only shrink —
    // checking before counting is race-free
    if (backpressure && r->ring.size() >= r->ring_cap) return -1;
    r->datagrams++;
    if (kernel_trunc || n >= (size_t)r->max_len) {
      r->toolong++;
      return 0;
    }
    // tenant identity resolves here, before admission, so the fairness
    // decision and the per-tenant shed count land on the same identity.
    // Lock order r->mu -> tt.mu (tenant_intern / tenant_allow); nothing
    // takes them in reverse.
    TenantTable* tt = r->parser.rt().tenants.get();
    TenantEntry* te = nullptr;
    int32_t tenant = 0;
    if (tt && tt->enabled.load(std::memory_order_relaxed)) {
      te = tt->dflt;
      const char* v = nullptr;
      size_t vlen = 0;
      if (tenant_extract(tt->tag, data, n, &v, &vlen)) {
        std::string key(v, vlen);
        auto it = r->tcache.find(key);
        if (it != r->tcache.end()) {
          tenant = it->second.first;
          te = it->second.second;
        } else {
          tenant = tenant_intern(*tt, v, vlen, &te);
          // an intern-cap overflow maps onto the default tenant; don't
          // cache that as this name's identity (the cap could in theory
          // be lifted by a restore re-interning in a different order)
          if (tenant != 0 || key == te->name)
            r->tcache.emplace(std::move(key), std::make_pair(tenant, te));
        }
      }
    }
    if ((r->adm.enabled || te) &&
        !admit_datagram2(r->adm, tt, te, tenant, data, n,
                         std::chrono::steady_clock::now()))
      return 0;
    if (r->ring.size() >= r->ring_cap) {
      r->ring_dropped++;
      return 0;
    }
    r->ring.push_back(Dgram{std::string(data, n), te, tenant});
    if ((uint64_t)r->ring.size() > r->ring_highwater)
      r->ring_highwater = (uint64_t)r->ring.size();
  }
  r->cv.notify_one();
  return 1;
}

bool ring_push(Ring* r, const char* data, size_t n, bool kernel_trunc) {
  return ring_push2(r, data, n, kernel_trunc, false) == 1;
}

void vrm_reader_main(MultiRing* mr, Ring* r) {
  pin_self(r->pin_core);
  constexpr int VLEN = 64;
  std::vector<std::vector<char>> bufs(VLEN, std::vector<char>(r->max_len));
  mmsghdr msgs[VLEN];
  iovec iovs[VLEN];
  struct timeval tv;
  tv.tv_sec = 0;
  tv.tv_usec = 200 * 1000;
  setsockopt(r->fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  while (!mr->stop.load(std::memory_order_relaxed)) {
    for (int i = 0; i < VLEN; i++) {
      iovs[i].iov_base = bufs[i].data();
      iovs[i].iov_len = (size_t)r->max_len;
      memset(&msgs[i], 0, sizeof(msgs[i]));
      msgs[i].msg_hdr.msg_iov = &iovs[i];
      msgs[i].msg_hdr.msg_iovlen = 1;
    }
    int n = recvmmsg(r->fd, msgs, VLEN, MSG_WAITFORONE, nullptr);
    if (n <= 0) {
      if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
          errno != EINTR)
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
      continue;
    }
    for (int i = 0; i < n; i++)
      ring_push(r, bufs[i].data(), (size_t)msgs[i].msg_len,
                (msgs[i].msg_hdr.msg_flags & MSG_TRUNC) != 0);
  }
}

// Per-ring parse loop: pop one datagram, parse it into this ring's staging
// under stage_mu (held only for the parse itself). A full staging lane
// parks the datagram with its resume offset and waits for the pipeline to
// emit; the swap-boundary pause parks it the same way.
void vrm_worker_main(MultiRing* mr, Ring* r) {
  pin_self(r->pin_core);
  Dgram local;
  size_t off = 0;
  bool have = false;
  while (!mr->stop.load(std::memory_order_relaxed)) {
    if (!have) {
      std::unique_lock<std::mutex> lk(r->mu);
      if (r->ring.empty())
        r->cv.wait_for(lk, std::chrono::milliseconds(100));
      if (mr->stop.load(std::memory_order_relaxed)) break;
      if (r->ring.empty() || mr->pause.load(std::memory_order_relaxed))
        continue;
      local = std::move(r->ring.front());
      r->ring.pop_front();
      r->parse_batches++;
      off = 0;
      have = true;
    }
    bool full = false;
    bool parsed = false;
    bool rich = false;
    {
      std::unique_lock<std::mutex> lk(r->stage_mu);
      if (!mr->pause.load(std::memory_order_relaxed)) {
        // parse context: the tenant resolved at admission time, with the
        // demotion flag re-read per attempt so a parked datagram resumes
        // under the tenant's current quarantine state
        r->parser.cur_tenant = local.tenant;
        r->parser.cur_entry = local.te;
        r->parser.cur_demoted =
            local.te && local.te->demoted.load(std::memory_order_relaxed);
        int consumed = 0;
        full = vt_feed(&r->parser, local.data.data(),
                       (int)local.data.size(), (int)off, &consumed) != 0;
        off = (size_t)consumed;
        if (!full) have = false;
        parsed = true;
        Parser& p = r->parser;
        rich = p.nc * 2 >= p.bc || p.ng * 2 >= p.bg || p.ns * 2 >= p.bs ||
               p.nh * 2 >= p.bh;
      }
    }
    if (parsed && !full) {
      // opportunistic wake when lanes run half full so emits don't wait
      // for a hard stall (lost wakeups here only cost one wait timeout)
      if (rich) mr->wait_cv.notify_all();
      continue;
    }
    if (full) {
      {
        std::lock_guard<std::mutex> lk(r->mu);
        r->stalls++;
      }
      r->stalled.store(true, std::memory_order_release);
      // ordered notify: the pipeline checks stalled under wait_mu, so
      // taking it here makes the stall wakeup lossless
      { std::lock_guard<std::mutex> lk(mr->wait_mu); }
      mr->wait_cv.notify_all();
    }
    // stalled (wait for an emit) or paused (wait for resume)
    std::unique_lock<std::mutex> lk(r->mu);
    r->space_cv.wait_for(lk, std::chrono::milliseconds(50), [&] {
      return mr->stop.load(std::memory_order_relaxed) ||
             (!mr->pause.load(std::memory_order_relaxed) &&
              !r->stalled.load(std::memory_order_acquire));
    });
  }
}

}  // namespace

extern "C" {

// Start n_rings independent ingest lanes against the master parser.
// fds[i] >= 0 attaches a dup()ed SO_REUSEPORT socket to ring i (fds may be
// null / entries -1 for inject-only rings, e.g. benches). pin_cores[i] >= 0
// pins ring i's reader+worker threads to that core (null = no pinning).
void* vrm_start(void* parser, const int* fds, int n_rings, int max_len,
                int ring_cap, const int* pin_cores) {
  auto* mr = new MultiRing();
  auto* m = (Parser*)parser;
  mr->master = m;
  for (int i = 0; i < n_rings; i++) {
    auto r = std::make_unique<Ring>();
    r->max_len = max_len > 0 ? max_len : 65536;
    r->ring_cap = (size_t)(ring_cap > 0 ? ring_cap : 65536);
    r->pin_core = pin_cores ? pin_cores[i] : -1;
    r->parser.init(m->counters.capacity, m->gauges.capacity,
                   m->sets.capacity, m->histos.capacity,
                   m->counters.n_shards, m->hll_precision, m->bc, m->bg,
                   m->bs, m->bh);
    r->parser.master = m;
    if (fds && fds[i] >= 0) {
      int own = dup(fds[i]);
      if (own >= 0) r->fd = own;
    }
    mr->rings.push_back(std::move(r));
  }
  for (auto& r : mr->rings) {
    Ring* rp = r.get();
    if (rp->fd >= 0) rp->reader = std::thread(vrm_reader_main, mr, rp);
    rp->worker = std::thread(vrm_worker_main, mr, rp);
  }
  return mr;
}

int vrm_n_rings(void* h) { return (int)((MultiRing*)h)->rings.size(); }

// Queue one datagram onto ring i through the same toolong/admission/
// ring-cap accounting as the socket path (benches and tests use this for
// deterministic ring placement — SO_REUSEPORT flow hashing is opaque).
// Verdicts: 1 = queued, 0 = counted-and-rejected (toolong or admission
// shed — the datagrams == toolong + admitted + shed identity holds),
// -1 = backpressure: the ring is full and NOTHING was counted — the
// caller still owns the datagram and paces/retries without inflating
// any counter.
int vrm_inject(void* h, int ring, const char* data, int len) {
  auto* mr = (MultiRing*)h;
  return ring_push2(mr->rings[ring].get(), data, (size_t)len, false, true);
}

// Block the pipeline thread until a ring stalls on full staging (or the
// opportunistic half-full wake fires, or max_wait_ms passes). Returns the
// number of currently-stalled rings.
int vrm_wait(void* h, int max_wait_ms) {
  auto* mr = (MultiRing*)h;
  auto pred = [&] {
    if (mr->stop.load(std::memory_order_relaxed)) return true;
    for (auto& r : mr->rings) {
      if (r->stalled.load(std::memory_order_acquire)) return true;
      Parser& p = r->parser;
      if (p.nc * 2 >= p.bc || p.ng * 2 >= p.bg || p.ns * 2 >= p.bs ||
          p.nh * 2 >= p.bh)
        return true;
    }
    return false;
  };
  {
    std::unique_lock<std::mutex> lk(mr->wait_mu);
    if (max_wait_ms > 0 && !pred())
      mr->wait_cv.wait_for(lk, std::chrono::milliseconds(max_wait_ms),
                           pred);
  }
  int n = 0;
  for (auto& r : mr->rings)
    if (r->stalled.load(std::memory_order_acquire)) n++;
  return n;
}

// Staged rows across all rings (racy snapshot; idle heuristic only).
int vrm_pending(void* h) {
  auto* mr = (MultiRing*)h;
  uint64_t n = 0;
  for (auto& r : mr->rings) {
    Parser& p = r->parser;
    n += p.nc + p.ng + p.ns + p.nh;
  }
  return (int)n;
}

// Emit ring i's staged lanes into its packed arena row (same layout/
// sentinel contract as vt_emit_packed). stage_mu holds off the worker's
// parse for the copy; clearing the stall under the ring mutex makes the
// worker's resume wakeup lossless.
void vrm_emit(void* h, int ring, int32_t* buf, const int32_t* off,
              uint32_t* prev, uint32_t* counts_out) {
  auto* mr = (MultiRing*)h;
  Ring* r = mr->rings[ring].get();
  {
    std::lock_guard<std::mutex> lk(r->stage_mu);
    vt_emit_packed(&r->parser, buf, off, prev, counts_out);
  }
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->stalled.store(false, std::memory_order_release);
  }
  r->space_cv.notify_all();
}

// Pre-sharded emit of ring i's staging (vt_emit_sharded semantics: rows
// grouped by owner shard, slots rebased shard-local, per-kind shard
// bounds). Same locking/stall discipline as vrm_emit — this is the
// sharded backend's per-ring drain.
void vrm_emit_sharded(void* h, int ring, int32_t* c_slot, float* c_inc,
                      int32_t* g_slot, float* g_val, int32_t* s_slot,
                      int32_t* s_reg, uint8_t* s_rho, int32_t* h_slot,
                      float* h_val, float* h_wt, int32_t* bounds,
                      uint32_t* counts_out) {
  auto* mr = (MultiRing*)h;
  Ring* r = mr->rings[ring].get();
  {
    std::lock_guard<std::mutex> lk(r->stage_mu);
    vt_emit_sharded(&r->parser, c_slot, c_inc, g_slot, g_val, s_slot,
                    s_reg, s_rho, h_slot, h_val, h_wt, bounds, counts_out);
  }
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->stalled.store(false, std::memory_order_release);
  }
  r->space_cv.notify_all();
}

// Swap-boundary quiesce: after vrm_pause returns no worker is inside a
// parse and none will enter one until vrm_resume, so the caller can emit
// every ring and reset the shared tables without racing staged rows
// against a cleared key space.
void vrm_pause(void* h) {
  auto* mr = (MultiRing*)h;
  mr->pause.store(true, std::memory_order_release);
  for (auto& r : mr->rings) {
    // barrier: any in-flight parse (which checks pause under stage_mu)
    // completes before we proceed
    std::lock_guard<std::mutex> lk(r->stage_mu);
  }
}

void vrm_resume(void* h) {
  auto* mr = (MultiRing*)h;
  mr->pause.store(false, std::memory_order_release);
  for (auto& r : mr->rings) {
    { std::lock_guard<std::mutex> lk(r->mu); }
    r->space_cv.notify_all();
    r->cv.notify_all();
  }
}

// Flush boundary: reset the master tables and every ring's key-replica
// cache. Caller must hold the quiesce (vrm_pause) and have emitted all
// rings first.
void vrm_reset(void* h) {
  auto* mr = (MultiRing*)h;
  vt_reset(mr->master);
  for (auto& r : mr->rings) r->parser.local_cache.clear();
}

// Multi-ring shard-map staging: the rings route every table access to
// the master, so staging on the master covers all of them. Applied by
// the vrm_reset inside the next swap quiesce (ring local caches are
// cleared there too, so no ring can hit an old-map slot afterwards).
void vrm_shard_map_set(void* h, uint32_t n_shards) {
  auto* mr = (MultiRing*)h;
  vt_shard_map_set(mr->master, n_shards);
}

// Multi-ring capacity staging: the rings route every table access to the
// master, so staging there covers all of them; the local replica caches
// hold (key -> slot) entries that the vrm_reset inside the same quiesce
// clears before any ring can hit an old-capacity slot.
void vrm_capacity_set(void* h, uint32_t cc, uint32_t gc, uint32_t sc,
                      uint32_t hc) {
  auto* mr = (MultiRing*)h;
  vt_capacity_set(mr->master, cc, gc, sc, hc);
}

// Master-table occupancy snapshot (vt_table_stats layout): the rings
// share the master's slot space, so this IS the multi-ring occupancy.
void vrm_table_stats(void* h, uint64_t* out) {
  auto* mr = (MultiRing*)h;
  vt_table_stats(mr->master, out);
}

// Per-ring counter snapshot: [0]=datagrams, [1]=ring_dropped,
// [2]=ring depth, [3]=toolong (vr_counters layout).
void vrm_counters(void* h, int ring, uint64_t* out) {
  auto* mr = (MultiRing*)h;
  Ring* r = mr->rings[ring].get();
  std::lock_guard<std::mutex> lk(r->mu);
  out[0] = r->datagrams;
  out[1] = r->ring_dropped;
  out[2] = (uint64_t)r->ring.size();
  out[3] = r->toolong;
}

// Per-ring deep telemetry (vr_stats layout): [0]=ring depth, [1]=depth
// high-water, [2]=parse batches (datagrams parsed), [3]=staging stalls,
// [4]=emit calls, [5]=emit ns, [6]=datagrams received, [7]=ring_dropped.
void vrm_ring_stats(void* h, int ring, uint64_t* out) {
  auto* mr = (MultiRing*)h;
  Ring* r = mr->rings[ring].get();
  {
    std::lock_guard<std::mutex> lk(r->mu);
    out[0] = (uint64_t)r->ring.size();
    out[1] = r->ring_highwater;
    out[2] = r->parse_batches;
    out[3] = r->stalls;
    out[6] = r->datagrams;
    out[7] = r->ring_dropped;
  }
  out[4] = r->parser.emit_packed_calls.load(std::memory_order_relaxed);
  out[5] = r->parser.emit_packed_ns.load(std::memory_order_relaxed);
}

// Push controller admission knobs to every ring. The aggregate token rate
// and burst split evenly across rings so the host-level admit rate matches
// the single-ring contract while each ring buckets independently off-GIL.
void vrm_admission_set(void* h, int enabled, int state, double rate,
                       double burst, const char* tags, int tags_len) {
  auto* mr = (MultiRing*)h;
  double n = (double)mr->rings.size();
  double rr = rate > 0.0 ? rate / n : rate;
  double bb = burst > 0.0 ? burst / n : burst;
  for (auto& r : mr->rings) {
    std::lock_guard<std::mutex> lk(r->mu);
    apply_admission(r->adm, enabled, state, rr, bb, tags, tags_len);
  }
}

// Drain-and-reset ring i's exact per-class admission deltas
// (vr_admission_counters layout). Callers must fold across ALL rings.
void vrm_admission_counters(void* h, int ring, uint64_t* out) {
  auto* mr = (MultiRing*)h;
  Ring* r = mr->rings[ring].get();
  std::lock_guard<std::mutex> lk(r->mu);
  for (int i = 0; i < 3; i++) {
    out[i] = r->adm.admitted[i];
    out[3 + i] = r->adm.shed[i];
    r->adm.admitted[i] = 0;
    r->adm.shed[i] = 0;
  }
}

// Engine-wide parse stats summed over ring parsers + master (vt_stats
// layout: processed, parse_errors, table drops).
void vrm_stats(void* h, uint64_t* out) {
  auto* mr = (MultiRing*)h;
  uint64_t pr = 0, pe = 0;
  for (auto& r : mr->rings) {
    pr += r->parser.processed.load(std::memory_order_relaxed);
    pe += r->parser.parse_errors.load(std::memory_order_relaxed);
  }
  vt_stats(mr->master, out);
  out[0] += pr;
  out[1] += pe;
}

// ---- tenant identity / fairness / quarantine ABI ----
//
// vt_tenant_config must run before rings start (tt.tag is read lock-free
// on the admission path); everything else is safe at any time. All vt_*
// tenant calls target the MASTER parser handle.

// Create (or reconfigure) the tenant table. Interns "default" as id 0.
void vt_tenant_config(void* hp, int enabled, const char* tag, int tag_len,
                      double burst_mult, uint32_t q_max_keys,
                      double q_decay, double q_readmit_frac) {
  auto* p = (Parser*)hp;
  if (!p->tenants) {
    p->tenants = std::make_unique<TenantTable>();
    auto e = std::make_unique<TenantEntry>();
    e->name = "default";
    p->tenants->dflt = e.get();
    p->tenants->entries.push_back(std::move(e));
    p->tenants->by_name.emplace("default", 0);
  }
  TenantTable& tt = *p->tenants;
  {
    std::lock_guard<std::mutex> lk(tt.mu);
    tt.tag.assign(tag ? tag : "", tag && tag_len > 0 ? (size_t)tag_len : 0);
    tt.burst_mult = burst_mult > 0.0 ? burst_mult : 2.0;
    tt.q_max_keys = q_max_keys;
    tt.q_decay = q_decay >= 0.0 && q_decay < 1.0 ? q_decay : 0.5;
    tt.q_readmit_frac = q_readmit_frac > 0.0 ? q_readmit_frac : 0.5;
  }
  tt.enabled.store(enabled != 0, std::memory_order_release);
}

// Per-poll push: base admit rate (tokens/s per unit weight; <=0 disables
// the fairness buckets) plus a "name\tweight\n" blob. A weight change
// re-primes that tenant's bucket; unknown names are interned so weights
// can be configured ahead of first traffic.
void vt_tenant_params(void* hp, double base_rate, const char* blob,
                      int len) {
  auto* p = (Parser*)hp;
  if (!p->tenants) return;
  TenantTable& tt = *p->tenants;
  tt.base_rate.store(base_rate, std::memory_order_relaxed);
  const char* q = blob;
  const char* end = blob + (blob && len > 0 ? len : 0);
  while (q && q < end) {
    const char* nl = (const char*)memchr(q, '\n', (size_t)(end - q));
    size_t n = nl ? (size_t)(nl - q) : (size_t)(end - q);
    const char* tab = (const char*)memchr(q, '\t', n);
    if (tab && tab > q) {
      std::string wstr(tab + 1, n - (size_t)(tab - q) - 1);
      double w = strtod(wstr.c_str(), nullptr);
      TenantEntry* te = nullptr;
      tenant_intern(tt, q, (size_t)(tab - q), &te);
      if (te) {
        std::lock_guard<std::mutex> lk(tt.mu);
        if (te->weight != w) {
          te->weight = w;
          te->primed = false;
        }
      }
    }
    q += n + 1;
  }
}

// Drain names interned since the last call as [i32 id][u16 len][name]*.
// Returns the entry count, or -bytes_needed (nothing drained) when cap
// is too small.
int vt_tenant_names(void* hp, char* buf, int cap) {
  auto* p = (Parser*)hp;
  if (!p->tenants) return 0;
  TenantTable& tt = *p->tenants;
  std::lock_guard<std::mutex> lk(tt.mu);
  size_t need = 0;
  for (int32_t id : tt.fresh) need += 6 + tt.entries[id]->name.size();
  if (need > (size_t)(cap > 0 ? cap : 0)) return -(int)need;
  char* w = buf;
  int n = 0;
  for (int32_t id : tt.fresh) {
    const std::string& nm = tt.entries[id]->name;
    uint16_t l = (uint16_t)nm.size();
    memcpy(w, &id, 4);
    memcpy(w + 4, &l, 2);
    memcpy(w + 6, nm.data(), nm.size());
    w += 6 + nm.size();
    n++;
  }
  tt.fresh.clear();
  return n;
}

// Non-destructive snapshot of every tenant for checkpoint / telemetry:
// [i32 id][u8 demoted][f64 key_est][u16 len][name]* in id order. The
// estimate folds in the current window so a checkpoint taken mid-flush
// carries the full count. Returns entries or -bytes_needed.
int vt_tenant_table(void* hp, char* buf, int cap) {
  auto* p = (Parser*)hp;
  if (!p->tenants) return 0;
  TenantTable& tt = *p->tenants;
  std::lock_guard<std::mutex> lk(tt.mu);
  size_t need = 0;
  for (auto& e : tt.entries) need += 15 + e->name.size();
  if (need > (size_t)(cap > 0 ? cap : 0)) return -(int)need;
  char* w = buf;
  int n = 0;
  for (auto& e : tt.entries) {
    int32_t id = n;
    uint8_t dem = e->demoted.load(std::memory_order_relaxed) ? 1 : 0;
    double est = e->key_est.load(std::memory_order_relaxed) +
                 (double)e->window_keys.load(std::memory_order_relaxed);
    uint16_t l = (uint16_t)e->name.size();
    memcpy(w, &id, 4);
    memcpy(w + 4, &dem, 1);
    memcpy(w + 5, &est, 8);
    memcpy(w + 13, &l, 2);
    memcpy(w + 15, e->name.data(), e->name.size());
    w += 15 + e->name.size();
    n++;
  }
  return n;
}

// Restore quarantine state from a checkpoint: [u8 demoted][f64 key_est]
// [u16 len][name]* — names are (re-)interned in blob order, so a table
// restored into a fresh process reproduces the same id assignment it was
// snapshotted with. Returns entries applied.
int vt_tenant_restore(void* hp, const char* blob, int len) {
  auto* p = (Parser*)hp;
  if (!p->tenants || !blob) return 0;
  TenantTable& tt = *p->tenants;
  const char* q = blob;
  const char* end = blob + (len > 0 ? len : 0);
  int n = 0;
  while (q + 11 <= end) {
    uint8_t dem = (uint8_t)*q;
    double est;
    uint16_t l;
    memcpy(&est, q + 1, 8);
    memcpy(&l, q + 9, 2);
    q += 11;
    if (q + l > end) break;
    TenantEntry* te = nullptr;
    tenant_intern(tt, q, (size_t)l, &te);
    q += l;
    if (te) {
      te->key_est.store(est, std::memory_order_relaxed);
      te->demoted.store(dem != 0, std::memory_order_relaxed);
    }
    n++;
  }
  return n;
}

// Python-feed-path parse context (the ring engine sets it per datagram in
// vrm_worker_main): subsequent vt_feed calls parse as `name`. Empty name
// or disabled table -> default tenant / no tenant context.
void vt_set_tenant(void* hp, const char* name, int name_len) {
  auto* p = (Parser*)hp;
  TenantTable* tt = p->rt().tenants.get();
  if (!tt || !tt->enabled.load(std::memory_order_relaxed)) {
    p->cur_tenant = 0;
    p->cur_entry = nullptr;
    p->cur_demoted = false;
    return;
  }
  if (!name || name_len <= 0) {
    p->cur_tenant = 0;
    p->cur_entry = tt->dflt;
  } else {
    TenantEntry* te = nullptr;
    p->cur_tenant = tenant_intern(*tt, name, (size_t)name_len, &te);
    p->cur_entry = te;
  }
  p->cur_demoted =
      p->cur_entry && p->cur_entry->demoted.load(std::memory_order_relaxed);
}

// Drain this parser's exact demoted-row counts as parallel id/count
// arrays. Returns entries, or -entries_needed (nothing drained) when cap
// is too small. Python-feed-path counterpart of vrm_tenant_counters.
int vt_tenant_rows(void* hp, int32_t* ids, uint64_t* counts, int cap) {
  auto* p = (Parser*)hp;
  if (p->demoted_rows.empty()) return 0;
  if ((int)p->demoted_rows.size() > cap)
    return -(int)p->demoted_rows.size();
  int n = 0;
  for (auto& kv : p->demoted_rows) {
    ids[n] = kv.first;
    counts[n] = kv.second;
    n++;
  }
  p->demoted_rows.clear();
  return n;
}

// Standalone extraction (no parser handle) so tests can fuzz the exact
// C++ tenant_extract against the Python mirror. Returns the value length
// copied into out, 0 for default-tenant outcomes, -len_needed on a small
// cap.
int vt_tenant_extract(const char* tag, int tag_len, const char* data,
                      int len, char* out, int cap) {
  std::string t(tag ? tag : "", tag && tag_len > 0 ? (size_t)tag_len : 0);
  const char* v = nullptr;
  size_t vlen = 0;
  if (!data || len <= 0 || !tenant_extract(t, data, (size_t)len, &v, &vlen))
    return 0;
  if (vlen > (size_t)(cap > 0 ? cap : 0)) return -(int)vlen;
  memcpy(out, v, vlen);
  return (int)vlen;
}

// Drain-and-reset ring i's exact per-(tenant, class) admission deltas and
// its parser's demoted-row deltas, merged per tenant id. Output stride 7:
// [admitted self, high, low, shed self, high, low, demoted_rows]. Returns
// tenant count, or -count_needed (NOTHING drained) when cap is too small.
// Callers must fold across ALL rings, like vrm_admission_counters.
int vrm_tenant_counters(void* h, int ring, int32_t* ids, uint64_t* counts,
                        int cap) {
  auto* mr = (MultiRing*)h;
  Ring* r = mr->rings[ring].get();
  // r->mu guards adm.per_tenant, stage_mu guards parser.demoted_rows;
  // scoped_lock avoids ordering against the worker's r->mu -> stage_mu
  std::scoped_lock lk(r->mu, r->stage_mu);
  std::unordered_map<int32_t, std::array<uint64_t, 7>> acc;
  for (auto& kv : r->adm.per_tenant) {
    auto& row = acc[kv.first];
    for (int i = 0; i < 6; i++) row[i] += kv.second[i];
  }
  for (auto& kv : r->parser.demoted_rows) acc[kv.first][6] += kv.second;
  if ((int)acc.size() > cap) return -(int)acc.size();
  int n = 0;
  for (auto& kv : acc) {
    ids[n] = kv.first;
    memcpy(counts + (size_t)n * 7, kv.second.data(), 7 * sizeof(uint64_t));
    n++;
  }
  r->adm.per_tenant.clear();
  r->parser.demoted_rows.clear();
  return n;
}

void vrm_stop(void* h) {
  auto* mr = (MultiRing*)h;
  mr->stop.store(true);
  for (auto& r : mr->rings) {
    { std::lock_guard<std::mutex> lk(r->mu); }
    r->cv.notify_all();
    r->space_cv.notify_all();
  }
  { std::lock_guard<std::mutex> lk(mr->wait_mu); }
  mr->wait_cv.notify_all();
  for (auto& r : mr->rings) {
    if (r->reader.joinable()) r->reader.join();
    if (r->worker.joinable()) r->worker.join();
    if (r->fd >= 0) close(r->fd);
  }
  delete mr;
}

}  // extern "C"
