"""ctypes bindings for the native DogStatsD ingest engine (dogstatsd.cpp).

The .so is compiled on first import (g++ -O2, cached next to the source and
rebuilt when the source changes). `available()` gates the fast path: any
build/load failure falls back to the pure-Python parser with a warning —
semantics are identical (tests/test_native.py asserts parity).
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
from typing import List, Optional

import numpy as np

log = logging.getLogger("veneur_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "dogstatsd.cpp")
_lib = None
_load_err: Optional[str] = None

# rings_inject verdicts (dogstatsd.cpp ring_push2): BACKPRESSURE means a
# full ring refused the datagram WITHOUT counting it — pace and retry;
# REJECTED means it was counted (toolong or admission shed) and is gone.
INJECT_OK = 1
INJECT_REJECTED = 0
INJECT_BACKPRESSURE = -1


def _build_and_load():
    global _lib, _load_err
    if _lib is not None or _load_err is not None:
        return
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        # VENEUR_NATIVE_SANITIZE=1 builds with ASan+UBSan under a
        # distinct cache name so sanitized and plain processes never
        # race for the same .so. The loading process must arrange for
        # libasan to be resolvable (LD_PRELOAD under a non-instrumented
        # python) — see tests/test_native_sanitize.py.
        sanitize = os.environ.get("VENEUR_NATIVE_SANITIZE") == "1"
        prefix = "_dogstatsd_san_" if sanitize else "_dogstatsd_"
        so_path = os.path.join(_DIR, f"{prefix}{digest}.so")
        if not os.path.exists(so_path):
            for stale in os.listdir(_DIR):
                if (stale.startswith(prefix)
                        and stale.endswith(".so")
                        and stale != os.path.basename(so_path)):
                    try:
                        os.unlink(os.path.join(_DIR, stale))
                    except OSError:
                        pass
            # temp + rename so a concurrent process never dlopens a
            # half-written ELF
            tmp_path = f"{so_path}.{os.getpid()}.tmp"
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   "-pthread"]
            if sanitize:
                cmd += ["-g", "-fsanitize=address,undefined",
                        "-fno-sanitize-recover=all",
                        "-fno-omit-frame-pointer"]
            subprocess.run(cmd + ["-o", tmp_path, _SRC],
                           check=True, capture_output=True, timeout=120)
            os.replace(tmp_path, so_path)
        lib = ctypes.CDLL(so_path)
        lib.vt_new.restype = ctypes.c_void_p
        lib.vt_new.argtypes = [ctypes.c_uint32] * 5 + [ctypes.c_int] + \
            [ctypes.c_uint32] * 4
        lib.vt_free.argtypes = [ctypes.c_void_p]
        lib.vt_feed.restype = ctypes.c_int
        lib.vt_feed.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int, ctypes.c_int,
                                ctypes.POINTER(ctypes.c_int)]
        lib.vt_emit.argtypes = [ctypes.c_void_p] + \
            [ctypes.c_void_p] * 10 + [ctypes.POINTER(ctypes.c_uint32)]
        lib.vt_emit_packed.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32)]
        lib.vt_pending.restype = ctypes.c_int
        lib.vt_pending.argtypes = [ctypes.c_void_p]
        lib.vt_new_keys.restype = ctypes.c_int
        lib.vt_new_keys.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int]
        lib.vt_next_special.restype = ctypes.c_int
        lib.vt_next_special.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_int]
        lib.vt_slot_for.restype = ctypes.c_int32
        lib.vt_slot_for.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int)]
        lib.vt_reset.argtypes = [ctypes.c_void_p]
        lib.vt_shard_map_set.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.vt_capacity_set.argtypes = [ctypes.c_void_p] + \
            [ctypes.c_uint32] * 4
        lib.vt_table_stats.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint64)]
        lib.vt_stats.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_uint64)]
        lib.vr_start.restype = ctypes.c_void_p
        lib.vr_start.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_int),
                                 ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.vr_pump.restype = ctypes.c_int
        lib.vr_pump.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                ctypes.POINTER(ctypes.c_uint64)]
        lib.vr_counters.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint64)]
        lib.vr_stats.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_uint64)]
        lib.vr_admission_set.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_int]
        lib.vr_admission_counters.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.vr_stop.argtypes = [ctypes.c_void_p]
        lib.vt_hash64_batch.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.vi_import.restype = ctypes.c_int
        lib.vi_import.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.vi_stats.restype = ctypes.c_int
        lib.vi_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int]
        lib.vt_route_digest.restype = ctypes.c_uint32
        lib.vt_route_digest.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int]
        lib.vt_emit_sharded.argtypes = [ctypes.c_void_p] + \
            [ctypes.c_void_p] * 10 + [ctypes.POINTER(ctypes.c_int32),
                                      ctypes.POINTER(ctypes.c_uint32)]
        lib.vrm_start.restype = ctypes.c_void_p
        lib.vrm_start.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
        lib.vrm_n_rings.restype = ctypes.c_int
        lib.vrm_n_rings.argtypes = [ctypes.c_void_p]
        lib.vrm_inject.restype = ctypes.c_int
        lib.vrm_inject.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                   ctypes.c_char_p, ctypes.c_int]
        lib.vrm_wait.restype = ctypes.c_int
        lib.vrm_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.vrm_pending.restype = ctypes.c_int
        lib.vrm_pending.argtypes = [ctypes.c_void_p]
        lib.vrm_emit.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32)]
        lib.vrm_emit_sharded.argtypes = [ctypes.c_void_p, ctypes.c_int] + \
            [ctypes.c_void_p] * 10 + [ctypes.POINTER(ctypes.c_int32),
                                      ctypes.POINTER(ctypes.c_uint32)]
        lib.vrm_pause.argtypes = [ctypes.c_void_p]
        lib.vrm_resume.argtypes = [ctypes.c_void_p]
        lib.vrm_reset.argtypes = [ctypes.c_void_p]
        lib.vrm_shard_map_set.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.vrm_capacity_set.argtypes = [ctypes.c_void_p] + \
            [ctypes.c_uint32] * 4
        lib.vrm_table_stats.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_uint64)]
        lib.vrm_counters.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_uint64)]
        lib.vrm_ring_stats.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_uint64)]
        lib.vrm_admission_set.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_int]
        lib.vrm_admission_counters.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)]
        lib.vrm_stats.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint64)]
        lib.vrm_stop.argtypes = [ctypes.c_void_p]
        lib.vt_tenant_config.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_double, ctypes.c_uint32, ctypes.c_double,
            ctypes.c_double]
        lib.vt_tenant_params.argtypes = [
            ctypes.c_void_p, ctypes.c_double, ctypes.c_char_p, ctypes.c_int]
        lib.vt_tenant_names.restype = ctypes.c_int
        lib.vt_tenant_names.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_int]
        lib.vt_tenant_table.restype = ctypes.c_int
        lib.vt_tenant_table.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_int]
        lib.vt_tenant_restore.restype = ctypes.c_int
        lib.vt_tenant_restore.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_int]
        lib.vt_set_tenant.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int]
        lib.vt_tenant_rows.restype = ctypes.c_int
        lib.vt_tenant_rows.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
        lib.vt_tenant_extract.restype = ctypes.c_int
        lib.vt_tenant_extract.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int]
        lib.vrm_tenant_counters.restype = ctypes.c_int
        lib.vrm_tenant_counters.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
        _lib = lib
    except Exception as e:  # noqa: BLE001 — any failure => python fallback
        _load_err = str(e)
        log.warning("native ingest unavailable, using python parser: %s", e)


def available() -> bool:
    _build_and_load()
    return _lib is not None


KIND_NAMES = {0: "counter", 1: "gauge", 2: "histogram", 3: "set",
              4: "timer"}
KIND_IDS = {v: k for k, v in KIND_NAMES.items()}


def hash64_batch(members: List[bytes]) -> "np.ndarray":
    """FNV-1a 64 of each byte string, hashed in one C call (bit-identical
    to utils.hashing.fnv1a_64). Raises when the engine isn't built —
    callers gate on available()."""
    _build_and_load()
    if _lib is None:
        raise RuntimeError(f"native ingest unavailable: {_load_err}")
    n = len(members)
    buf = b"".join(members)
    offs = np.zeros(n + 1, np.int64)
    if n:
        np.cumsum([len(m) for m in members], out=offs[1:])
    out = np.empty(n, np.uint64)
    _lib.vt_hash64_batch(
        buf, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return out


def tenant_extract(tag: str, data: bytes) -> Optional[str]:
    """The C++ engine's tenant-tag extraction (vt_tenant_extract) exposed
    standalone: the value of the first well-formed `tag` occurrence in the
    raw datagram, or None for every default-tenant outcome (missing tag,
    empty/oversized/invalid-UTF-8 value, tag split by truncation). Tests
    fuzz this against reliability/tenancy.py extract_tenant for parity.
    Raises when the engine isn't built — callers gate on available()."""
    _build_and_load()
    if _lib is None:
        raise RuntimeError(f"native ingest unavailable: {_load_err}")
    tag_b = tag.encode("utf-8", "surrogateescape")
    out = ctypes.create_string_buffer(256)
    n = _lib.vt_tenant_extract(tag_b, len(tag_b), data, len(data), out,
                               len(out))
    if n <= 0:
        return None
    return out.raw[:n].decode("utf-8", "surrogateescape")


def route_digest(kind: str, name: str, joined_tags: str) -> int:
    """The C++ engine's routing digest (fnv1a-32 over name, kind, joined
    tags) — must be byte-identical to collective.keytable.route_digest;
    tests/test_native.py pins the parity over a fuzz corpus. Raises when
    the engine isn't built — callers gate on available()."""
    _build_and_load()
    if _lib is None:
        raise RuntimeError(f"native ingest unavailable: {_load_err}")
    name_b = name.encode("utf-8", "surrogateescape")
    kind_b = kind.encode("utf-8")
    tags_b = joined_tags.encode("utf-8", "surrogateescape")
    return int(_lib.vt_route_digest(name_b, len(name_b), kind_b,
                                    len(kind_b), tags_b, len(tags_b)))


def _tenant_merge(acc: dict, one: dict) -> None:
    """Accumulate one ring's per-tenant drain into a host-wide fold
    (ring_tenant_drain_one layout: nested admitted/shed class dicts plus
    a demoted_rows scalar)."""
    for tenant, ent in one.items():
        dst = acc.setdefault(tenant, {})
        for side in ("admitted", "shed"):
            for cls, n in ent.get(side, {}).items():
                d = dst.setdefault(side, {})
                d[cls] = d.get(cls, 0) + n
        if ent.get("demoted_rows"):
            dst["demoted_rows"] = (dst.get("demoted_rows", 0)
                                   + ent["demoted_rows"])


class NativeIngest:
    """One parser+keytable+stager instance (mirrors aggregation/host.py
    KeyTable+Batcher, but in C++)."""

    def __init__(self, spec, bspec, n_shards: int = 1):
        _build_and_load()
        if _lib is None:
            raise RuntimeError(f"native ingest unavailable: {_load_err}")
        self.spec = spec
        self.bspec = bspec
        self._h = _lib.vt_new(
            spec.counter_capacity, spec.gauge_capacity, spec.set_capacity,
            spec.histo_capacity, n_shards, spec.hll_precision,
            bspec.counter, bspec.gauge, bspec.set, bspec.histo)
        self._keybuf = ctypes.create_string_buffer(1 << 20)
        self._specialbuf = ctypes.create_string_buffer(1 << 16)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h and _lib is not None:
            _lib.vt_free(h)
            self._h = None

    def feed(self, data: bytes, start: int = 0) -> tuple:
        """Parse a packet buffer from byte offset `start`. Returns
        (full, consumed): full means a staging area filled and emit()
        should run; consumed is the absolute offset of the first
        unhandled byte — resume with feed(data, consumed) after emitting.
        The same bytes object is passed back unsliced, so a lane-full
        stop never copies a multi-KB remainder (same offset model as
        import_metriclist)."""
        consumed = ctypes.c_int(0)
        rc = _lib.vt_feed(self._h, data, len(data), start,
                          ctypes.byref(consumed))
        return bool(rc), consumed.value

    def emit_into(self, batcher_arrays) -> tuple:
        """Copy staged samples into numpy arrays. batcher_arrays is the
        tuple (c_slot, c_inc, g_slot, g_val, s_slot, s_reg, s_rho, h_slot,
        h_val, h_wt) of pre-sentinel-filled numpy arrays."""
        counts = (ctypes.c_uint32 * 4)()
        ptrs = [a.ctypes.data_as(ctypes.c_void_p) for a in batcher_arrays]
        _lib.vt_emit(self._h, *ptrs, counts)
        return tuple(counts)

    def emit_packed(self, flat: "np.ndarray", lane_offs: "np.ndarray",
                    prev_counts: "np.ndarray") -> tuple:
        """Zero-copy emit into a caller-owned flat i32 buffer laid out
        exactly like aggregation/step.py pack_batch. `lane_offs` is the
        int32[10] word offsets of the ten native lanes in that layout;
        `prev_counts` is this buffer's uint32[4] counts from ITS previous
        emit (updated in place — the engine re-sentinels only the rows the
        previous emit dirtied past the new counts). Returns (nc, ng, ns,
        nh) and resets staging."""
        counts = (ctypes.c_uint32 * 4)()
        _lib.vt_emit_packed(
            self._h,
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lane_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            prev_counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            counts)
        return tuple(counts)

    def emit_sharded(self, batcher_arrays, bounds: "np.ndarray") -> tuple:
        """Pre-sharded emit: like emit_into but rows arrive grouped by
        owner shard (stable, so arrival order — gauge LWW — is preserved
        within each shard) with slots rebased shard-local. `bounds`
        (int32[4*(n_shards+1)], kinds in counter/gauge/set/histo order)
        receives per-kind shard prefix bounds so per-shard batchers take
        contiguous slices with no argsort. Returns (nc, ng, ns, nh)."""
        counts = (ctypes.c_uint32 * 4)()
        ptrs = [a.ctypes.data_as(ctypes.c_void_p) for a in batcher_arrays]
        _lib.vt_emit_sharded(
            self._h, *ptrs,
            bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), counts)
        return tuple(counts)

    def pending(self) -> int:
        return _lib.vt_pending(self._h)

    def slot_for(self, kind: str, name: str, joined_tags: str, scope: int,
                 digest: int):
        """(slot, was_new) for a Python-side caller sharing the native slot
        space; slot is None at capacity."""
        was_new = ctypes.c_int(0)
        name_b = name.encode("utf-8", "surrogateescape")
        tags_b = joined_tags.encode("utf-8", "surrogateescape")
        slot = _lib.vt_slot_for(
            self._h, KIND_IDS[kind], scope, name_b, len(name_b),
            tags_b, len(tags_b), digest & 0xFFFFFFFF,
            ctypes.byref(was_new))
        return (None if slot < 0 else slot), bool(was_new.value)

    def drain_new_keys(self) -> List[tuple]:
        """[(kind, slot, scope, name, joined_tags, imported)] allocated
        since the last drain. The scope byte's bit 7 marks slots first
        created by the native import path (imported_only labeling)."""
        n = _lib.vt_new_keys(self._h, self._keybuf,
                             len(self._keybuf))
        if n < 0:
            self._keybuf = ctypes.create_string_buffer(-n * 2)
            n = _lib.vt_new_keys(self._h, self._keybuf, len(self._keybuf))
        out = []
        raw = self._keybuf.raw[:n]
        off = 0
        while off < n:
            kind = raw[off]
            slot = int.from_bytes(raw[off + 1:off + 5], "little",
                                  signed=True)
            scope = raw[off + 5] & 0x7F
            imported = bool(raw[off + 5] & 0x80)
            nl = int.from_bytes(raw[off + 6:off + 8], "little")
            name = raw[off + 8:off + 8 + nl].decode(
                "utf-8", "surrogateescape")
            off += 8 + nl
            tl = int.from_bytes(raw[off:off + 2], "little")
            tags = raw[off + 2:off + 2 + tl].decode(
                "utf-8", "surrogateescape")
            off += 2 + tl
            out.append((KIND_NAMES[kind], slot, scope, name, tags,
                        imported))
        return out

    def import_metriclist(self, data: bytes, offset: int = 0):
        """Decode + stage a serialized forwardrpc.MetricList starting at
        `offset` (the whole buffer is passed zero-copy; re-entry never
        re-slices a multi-MB remainder). Returns
        (handled_count, consumed_abs, fallback_spans, lane_full) —
        consumed_abs is the absolute offset fully handled (re-enter
        there after emitting when lane_full), fallback_spans is
        [(abs_off, length)] of Metric submessages for the Python path."""
        consumed = ctypes.c_int(0)
        n_fb = ctypes.c_int(0)
        full_stop = ctypes.c_int(0)
        fb_cap = 1024
        fb_off = (ctypes.c_int32 * fb_cap)()
        fb_len = (ctypes.c_int32 * fb_cap)()
        staged = _lib.vi_import(self._h, data, len(data), offset,
                                ctypes.byref(consumed), fb_off, fb_len,
                                fb_cap, ctypes.byref(n_fb),
                                ctypes.byref(full_stop))
        spans = [(fb_off[i], fb_len[i]) for i in range(n_fb.value)]
        return (staged, consumed.value, spans, bool(full_stop.value))

    def drain_import_stats(self):
        """(slots, mins, maxes, recip_corrs) numpy arrays of the
        per-imported-histogram scalar stats staged by import_metriclist."""
        cap = 4096
        slots = np.empty(cap, np.int32)
        mns = np.empty(cap, np.float32)
        mxs = np.empty(cap, np.float32)
        rc = np.empty(cap, np.float32)
        out = [[], [], [], []]
        while True:
            n = _lib.vi_stats(
                self._h,
                slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                mns.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                mxs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                rc.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), cap)
            if n <= 0:
                break
            out[0].append(slots[:n].copy())
            out[1].append(mns[:n].copy())
            out[2].append(mxs[:n].copy())
            out[3].append(rc[:n].copy())
            if n < cap:
                break
        if not out[0]:
            z = np.empty(0, np.float32)
            return np.empty(0, np.int32), z, z, z
        return tuple(np.concatenate(x) for x in out)

    def drain_specials(self) -> List[bytes]:
        """Event/service-check lines the C++ parser escalated."""
        out = []
        while True:
            n = _lib.vt_next_special(self._h, self._specialbuf,
                                     len(self._specialbuf))
            if n == 0:
                break
            if n < 0:
                self._specialbuf = ctypes.create_string_buffer(-n * 2)
                continue
            out.append(self._specialbuf.raw[:n])
        return out

    def reset(self):
        r = getattr(self, "_rings", None)
        if r:
            # clears the master tables AND every ring's key-replica cache;
            # callers hold the rings_pause() quiesce across this
            _lib.vrm_reset(r)
        else:
            _lib.vt_reset(self._h)

    def shard_map_set(self, n_shards: int):
        """Stage a shard-map change; it takes effect at the next reset()
        (i.e. inside the swap quiesce), never immediately. Only
        veneur_tpu/reshard/quiesce.py may call this — vtlint's
        reshard-quiesce pass enforces the boundary."""
        r = getattr(self, "_rings", None)
        if r:
            _lib.vrm_shard_map_set(r, int(n_shards))
        else:
            _lib.vt_shard_map_set(self._h, int(n_shards))

    def capacity_set(self, counter: int, gauge: int, set_: int,
                     histo: int):
        """Stage new per-kind table capacities (0 = keep current); they
        take effect at the next reset() (i.e. inside the swap quiesce),
        never immediately. Only veneur_tpu/tables/growth.py may call
        this — vtlint's table-grow-quiesce pass enforces the boundary."""
        r = getattr(self, "_rings", None)
        if r:
            _lib.vrm_capacity_set(r, int(counter), int(gauge), int(set_),
                                  int(histo))
        else:
            _lib.vt_capacity_set(self._h, int(counter), int(gauge),
                                 int(set_), int(histo))

    def table_stats(self) -> dict:
        """Per-kind key-table occupancy for the growth planner:
        {kind: (allocated, dropped, capacity)} over the engine's four
        tables. Locks the key tables shared — safe alongside ring
        parsing."""
        s = (ctypes.c_uint64 * 12)()
        r = getattr(self, "_rings", None)
        if r:
            _lib.vrm_table_stats(r, s)
        else:
            _lib.vt_table_stats(self._h, s)
        kinds = ("counter", "gauge", "set", "histo")
        return {k: (int(s[i * 3]), int(s[i * 3 + 1]), int(s[i * 3 + 2]))
                for i, k in enumerate(kinds)}

    def stats(self) -> dict:
        s = (ctypes.c_uint64 * 3)()
        r = getattr(self, "_rings", None)
        if r:
            _lib.vrm_stats(r, s)  # summed over ring parsers + master
        else:
            _lib.vt_stats(self._h, s)
        return {"processed": s[0], "parse_errors": s[1], "dropped": s[2]}

    # -- native UDP reader group (vr_* in dogstatsd.cpp) --------------------

    def readers_start(self, fds: List[int], max_len: int = 65536,
                      ring_cap: int = 65536) -> None:
        """Spawn one C++ recvmmsg thread per fd, feeding the shared
        datagram ring drained by pump(). Each fd is dup()ed into C++
        ownership (vr_start), so the Python sockets may be closed at any
        time after this returns; the dups are released by
        readers_stop()."""
        arr = (ctypes.c_int * len(fds))(*fds)
        self._readers = _lib.vr_start(self._h, arr, len(fds), max_len,
                                      ring_cap)

    def pump(self, max_wait_ms: int) -> tuple:
        """Drain queued datagrams into staging (blocks in C++ with the GIL
        released while the ring is idle). Returns (full, stats) where full
        means a staging lane filled — emit and call pump(0) again — and
        stats is {parsed, ring_depth, ring_dropped, datagrams}."""
        out = (ctypes.c_uint64 * 4)()
        full = _lib.vr_pump(self._readers, max_wait_ms, out)
        return bool(full), {"parsed": out[0], "ring_depth": out[1],
                            "ring_dropped": out[2], "datagrams": out[3]}

    def reader_counters(self) -> dict:
        """Live reader counters, callable from any thread. With the
        multi-ring engine the totals are exact sums over every ring."""
        m = getattr(self, "_rings", None)
        if m:
            agg = {"datagrams": 0, "ring_dropped": 0, "ring_depth": 0,
                   "toolong": 0}
            out = (ctypes.c_uint64 * 4)()
            for i in range(self._n_rings):
                _lib.vrm_counters(m, i, out)
                agg["datagrams"] += out[0]
                agg["ring_dropped"] += out[1]
                agg["ring_depth"] += out[2]
                agg["toolong"] += out[3]
            return agg
        r = getattr(self, "_readers", None)
        if not r:
            return {"datagrams": 0, "ring_dropped": 0, "ring_depth": 0,
                    "toolong": 0}
        out = (ctypes.c_uint64 * 4)()
        _lib.vr_counters(r, out)
        return {"datagrams": out[0], "ring_dropped": out[1],
                "ring_depth": out[2], "toolong": out[3]}

    def ring_stats(self) -> dict:
        """Deep ring/emit telemetry snapshot, callable from any thread
        (one C++ lock, no hot-path cost): ring depth + high-water, pump
        batch/stall counts, emit_packed call/ns totals, datagram and
        ring-drop totals. Zeros when no reader group is running. With the
        multi-ring engine, counters are exact cross-ring sums and
        ring_depth/ring_highwater aggregate as sum/max."""
        m = getattr(self, "_rings", None)
        if m:
            agg = {"ring_depth": 0, "ring_highwater": 0,
                   "pump_batches": 0, "pump_stalls": 0,
                   "emit_packed_calls": 0, "emit_packed_ns": 0,
                   "datagrams": 0, "ring_dropped": 0}
            for per in self.ring_stats_per_ring():
                agg["ring_depth"] += per["ring_depth"]
                agg["ring_highwater"] = max(agg["ring_highwater"],
                                            per["ring_highwater"])
                agg["pump_batches"] += per["pump_batches"]
                agg["pump_stalls"] += per["pump_stalls"]
                agg["emit_packed_calls"] += per["emit_packed_calls"]
                agg["emit_packed_ns"] += per["emit_packed_ns"]
                agg["datagrams"] += per["datagrams"]
                agg["ring_dropped"] += per["ring_dropped"]
            return agg
        r = getattr(self, "_readers", None)
        if not r:
            return {"ring_depth": 0, "ring_highwater": 0,
                    "pump_batches": 0, "pump_stalls": 0,
                    "emit_packed_calls": 0, "emit_packed_ns": 0,
                    "datagrams": 0, "ring_dropped": 0}
        out = (ctypes.c_uint64 * 8)()
        _lib.vr_stats(r, out)
        return {"ring_depth": out[0], "ring_highwater": out[1],
                "pump_batches": out[2], "pump_stalls": out[3],
                "emit_packed_calls": out[4], "emit_packed_ns": out[5],
                "datagrams": out[6], "ring_dropped": out[7]}

    def admission_set(self, enabled: bool, state: int, rate: float,
                      burst: float, high_tags) -> None:
        """Push the OverloadController's statsd admission knobs into the
        reader ring (called from the controller poll thread). high_tags is
        an iterable of shed_priority_tags strings. With the multi-ring
        engine, rate/burst split evenly across rings inside the C++ so the
        host-level admit rate matches the single-ring contract."""
        joined = "\n".join(high_tags).encode("utf-8", "surrogateescape")
        m = getattr(self, "_rings", None)
        if m:
            _lib.vrm_admission_set(m, 1 if enabled else 0, int(state),
                                   float(rate), float(burst), joined,
                                   len(joined))
            return
        r = getattr(self, "_readers", None)
        if not r:
            return
        _lib.vr_admission_set(r, 1 if enabled else 0, int(state),
                              float(rate), float(burst), joined,
                              len(joined))

    def admission_drain(self) -> dict:
        """Drain-and-reset exact per-class ring admission deltas:
        {"admitted": {class: n}, "shed": {class: n}} with zero entries
        omitted (classes: self/high/low, mirroring PriorityClassifier).
        With the multi-ring engine, the per-class deltas are drained from
        EVERY ring and summed so the invariant sent == toolong + admitted
        + shed holds host-wide."""
        names = ("self", "high", "low")
        m = getattr(self, "_rings", None)
        if m:
            adm = [0, 0, 0]
            shed = [0, 0, 0]
            tenants: dict = {}
            for i in range(self._n_rings):
                one = self.ring_admission_drain_one(i)
                for c in range(3):
                    adm[c] += one["admitted"].get(names[c], 0)
                    shed[c] += one["shed"].get(names[c], 0)
                _tenant_merge(tenants, one.get("tenants", {}))
            d = {
                "admitted": {names[i]: adm[i] for i in range(3) if adm[i]},
                "shed": {names[i]: shed[i] for i in range(3) if shed[i]},
            }
            if tenants:
                d["tenants"] = tenants
            return d
        r = getattr(self, "_readers", None)
        if not r:
            return {"admitted": {}, "shed": {}}
        out = (ctypes.c_uint64 * 6)()
        _lib.vr_admission_counters(r, out)
        return {
            "admitted": {names[i]: out[i] for i in range(3) if out[i]},
            "shed": {names[i]: out[3 + i] for i in range(3) if out[3 + i]},
        }

    def readers_stop(self) -> None:
        r = getattr(self, "_readers", None)
        if r:
            _lib.vr_stop(r)
            self._readers = None
        m = getattr(self, "_rings", None)
        if m:
            _lib.vrm_stop(m)
            self._rings = None
            self._n_rings = 0

    # -- multi-ring engine (vrm_* in dogstatsd.cpp) -------------------------

    @property
    def n_rings(self) -> int:
        """Rings in the multi-ring engine; 0 when it isn't running."""
        return getattr(self, "_n_rings", 0) if getattr(
            self, "_rings", None) else 0

    def rings_start(self, n_rings: int, fds=None, max_len: int = 65536,
                    ring_cap: int = 65536, pin_cores=None) -> None:
        """Start the multi-ring engine: one ring + parser thread pair per
        entry (vrm_start), all sharing this instance's key tables. fds[i]
        >= 0 attaches a dup()ed SO_REUSEPORT socket to ring i; None/-1
        entries make inject-only rings (benches, tests use rings_inject
        for deterministic placement). pin_cores[i] >= 0 pins ring i's
        reader+worker threads to that core."""
        fd_arr = (ctypes.c_int * n_rings)(
            *[(fds[i] if fds is not None and i < len(fds)
               and fds[i] is not None else -1) for i in range(n_rings)])
        pin_arr = None
        if pin_cores:
            pin_arr = (ctypes.c_int * n_rings)(
                *[(pin_cores[i] if i < len(pin_cores) else -1)
                  for i in range(n_rings)])
        self._rings = _lib.vrm_start(self._h, fd_arr, n_rings, max_len,
                                     ring_cap, pin_arr)
        self._n_rings = n_rings

    def rings_inject(self, ring: int, data: bytes) -> int:
        """Queue one datagram onto ring i through the same toolong/
        admission/ring-cap accounting as the socket path. Returns a
        verdict: INJECT_OK (1) queued; INJECT_REJECTED (0) counted and
        dropped (toolong or admission shed — the datagrams == toolong +
        admitted + shed identity holds); INJECT_BACKPRESSURE (-1) the
        ring is full and NOTHING was counted — the caller still owns the
        datagram and should pace, then retry. Retrying a BACKPRESSURE
        verdict never double-counts (the old bool return counted the
        datagram before the ring-full check, so pace-and-retry loops
        inflated the received count)."""
        return int(_lib.vrm_inject(self._rings, ring, data, len(data)))

    def rings_wait(self, max_wait_ms: int) -> int:
        """Block (GIL released) until a ring stalls on full staging or
        staging runs rich, or the timeout passes. Returns the number of
        stalled rings."""
        return _lib.vrm_wait(self._rings, max_wait_ms)

    def rings_pending(self) -> int:
        """Staged rows across all rings (racy snapshot, idle heuristic)."""
        return _lib.vrm_pending(self._rings)

    def rings_emit(self, ring: int, flat: "np.ndarray",
                   lane_offs: "np.ndarray",
                   prev_counts: "np.ndarray") -> tuple:
        """emit_packed for ring i's staging into its packed arena row
        (same layout/sentinel contract as emit_packed; `flat` is the
        ring's row view of the (rings, words) arena)."""
        counts = (ctypes.c_uint32 * 4)()
        _lib.vrm_emit(
            self._rings, ring,
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lane_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            prev_counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            counts)
        return tuple(counts)

    def rings_emit_sharded(self, ring: int, batcher_arrays,
                           bounds: "np.ndarray") -> tuple:
        """emit_sharded for ring i's staging: rows grouped by owner shard
        with shard-local slots and per-kind shard bounds — the sharded
        backend's per-ring drain."""
        counts = (ctypes.c_uint32 * 4)()
        ptrs = [a.ctypes.data_as(ctypes.c_void_p) for a in batcher_arrays]
        _lib.vrm_emit_sharded(
            self._rings, ring, *ptrs,
            bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), counts)
        return tuple(counts)

    def rings_pause(self) -> None:
        """Swap-boundary quiesce: no ring worker parses again until
        rings_resume(). Emit every ring, then reset(), inside this."""
        _lib.vrm_pause(self._rings)

    def rings_resume(self) -> None:
        _lib.vrm_resume(self._rings)

    def ring_counters_one(self, ring: int) -> dict:
        """Per-ring reader counters (reader_counters layout)."""
        out = (ctypes.c_uint64 * 4)()
        _lib.vrm_counters(self._rings, ring, out)
        return {"datagrams": out[0], "ring_dropped": out[1],
                "ring_depth": out[2], "toolong": out[3]}

    def ring_stats_one(self, ring: int) -> dict:
        """Per-ring deep telemetry (ring_stats layout)."""
        out = (ctypes.c_uint64 * 8)()
        _lib.vrm_ring_stats(self._rings, ring, out)
        return {"ring_depth": out[0], "ring_highwater": out[1],
                "pump_batches": out[2], "pump_stalls": out[3],
                "emit_packed_calls": out[4], "emit_packed_ns": out[5],
                "datagrams": out[6], "ring_dropped": out[7]}

    def ring_stats_per_ring(self) -> List[dict]:
        """ring_stats_one for every ring (empty when not multi-ring)."""
        if not getattr(self, "_rings", None):
            return []
        out = []
        for i in range(self._n_rings):
            out.append(self.ring_stats_one(i))
        return out

    def ring_admission_drain_one(self, ring: int) -> dict:
        """Drain-and-reset ring i's exact per-class admission deltas
        (admission_drain layout), plus — when the tenant table is live —
        a "tenants" sub-dict of per-tenant admitted/shed/demoted_rows
        deltas drained through the SAME per-ring fold point. Callers must
        fold across ALL rings — use admission_drain() for the exact
        host-wide sum."""
        out = (ctypes.c_uint64 * 6)()
        _lib.vrm_admission_counters(self._rings, ring, out)
        names = ("self", "high", "low")
        d = {
            "admitted": {names[i]: out[i] for i in range(3) if out[i]},
            "shed": {names[i]: out[3 + i] for i in range(3) if out[3 + i]},
        }
        if getattr(self, "_tenant_names", None) is not None:
            tenants = self.ring_tenant_drain_one(ring)
            if tenants:
                d["tenants"] = tenants
        return d

    # -- multi-tenant identity / fairness / quarantine ----------------------

    def tenant_config(self, enabled: bool, tag: str = "tenant:",
                      burst_mult: float = 2.0, q_max_keys: int = 0,
                      q_decay: float = 0.5,
                      q_readmit_frac: float = 0.5) -> None:
        """Create/configure the tenant table on the master parser. Must
        run before rings_start — the tag is read lock-free on the
        admission path. Interns the default tenant as id 0."""
        tag_b = tag.encode("utf-8", "surrogateescape")
        _lib.vt_tenant_config(self._h, 1 if enabled else 0, tag_b,
                              len(tag_b), float(burst_mult),
                              int(q_max_keys), float(q_decay),
                              float(q_readmit_frac))
        if getattr(self, "_tenant_names", None) is None:
            self._tenant_names = {0: "default"}

    def tenant_params(self, base_rate: float, weights: dict) -> None:
        """Per-poll push: base admit rate (tokens/s per unit weight; <=0
        disables the fairness buckets) and {tenant: weight} overrides.
        Unknown names are interned so weights precede first traffic."""
        blob = "".join(
            f"{name}\t{float(w)}\n" for name, w in weights.items()
        ).encode("utf-8", "surrogateescape")
        _lib.vt_tenant_params(self._h, float(base_rate), blob, len(blob))

    def _tenant_refresh_names(self) -> None:
        """Drain newly interned (id, name) pairs into the local map."""
        cap = 4096
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = _lib.vt_tenant_names(self._h, buf, cap)
            if n >= 0:
                break
            cap = -n * 2
        raw = buf.raw
        off = 0
        for _ in range(n):
            tid = int.from_bytes(raw[off:off + 4], "little", signed=True)
            ln = int.from_bytes(raw[off + 4:off + 6], "little")
            self._tenant_names[tid] = raw[off + 6:off + 6 + ln].decode(
                "utf-8", "surrogateescape")
            off += 6 + ln

    def _tenant_name(self, tid: int) -> str:
        name = self._tenant_names.get(tid)
        if name is None:
            self._tenant_refresh_names()
            name = self._tenant_names.get(tid, f"tenant#{tid}")
        return name

    def tenant_table(self) -> dict:
        """Non-destructive snapshot of every interned tenant:
        {name: {"demoted": bool, "key_est": float}} (checkpoint +
        quarantine telemetry source)."""
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = _lib.vt_tenant_table(self._h, buf, cap)
            if n >= 0:
                break
            cap = -n * 2
        raw = buf.raw
        out = {}
        off = 0
        for _ in range(n):
            tid = int.from_bytes(raw[off:off + 4], "little", signed=True)
            demoted = raw[off + 4] != 0
            est = np.frombuffer(raw[off + 5:off + 13], "<f8")[0]
            ln = int.from_bytes(raw[off + 13:off + 15], "little")
            name = raw[off + 15:off + 15 + ln].decode(
                "utf-8", "surrogateescape")
            off += 15 + ln
            self._tenant_names[tid] = name
            out[name] = {"demoted": demoted, "key_est": float(est)}
        return out

    def tenant_restore(self, entries) -> int:
        """Restore quarantine state from a checkpoint: entries is an
        iterable of (name, demoted, key_est) in snapshot order — names
        re-intern in that order, reproducing the snapshot's ids. Returns
        entries applied."""
        parts = []
        for name, demoted, est in entries:
            nb = name.encode("utf-8", "surrogateescape")
            parts.append(bytes([1 if demoted else 0]))
            parts.append(np.float64(est).tobytes())
            parts.append(len(nb).to_bytes(2, "little"))
            parts.append(nb)
        blob = b"".join(parts)
        n = int(_lib.vt_tenant_restore(self._h, blob, len(blob)))
        self._tenant_refresh_names()
        return n

    def set_tenant(self, name: str) -> None:
        """Python-feed-path parse context: subsequent feed() calls parse
        as `name` (empty -> default tenant). The ring engine resolves
        identity itself in ring_push; this is for the fallback path and
        tests."""
        nb = name.encode("utf-8", "surrogateescape")
        _lib.vt_set_tenant(self._h, nb, len(nb))

    def tenant_rows_drain(self) -> dict:
        """Drain-and-reset the master parser's exact demoted-row counts
        ({tenant: rows}) staged by the Python feed path."""
        cap = 64
        while True:
            ids = (ctypes.c_int32 * cap)()
            counts = (ctypes.c_uint64 * cap)()
            n = _lib.vt_tenant_rows(self._h, ids, counts, cap)
            if n >= 0:
                break
            cap = -n * 2
        return {self._tenant_name(ids[i]): int(counts[i])
                for i in range(n)}

    def ring_tenant_drain_one(self, ring: int) -> dict:
        """Drain-and-reset ring i's exact per-tenant deltas:
        {tenant: {"admitted": {class: n}, "shed": {class: n},
        "demoted_rows": n}} with zero entries omitted. Callers must fold
        across ALL rings (ring_admission_drain_one / admission_drain do)."""
        cap = getattr(self, "_tenant_cap", 64)
        while True:
            ids = (ctypes.c_int32 * cap)()
            counts = (ctypes.c_uint64 * (cap * 7))()
            n = _lib.vrm_tenant_counters(self._rings, ring, ids, counts,
                                         cap)
            if n >= 0:
                break
            cap = -n * 2
        self._tenant_cap = cap
        names = ("self", "high", "low")
        out = {}
        for i in range(n):
            row = counts[i * 7:(i + 1) * 7]
            adm = {names[c]: int(row[c]) for c in range(3) if row[c]}
            shed = {names[c]: int(row[3 + c]) for c in range(3)
                    if row[3 + c]}
            ent = {}
            if adm:
                ent["admitted"] = adm
            if shed:
                ent["shed"] = shed
            if row[6]:
                ent["demoted_rows"] = int(row[6])
            if ent:
                out[self._tenant_name(ids[i])] = ent
        return out
