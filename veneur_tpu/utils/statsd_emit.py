"""Plain-DogStatsD UDP emission shared by the server's stats_address
mirror and the proxy's runtime-metrics ticker (reference: statsd.New
clients at server.go:297 and proxy.go:213 — one shared client library
there, one shared helper here, so line format / chunking / addressing
can't drift between the two daemons)."""

from __future__ import annotations

import socket
from typing import List, Tuple

# the reference's datadog statsd client batches messages per payload;
# 25 short lines stays far under any sane MTU the way the server's
# mirror always has
LINES_PER_DATAGRAM = 25


def parse_addr(stats_address: str) -> Tuple[str, int]:
    """host:port with the host defaulting to loopback (`:8125` and
    `8125` both mean 127.0.0.1:8125, matching the server mirror)."""
    host, _, port = stats_address.rpartition(":")
    return (host or "127.0.0.1", int(port))


def format_line(name: str, value: float, type_char: str,
                tags: str = "") -> bytes:
    """One DogStatsD line; values use repr(float) like the server
    mirror (full round-trip precision, no scientific surprises for
    the magnitudes self-metrics carry)."""
    line = b"%s:%s|%s" % (name.encode(), repr(float(value)).encode(),
                          type_char.encode())
    if tags:
        line += b"|#" + tags.encode()
    return line


def send_lines(sock: socket.socket, dest: Tuple[str, int],
               lines: List[bytes]) -> None:
    for i in range(0, len(lines), LINES_PER_DATAGRAM):
        sock.sendto(b"\n".join(lines[i:i + LINES_PER_DATAGRAM]), dest)


def current_rss_bytes() -> float:
    """Resident set size, CURRENT not peak: /proc/self/statm page count
    on Linux; getrusage peak (KiB on Linux, bytes on macOS) as the
    fallback where /proc is absent."""
    try:
        with open("/proc/self/statm", "rb") as f:
            pages = int(f.read().split()[1])
        import os
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        import resource
        import sys
        ru = resource.getrusage(resource.RUSAGE_SELF)
        scale = 1 if sys.platform == "darwin" else 1024
        return float(ru.ru_maxrss * scale)


def runtime_gauges() -> tuple:
    """(rss_bytes, total_gc_collections) — the ONE place the "CPython
    equivalent of Go's HeapAlloc/NumGC" mapping lives (reference
    flusher.go:36-43 and proxy.go:656 both report these; Go's
    PauseTotalNs has no CPython counterpart — collections are not
    stop-the-world-timed — and is deliberately not faked)."""
    import gc
    return (current_rss_bytes(),
            float(sum(s["collections"] for s in gc.get_stats())))
