"""Crash reporting (reference sentry.go).

`consume_panic` mirrors ConsumePanic (sentry.go:16-51): synchronously ship
the exception to Sentry, then re-raise — crash-only design; process
supervision restarts. `SentryLogHandler` is the logrus-hook analogue
(sentry.go:54+): Error-and-above log records also ship.

The Sentry client is a minimal store-API POST (no raven dependency): DSN
`https://<key>@<host>/<project>` → POST /api/<project>/store/ with
X-Sentry-Auth. Failures to report are swallowed — crash reporting must
never mask the crash itself.
"""

from __future__ import annotations

import json
import logging
import socket
import sys
import time
import traceback
import urllib.request
from typing import Optional
from urllib.parse import urlparse

log = logging.getLogger("veneur_tpu.crash")


class SentryClient:
    def __init__(self, dsn: str):
        u = urlparse(dsn)
        if not (u.scheme and u.username and u.path.strip("/")):
            raise ValueError("invalid sentry DSN")
        self.key = u.username
        self.project = u.path.strip("/")
        port = f":{u.port}" if u.port else ""
        self.store_url = (f"{u.scheme}://{u.hostname}{port}"
                          f"/api/{self.project}/store/")

    def capture_exception(self, exc: BaseException,
                          level: str = "fatal") -> None:
        frames = [{"filename": f.filename, "function": f.name,
                   "lineno": f.lineno}
                  for f in traceback.extract_tb(exc.__traceback__)]
        self._send({
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "level": level,
            "platform": "python",
            "server_name": socket.gethostname(),
            "exception": {"values": [{
                "type": type(exc).__name__,
                "value": str(exc),
                "stacktrace": {"frames": frames},
            }]},
        })

    def capture_message(self, message: str, level: str = "error") -> None:
        self._send({
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "level": level,
            "platform": "python",
            "server_name": socket.gethostname(),
            "message": message,
        })

    def _send(self, event: dict) -> None:
        auth = (f"Sentry sentry_version=7, sentry_key={self.key}, "
                f"sentry_client=veneur-tpu/0.1")
        req = urllib.request.Request(
            self.store_url, data=json.dumps(event).encode(), method="POST",
            headers={"Content-Type": "application/json",
                     "X-Sentry-Auth": auth})
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                resp.read()
        except Exception as e:  # never mask the original failure
            log.debug("sentry report failed: %s", e)


_client: Optional[SentryClient] = None


def setup(dsn: str) -> Optional[SentryClient]:
    """Install the global client + the Error-and-above logging hook."""
    global _client
    if not dsn:
        return None
    _client = SentryClient(dsn)
    logging.getLogger().addHandler(SentryLogHandler(_client))
    return _client


class SentryLogHandler(logging.Handler):
    def __init__(self, client: SentryClient):
        super().__init__(level=logging.ERROR)
        self.client = client

    def emit(self, record):
        try:
            self.client.capture_message(
                self.format(record),
                level="fatal" if record.levelno >= logging.CRITICAL
                else "error")
        except Exception:
            pass


def consume_panic(exc: BaseException) -> None:
    """reference sentry.go:16 ConsumePanic: synchronous capture, then
    re-raise (the process dies; supervision restarts it)."""
    if _client is not None:
        try:
            _client.capture_exception(exc)
        except Exception:
            pass
    raise exc


def hook_threads() -> None:
    """Ship uncaught thread exceptions before the default handling —
    the goroutine-wrapping the reference does in every `go` callsite."""
    prev = getattr(sys, "__veneur_prev_threadhook__", None)
    if prev is not None:
        return
    import threading
    original = threading.excepthook
    sys.__veneur_prev_threadhook__ = original

    def hooked(args):
        if _client is not None and args.exc_value is not None:
            try:
                _client.capture_exception(args.exc_value)
            except Exception:
                pass
        original(args)

    threading.excepthook = hooked
