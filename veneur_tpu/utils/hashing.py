"""Hash functions for metric keying.

The reference shards every hop by a 32-bit FNV-1a digest over
name + type + sorted-joined-tags (reference samplers/parser.go:325-420 and
importsrv/server.go:141-148), and hashes set members with a 64-bit hash for
HyperLogLog insertion. We keep identical digest semantics (FNV-1a 32) so a
deployment can mix reference and TPU instances behind one proxy, and use
FNV-1a 64 + a splitmix64 finalizer for HLL member hashing (any well-mixed
64-bit hash family gives the same HLL error envelope).
"""

from __future__ import annotations

FNV32_OFFSET = 0x811C9DC5
FNV32_PRIME = 0x01000193
FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3
_M64 = (1 << 64) - 1


def fnv1a_32(data: bytes, h: int = FNV32_OFFSET) -> int:
    for b in data:
        h ^= b
        h = (h * FNV32_PRIME) & 0xFFFFFFFF
    return h


def fnv1a_64(data: bytes, h: int = FNV64_OFFSET) -> int:
    for b in data:
        h ^= b
        h = (h * FNV64_PRIME) & _M64
    return h


def splitmix64(x: int) -> int:
    """Finalizer to decorrelate FNV's weak low bits before HLL splitting."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def hll_reg_rho(member: bytes, precision: int):
    """(register index, rho) for one set member — host half of the HLL insert
    (device half is ops/hll.insert_batch)."""
    h = splitmix64(fnv1a_64(member))
    reg = h >> (64 - precision)
    rest = (h << precision) & _M64
    if rest == 0:
        rho = 64 - precision + 1
    else:
        rho = min(64 - rest.bit_length(), 64 - precision) + 1
    return reg, rho
