"""Hash functions for metric keying.

The reference shards every hop by a 32-bit FNV-1a digest over
name + type + sorted-joined-tags (reference samplers/parser.go:325-420 and
importsrv/server.go:141-148), and hashes set members with MetroHash64
(seed 1337) for HyperLogLog insertion (its vendored
axiomhq/hyperloglog hashFunc). We keep BOTH identical: the FNV-1a 32
digest so a deployment can mix reference and TPU instances behind one
proxy, and the metro member hash so set sketches union correctly across a
mixed fleet — with different member hashes the same user id would land in
different registers on the two implementations and the merged estimate
would double-count.
"""

from __future__ import annotations

FNV32_OFFSET = 0x811C9DC5
FNV32_PRIME = 0x01000193
FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3
_M64 = (1 << 64) - 1


def fnv1a_32(data: bytes, h: int = FNV32_OFFSET) -> int:
    for b in data:
        h ^= b
        h = (h * FNV32_PRIME) & 0xFFFFFFFF
    return h


def fnv1a_64(data: bytes, h: int = FNV64_OFFSET) -> int:
    for b in data:
        h ^= b
        h = (h * FNV64_PRIME) & _M64
    return h


def splitmix64(x: int) -> int:
    """Finalizer to decorrelate FNV's weak low bits before HLL splitting."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def _rotr(x: int, r: int) -> int:
    return ((x >> r) | (x << (64 - r))) & _M64


def metro_hash_64(data: bytes, seed: int = 1337) -> int:
    """MetroHash64 (J. Andrew Rogers' public-domain algorithm).

    This is the HLL member hash of the reference's vendored
    axiomhq/hyperloglog (hashFunc = metro Hash64 with seed 1337); set
    members must hash identically across a mixed fleet or merged sketches
    double-count common members.
    """
    k0, k1, k2, k3 = 0xD6D018F5, 0xA2AA033B, 0x62992FC1, 0x30BC5B29
    h = ((seed + k2) * k0) & _M64
    n = len(data)
    i = 0

    def u64(j):
        return int.from_bytes(data[j:j + 8], "little")

    if n >= 32:
        v0 = v1 = v2 = v3 = h
        while n - i >= 32:
            v0 = (v0 + u64(i) * k0) & _M64
            v0 = (_rotr(v0, 29) + v2) & _M64
            v1 = (v1 + u64(i + 8) * k1) & _M64
            v1 = (_rotr(v1, 29) + v3) & _M64
            v2 = (v2 + u64(i + 16) * k2) & _M64
            v2 = (_rotr(v2, 29) + v0) & _M64
            v3 = (v3 + u64(i + 24) * k3) & _M64
            v3 = (_rotr(v3, 29) + v1) & _M64
            i += 32
        v2 ^= (_rotr(((v0 + v3) * k0 + v1) & _M64, 37) * k1) & _M64
        v3 ^= (_rotr(((v1 + v2) * k1 + v0) & _M64, 37) * k0) & _M64
        v0 ^= (_rotr(((v0 + v2) * k0 + v3) & _M64, 37) * k1) & _M64
        v1 ^= (_rotr(((v1 + v3) * k1 + v2) & _M64, 37) * k0) & _M64
        h = (h + (v0 ^ v1)) & _M64
    if n - i >= 16:
        w0 = (h + u64(i) * k2) & _M64
        w0 = (_rotr(w0, 29) * k3) & _M64
        w1 = (h + u64(i + 8) * k2) & _M64
        w1 = (_rotr(w1, 29) * k3) & _M64
        w0 ^= (_rotr((w0 * k0) & _M64, 21) + w1) & _M64
        w1 ^= (_rotr((w1 * k3) & _M64, 21) + w0) & _M64
        h = (h + w1) & _M64
        i += 16
    if n - i >= 8:
        h = (h + u64(i) * k3) & _M64
        h ^= (_rotr(h, 55) * k1) & _M64
        i += 8
    if n - i >= 4:
        h = (h + int.from_bytes(data[i:i + 4], "little") * k3) & _M64
        h ^= (_rotr(h, 26) * k1) & _M64
        i += 4
    if n - i >= 2:
        h = (h + int.from_bytes(data[i:i + 2], "little") * k3) & _M64
        h ^= (_rotr(h, 48) * k1) & _M64
        i += 2
    if n - i >= 1:
        h = (h + data[i] * k3) & _M64
        h ^= (_rotr(h, 37) * k1) & _M64
    h ^= _rotr(h, 28)
    h = (h * k0) & _M64
    h ^= _rotr(h, 29)
    return h


def hll_reg_rho(member: bytes, precision: int):
    """(register index, rho) for one set member — host half of the HLL insert
    (device half is ops/hll.insert_batch). Index/rho split follows the
    reference sketch's getPosVal (top p bits → register; rho = clz of the
    rest + 1, capped at 64-p+1), on the metro member hash."""
    h = metro_hash_64(member)
    reg = h >> (64 - precision)
    rest = (h << precision) & _M64
    if rest == 0:
        rho = 64 - precision + 1
    else:
        rho = min(64 - rest.bit_length(), 64 - precision) + 1
    return reg, rho
