"""Compensated float32 accumulation for TPU.

TPUs have no fast float64; the reference keeps counter values as int64 and
histogram scalar aggregates as float64 (reference samplers/samplers.go:131,
477-481). To preserve the same effective precision over a flush interval we
store running sums as an unevaluated pair (hi, lo) of float32 — "two-float"
(double-single) arithmetic. Error-free transformation via Knuth's TwoSum,
so each accumulated addition is exact to ~48 bits of significand, well above
what a 10s flush interval of increments needs.
"""

from __future__ import annotations

import jax.numpy as jnp


def two_sum(a, b):
    """Knuth TwoSum: returns (s, err) with s = fl(a+b) and a+b = s + err exactly."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def twofloat_add(hi, lo, x):
    """Add x to the two-float accumulator (hi, lo). Returns new (hi, lo)."""
    s, e = two_sum(hi, x)
    lo = lo + e
    # renormalize so hi carries the leading bits
    hi, e2 = two_sum(s, lo)
    return hi, e2


def twofloat_total(hi, lo):
    """Collapse the accumulator to a single float (float32)."""
    return hi + lo


def twofloat_merge(hi_a, lo_a, hi_b, lo_b):
    """Merge two accumulators (e.g. across devices)."""
    hi, lo = two_sum(hi_a, hi_b)
    return twofloat_add(hi, lo, lo_a + lo_b)
