"""Compensated float32 accumulation for TPU.

TPUs have no fast float64; the reference keeps counter values as int64 and
histogram scalar aggregates as float64 (reference samplers/samplers.go:131,
477-481). To preserve the same effective precision over a flush interval we
store running sums as an unevaluated pair (hi, lo) of float32 — "two-float"
(double-single) arithmetic. Error-free transformation via Knuth's TwoSum,
so each accumulated addition is exact to ~48 bits of significand.

The exactness envelope vs the reference's int64 (the documented deviation;
tested in tests/test_aggregation.py::test_counter_exactness_envelope*):

- Each batch's scatter-adds land in a plain-f32 `*_acc` array that is
  folded into the pair INSIDE the same ingest program (step.py
  ingest_core), so the f32 accumulator never spans more than one batch
  and the pair absorbs every batch total via error-free TwoSum.
- A batch is exact while each (slot, batch) duplicate-sum stays within
  f32's exact range for its granularity (unit increments: < 2^24 hits
  on one slot in one batch). Past that, the rounding happens inside the
  XLA scatter itself; summed over an interval the relative error is
  bounded by 2^-25 (each batch contributes <= ulp(batch_slot_total)/2
  and the pair carries batch totals exactly).
- The pair carries ~48 significand bits; unit-increment interval totals
  through ~2.8e14 stay exact — the reference's int64 overflows later
  (2^63) but a 10s interval approaches neither bound.
- The pair must leave the device UNCOLLAPSED: hi + lo in f32 rounds back
  to 24 bits. flush_core ships (hi, lo) and the host combines in float64
  (aggregation/step.py combine_flush_scalars); the cross-replica merge
  folds pairs with compensated merges (parallel/sharded.py pair_total)
  instead of a plain f32 psum.
"""

from __future__ import annotations



def two_sum(a, b):
    """Knuth TwoSum: returns (s, err) with s = fl(a+b) and a+b = s + err exactly."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def twofloat_add(hi, lo, x):
    """Add x to the two-float accumulator (hi, lo). Returns new (hi, lo)."""
    s, e = two_sum(hi, x)
    lo = lo + e
    # renormalize so hi carries the leading bits
    hi, e2 = two_sum(s, lo)
    return hi, e2


def twofloat_total(hi, lo):
    """Collapse the accumulator to a single float (float32)."""
    return hi + lo


def twofloat_merge(hi_a, lo_a, hi_b, lo_b):
    """Merge two accumulators (e.g. across devices)."""
    hi, lo = two_sum(hi_a, hi_b)
    return twofloat_add(hi, lo, lo_a + lo_b)
