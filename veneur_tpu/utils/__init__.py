from veneur_tpu.utils.numerics import (
    two_sum,
    twofloat_add,
    twofloat_merge,
    twofloat_total,
)

__all__ = ["two_sum", "twofloat_add", "twofloat_merge", "twofloat_total"]
