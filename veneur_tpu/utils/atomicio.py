"""Crash-safe file writes: temp file in the target directory + os.replace.

POSIX rename is atomic within a filesystem, so readers observe either the
old file or the complete new one — never a torn write. The temp file MUST
live in the destination's directory (rename across filesystems is a
copy, not atomic), and durability additionally needs an fsync of the file
before the rename and of the directory after it (the rename itself is
metadata the directory owns). Shared by the checkpoint codec's manifest
(persistence/codec.py), the localfile sink, and the S3 plugin's local
staging.
"""

from __future__ import annotations

import os
import tempfile


def fsync_dir(path: str) -> None:
    """Flush a directory's metadata (new/renamed entries) to disk.
    Best-effort: some filesystems refuse O_RDONLY fsync on directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    """Write `data` to `path` such that a crash at any instant leaves
    either the previous content or the full new content."""
    dirpath = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=".tmp.", suffix=".partial",
                               dir=dirpath)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if fsync:
        fsync_dir(dirpath)


def atomic_append_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    """Append with the same all-or-nothing guarantee: existing content +
    `data` land via one rename, so a crash mid-append can never leave a
    half-written record at the tail (a plain "ab" write can). Costs a
    read of the existing file — appropriate for interval-cadence flush
    files, not per-sample logs."""
    try:
        with open(path, "rb") as f:
            prev = f.read()
    except FileNotFoundError:
        prev = b""
    atomic_write_bytes(path, prev + data, fsync=fsync)
