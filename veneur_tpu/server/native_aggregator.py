"""Native-ingest aggregation backend.

Wire packets are parsed, keyed, and staged entirely in C++
(veneur_tpu/native/dogstatsd.cpp); the Python side only moves completed
batches to the device. Python-originated samples (imports, span-extracted
metrics, service checks) share the same slot space through vt_slot_for and
stage through the ordinary Python Batcher — both batch streams feed the
same jitted ingest step.

Slot metadata (SlotMeta for flush labeling) is reconstructed lazily from
the C++ engine's new-key records; status checks keep a pure-Python table
(they never ride the native wire path's kinds).

Known imprecisions, documented:

- A histo slot first created by the import path and later hit by native
  wire samples keeps imported_only=True for the interval (the native path
  doesn't report per-slot direct-hit sets), so its aggregates are
  suppressed on a global tier — strictly conservative (percentiles still
  flush).
- Gauge last-write-wins is per-stream: when the same gauge key arrives
  both over the wire (native staging) and via Python-side paths
  (span-extracted/imported) in one interval, the flush order is
  deterministic (native batch first, Python batch second → Python-side
  write wins) but not arrival-ordered across the two streams. The
  single-stream case — by far the common one — is exactly ordered.
- A corrupt MetricList tail is a PARTIAL apply: import_pb_bytes stages
  incrementally, so metrics decoded before the undecodable boundary are
  already merged when the tail is dropped-and-counted, where the Python
  path's whole-request deserialize would reject ALL of them. Every
  intact metric is preserved either way; the difference is only which
  side of a mid-request corruption survives. (PARITY.md pins this with
  the other native-path deviations.)
"""

from __future__ import annotations

import logging
from typing import List

import numpy as np

from veneur_tpu.aggregation.host import (
    Batcher, BatchSpec, KeyTable, SlotMeta, _KindTable)
from veneur_tpu.aggregation.state import TableSpec
from veneur_tpu.native import NativeIngest
from veneur_tpu.server.aggregator import Aggregator
from veneur_tpu.server.sharded_aggregator import ShardedAggregator

log = logging.getLogger("veneur_tpu.server.native_aggregator")


class NativeKeyTable:
    """KeyTable facade over the C++ slot maps + a Python status table."""

    def __init__(self, spec: TableSpec, eng: NativeIngest, n_shards: int):
        self.spec = spec
        self.eng = eng
        self.n_shards = n_shards
        self.status = _KindTable(spec.status_capacity, n_shards)
        # drained metadata: kind-table name -> [(slot, SlotMeta)]
        self.meta = {"counter": [], "gauge": [], "set": [], "histo": []}
        self.by_slot = {"counter": {}, "gauge": {}, "set": {}, "histo": {}}
        self._finalized = False

    _TABLE = staticmethod(KeyTable._table_name)

    def _drain(self):
        if self._finalized:
            return
        for kind, slot, scope, name, joined, imported in \
                self.eng.drain_new_keys():
            tname = self._TABLE(kind)
            if slot in self.by_slot[tname]:
                # registered python-side with the exact tag tuple already
                continue
            # flush labels use the FIRST arrival's tags, matching the
            # reference's one-sampler-per-MetricKey semantics. Deliberate
            # deviation: an empty tag SECTION (`|#`) and no section both
            # serialize to joined == "" in the C++ key record, so the
            # label here is () where the reference would keep [""] when
            # the empty section arrived first — a cosmetic empty tag on
            # a pathological packet shape; the key identity (and the
            # digest) agree with the reference either way.
            m = SlotMeta(name=name,
                         tags=tuple(joined.split(",")) if joined else (),
                         scope=scope, kind=kind, joined_tags=joined,
                         imported_only=imported)
            self.meta[tname].append((slot, m))
            self.by_slot[tname][slot] = m

    def slot_for(self, kind: str, name: str, tags: tuple, scope: int,
                 digest: int, hostname: str = "", imported: bool = False,
                 joined_tags=None):
        if kind == "status":
            # joined-string identity, same as host.py KeyTable and the
            # C++ engine's keybuf (reference MetricKey.JoinedTags)
            key = (kind, name, joined_tags if joined_tags is not None
                   else ",".join(tags))
            slot = self.status.by_key.get(key)
            if slot is not None:
                return slot
            return self.status.alloc(key, digest, name, tags, scope, kind,
                                     hostname=hostname)
        joined = joined_tags if joined_tags is not None else ",".join(tags)
        slot, was_new = self.eng.slot_for(kind, name, joined, scope, digest)
        if slot is not None and was_new:
            # register the exact tuple now — tags from SSF maps may contain
            # commas, which a joined-string round-trip would corrupt
            tname = self._TABLE(kind)
            m = SlotMeta(name=name, tags=tags, scope=scope, kind=kind,
                         hostname=hostname, imported_only=imported,
                         joined_tags=joined)
            self.meta[tname].append((slot, m))
            self.by_slot[tname][slot] = m
        return slot

    def get_meta(self, kind: str):
        self._drain()
        if kind == "status":
            return self.status.meta
        return self.meta[self._TABLE(kind)]

    def meta_for_slot(self, kind: str, slot: int):
        if kind == "status":
            return self.status.by_slot.get(slot)
        self._drain()
        return self.by_slot[self._TABLE(kind)].get(slot)

    def dropped(self) -> int:
        return self.eng.stats()["dropped"] + self.status.dropped

    def finalize(self):
        """Detach: absorb remaining key records, stop draining (the engine's
        maps are about to be reset for the next interval)."""
        self._drain()
        self._finalized = True


class NativeAggregator(Aggregator):
    def __init__(self, spec: TableSpec, bspec: BatchSpec = BatchSpec(),
                 n_shards: int = 1, compact_every: int = 8, engine=None):
        super().__init__(spec, bspec, n_shards, compact_every)
        # live resharding passes the OLD aggregator's engine: the C++
        # reader rings/sockets keep feeding the same handle across the
        # rebuild (its staged shard map was applied by the reset inside
        # the drain swap), so ingest never restarts
        self.eng = engine if engine is not None \
            else NativeIngest(spec, bspec, n_shards)
        self.table = NativeKeyTable(spec, self.eng, n_shards)
        self._alloc_packed_buffers()
        if engine is not None and self.eng.n_rings:
            # engine reuse across a live reshard / table grow with the
            # multi-ring readers still running: rings_start (which
            # normally allocates the per-ring arenas) will not run again
            # on the rebuilt backend, so allocate them here
            self._alloc_ring_arenas(self.eng.n_rings)

    def _alloc_ring_arenas(self, n_rings: int):
        """Per-ring staging plan: two (rings, words) i32 arenas — one row
        per ring in the exact packed layout — double-buffered like the
        single-ring pair. Every ring's emit lands in its own row and the
        WHOLE arena crosses host->device as one donated transfer per step
        (ingest_step_packed_rings), so R rings cost one h2d RTT, not R.
        Row sentinels and per-row prev counts follow the vt_emit_packed
        incremental-restore contract per ring."""
        from veneur_tpu.aggregation.step import packed_layout
        layout, words = packed_layout(self._pk_sizes)
        self._rg_bufs = []
        self._rg_prev = []
        for _ in range(2):
            arena = np.zeros((n_rings, words), np.int32)
            for r in range(n_rings):
                self._init_packed_sentinels(arena[r], layout, self.spec)
            self._rg_bufs.append(arena)
            self._rg_prev.append(np.zeros((n_rings, 4), np.uint32))
        self._rg_idx = 0

    def _alloc_packed_buffers(self):
        """Two flat i32 host buffers in the exact pack_batch device layout,
        plus the lane word-offsets vt_emit_packed writes at. The native
        emit is zero-copy: C++ writes staged rows straight into one of
        these (double-buffered — the engine stages batch N+1 while batch
        N's h2d + donated step is in flight) and the buffer goes to
        ingest_step_packed as-is; no Batch pytree, no per-lane copies, no
        Python repack. All 16 lanes are present at the Python Batcher's
        sizes so the compile key (spec, sizes) matches the Python path
        and ONE compiled ingest program serves both — the status and
        histo_stat lanes never ride the native wire path and stay
        Python-initialized constant sentinel regions that C++ never
        touches."""
        from veneur_tpu.aggregation.step import packed_layout
        b, spec = self.bspec, self.spec
        # lane sizes in Batch._fields order — identical to batch_sizes()
        # of a Python Batcher emit, which is what keys the compiled step
        sizes = (b.counter, b.counter, b.gauge, b.gauge,
                 b.status, b.status, b.set, b.set, b.set,
                 b.histo, b.histo, b.histo,
                 b.histo_stat, b.histo_stat, b.histo_stat, b.histo_stat)
        layout, words = packed_layout(sizes)
        self._pk_sizes = sizes
        # the ten lanes the C++ engine stages, in vt_emit_packed's
        # argument order; the interleaved status/histo_stat lane offsets
        # stay Python-owned
        self._pk_offs = np.asarray(
            [layout[name][0] for name in (
                "counter_slot", "counter_inc", "gauge_slot", "gauge_val",
                "set_slot", "set_reg", "set_rho", "histo_slot",
                "histo_val", "histo_wt")], np.int32)
        self._pk_bufs = []
        self._pk_prev = []
        for _ in range(2):
            flat = np.zeros(words, np.int32)
            self._init_packed_sentinels(flat, layout, spec)
            self._pk_bufs.append(flat)
            # per-buffer staged-row counts from that buffer's previous
            # emit — vt_emit_packed's incremental sentinel-restore bound
            self._pk_prev.append(np.zeros(4, np.uint32))
        self._pk_idx = 0

    @staticmethod
    def _init_packed_sentinels(flat, layout, spec):
        """One-time sentinel fill of a fresh packed buffer: every slot
        lane at its table capacity (scatter mode='drop' padding), weight
        lanes 0, histo-stat min/max at +/-inf — the state Batcher.emit's
        partial reset maintains on the Python path. After this, the six
        C++-maintained lanes are kept in this state incrementally by
        vt_emit_packed and the status/histo_stat regions are never
        written again."""

        def lane(name, value, f32=False):
            off, n, _ = layout[name]
            view = flat[off:off + n]
            (view.view(np.float32) if f32 else view)[:] = value

        lane("counter_slot", spec.counter_capacity)
        lane("gauge_slot", spec.gauge_capacity)
        lane("set_slot", spec.set_capacity)
        lane("histo_slot", spec.histo_capacity)
        lane("status_slot", spec.status_capacity)
        lane("histo_stat_slot", spec.histo_capacity)
        lane("histo_stat_min", np.inf, f32=True)
        lane("histo_stat_max", -np.inf, f32=True)

    # -- wire path -----------------------------------------------------------
    def feed(self, data: bytes) -> List[bytes]:
        """Parse a packet buffer natively; returns escalated event/service-
        check lines for the caller to handle via the Python parser. A
        lane-full stop resumes at the consumed offset — the buffer is
        never re-sliced (NativeIngest.feed offset contract)."""
        full, off = self.eng.feed(data)
        while full:
            self._emit_native()
            full, off = self.eng.feed(data, off)
        return self.eng.drain_specials()

    def _emit_native(self):
        import time

        from veneur_tpu.aggregation.step import ingest_step_packed
        from veneur_tpu.observability import jaxruntime
        from veneur_tpu.server.aggregator import _SYNC_EVERY
        idx = self._pk_idx
        flat = self._pk_bufs[idx]
        nc, ng, ns, nh = self.eng.emit_packed(flat, self._pk_offs,
                                              self._pk_prev[idx])
        if nc + ng + ns + nh == 0:
            return
        self._pk_idx = 1 - idx
        self._steps += 1
        self.steps_total += 1
        flat[0] = 1 if self._steps % self.compact_every == 0 else 0
        self.h2d_bytes += flat.nbytes
        t0 = time.perf_counter_ns()
        self.state = ingest_step_packed(
            self.state, flat, spec=self.spec, sizes=self._pk_sizes)
        dispatch_dt = time.perf_counter_ns() - t0
        self.dispatch_ns += dispatch_dt
        if self.steps_total % _SYNC_EVERY == 0:
            self.step_ns += dispatch_dt + jaxruntime.sync_and_time(
                self.state)
            self.steps_synced += 1

    def extra_parse_errors(self) -> int:
        return self.eng.stats()["parse_errors"]

    # -- native import path (global tier) ------------------------------
    def import_pb_bytes(self, data: bytes):
        """Decode + stage a serialized forwardrpc.MetricList with the
        C++ engine (VERDICT r04 #5: the gRPC decode→slot path batched
        the way wire ingest staging is; reference importsrv/server.go:97
        SendMetrics). Counters/gauges/digests stage natively; sets,
        valueless metrics, and oneof/type mismatches fall back to the
        Python import_into path so error accounting matches the
        reference's per-metric semantics. Returns (metrics, errors)."""
        from veneur_tpu.forward.convert import import_into
        from veneur_tpu.proto import metricpb_pb2 as mpb
        total = 0
        errors = 0
        off = 0
        while off < len(data):
            staged, new_off, spans, lane_full = \
                self.eng.import_metriclist(data, off)
            total += staged + len(spans)
            for so, sl in spans:
                try:
                    import_into(self, mpb.Metric.FromString(
                        data[so:so + sl]))
                except Exception as e:
                    errors += 1
                    log.warning("bad imported metric (native path): %s",
                                e)
            if new_off >= len(data):
                break
            if not lane_full and new_off == off and staged == 0 \
                    and not spans:
                # undecodable at a top-level boundary (NOT a lane stop):
                # the Python deserializer would reject the whole request
                # — count one error and drop the remainder
                errors += 1
                log.warning("undecodable MetricList tail at offset %d "
                            "(%d bytes dropped)", off, len(data) - off)
                break
            # staging filled (or the fallback buffer did): free the
            # lanes, then re-enter at the reported boundary
            self._emit_native()
            off = new_off
        # per-digest exact min/max/recip ride the Python stats lane —
        # scatter min/max/add are order-independent vs the centroid
        # re-add, so batch boundaries don't matter
        slots, mns, mxs, rcs = self.eng.drain_import_stats()
        if len(slots):
            self.batcher.add_histo_stats_bulk(slots, mns, mxs, rcs)
        return total, errors

    # -- native UDP reader group ---------------------------------------------
    def readers_start(self, fds, max_len: int = 65536,
                      ring_cap: int = 65536, n_rings: int = 1,
                      pin_cores=None, force_rings: bool = False) -> None:
        """Start the native readers. n_rings == 1 keeps the proven
        single-ring vr_* engine (N reader threads -> one ring -> this
        thread's pump); n_rings > 1 starts the multi-ring vrm_* engine:
        one ring + parser + packed arena row per reader core, fds
        distributed round-robin across rings (each SO_REUSEPORT fd owns
        its ring), optional sched_affinity pinning per ring.
        force_rings routes even a 1-ring config through the vrm engine —
        tenant fairness lives only there (the vr_* path stays
        tenant-blind), so a tenancy-enabled server must set it."""
        if n_rings <= 1 and not force_rings:
            self.eng.readers_start(fds, max_len=max_len, ring_cap=ring_cap)
            return
        # every fd must own a ring (vrm readers are 1:1 with rings) — a
        # multi-address bind with more sockets than configured rings
        # grows the ring count rather than orphaning listeners
        n_rings = max(n_rings, len(fds) if fds else 0)
        self.rings_start(n_rings, fds=fds, max_len=max_len,
                         ring_cap=ring_cap, pin_cores=pin_cores)

    def rings_start(self, n_rings: int, fds=None, max_len: int = 65536,
                    ring_cap: int = 65536, pin_cores=None) -> None:
        """Multi-ring engine start (fd-less rings accept rings_inject only
        — bench/test entry). Allocates the per-ring arena pair."""
        self.eng.rings_start(n_rings, fds=fds, max_len=max_len,
                             ring_cap=ring_cap, pin_cores=pin_cores)
        self._alloc_ring_arenas(n_rings)

    def _emit_rings(self) -> bool:
        """Drain every ring's staging into the current arena's rows and
        run ONE device step over the whole arena. Returns False (no step)
        when all rings were empty — the common idle poll. The compact
        control word rides row 0 only."""
        import time

        from veneur_tpu.aggregation.step import ingest_step_packed_rings
        from veneur_tpu.observability import jaxruntime
        from veneur_tpu.server.aggregator import _SYNC_EVERY
        idx = self._rg_idx
        arena = self._rg_bufs[idx]
        prev = self._rg_prev[idx]
        total = 0
        for r in range(self.eng.n_rings):
            counts = self.eng.rings_emit(r, arena[r], self._pk_offs,
                                         prev[r])
            total += counts[0] + counts[1] + counts[2] + counts[3]
        if total == 0:
            return False
        self._rg_idx = 1 - idx
        self._steps += 1
        self.steps_total += 1
        arena[0, 0] = 1 if self._steps % self.compact_every == 0 else 0
        self.h2d_bytes += arena.nbytes
        t0 = time.perf_counter_ns()
        self.state = ingest_step_packed_rings(
            self.state, arena, spec=self.spec, sizes=self._pk_sizes)
        dispatch_dt = time.perf_counter_ns() - t0
        self.dispatch_ns += dispatch_dt
        if self.steps_total % _SYNC_EVERY == 0:
            self.step_ns += dispatch_dt + jaxruntime.sync_and_time(
                self.state)
            self.steps_synced += 1
        return True

    def pump(self, max_wait_ms: int, max_emits: int = 8) -> List[bytes]:
        """Drain the C++ datagram ring(s) into staging (GIL released while
        idle), emitting device batches whenever a lane fills. Bounded:
        under sustained overload an unbounded drain would never return to
        the pipeline dispatch loop and flush requests (which ride
        packet_queue) would starve — exactly when operators most need the
        flush. Returns escalated event/service-check lines."""
        if self.eng.n_rings:
            self.eng.rings_wait(max_wait_ms)
            for _ in range(max_emits):
                if not self._emit_rings():
                    break
            return self.eng.drain_specials()
        full, st = self.eng.pump(max_wait_ms)
        for _ in range(max_emits):
            if not full:
                break
            self._emit_native()
            full, st = self.eng.pump(0)
        if full:
            # leave staging drained so the next call ingests immediately
            self._emit_native()
        return self.eng.drain_specials()

    def reader_counters(self) -> dict:
        return self.eng.reader_counters()

    def ring_stats(self) -> dict:
        """Deep ring/emit telemetry (vr_stats): depth, high-water, pump
        batches/stalls, emit_packed call/ns totals. Any thread. In
        multi-ring mode this is the EXACT cross-ring aggregate (sums;
        high-water is the per-ring max)."""
        return self.eng.ring_stats()

    def ring_stats_per_ring(self) -> List[dict]:
        """Per-ring telemetry rows ([] outside multi-ring mode) — the
        `ring=<i>`-labeled collector family reads these."""
        return self.eng.ring_stats_per_ring()

    def admission_set(self, enabled: bool, state: int, rate: float,
                      burst: float, high_tags) -> None:
        """Push OverloadController statsd-admission knobs into the C++
        reader ring (tentpole (c): shedding runs in-engine, off-GIL)."""
        self.eng.admission_set(enabled, state, rate, burst, high_tags)

    def admission_drain(self) -> dict:
        """Exact per-class {admitted, shed} deltas since the last drain."""
        return self.eng.admission_drain()

    # -- tenant fairness/quarantine push-down (reliability/tenancy.py) -------
    def tenant_config(self, *a, **kw) -> None:
        """One-shot tenant-table creation; must land before rings start."""
        self.eng.tenant_config(*a, **kw)

    def tenant_params(self, base_rate: float, weights) -> None:
        self.eng.tenant_params(base_rate, weights)

    def tenant_table(self) -> dict:
        """Non-destructive {tenant: {demoted, key_est}} engine snapshot."""
        return self.eng.tenant_table()

    def tenant_restore(self, entries) -> int:
        return self.eng.tenant_restore(entries)

    def tenant_rows_drain(self) -> dict:
        return self.eng.tenant_rows_drain()

    def readers_stop(self) -> None:
        self.eng.readers_stop()

    # `processed` spans both ingest paths: the C++ engine's count plus the
    # Python-side samples (imports, extracted metrics, service checks).
    @property
    def processed(self):
        native = self.eng.stats()["processed"] if hasattr(self, "eng") else 0
        return self._py_processed + native

    @processed.setter
    def processed(self, v):
        native = self.eng.stats()["processed"] if hasattr(self, "eng") else 0
        self._py_processed = v - native

    # dropped spans both paths too: engine drops + python-side drops
    # (status-table capacity, import drops)
    @property
    def dropped_capacity(self):
        native = self.eng.stats()["dropped"] if hasattr(self, "eng") else 0
        return self._py_dropped + native

    @dropped_capacity.setter
    def dropped_capacity(self, v):
        native = self.eng.stats()["dropped"] if hasattr(self, "eng") else 0
        self._py_dropped = v - native

    # -- flush ---------------------------------------------------------------
    def swap(self):
        rings = bool(self.eng.n_rings)
        if rings:
            # quiesce: no ring worker parses between here and resume, so
            # staged rows can't race the table reset below. Datagrams
            # queued (or parked mid-parse on a lane stop) during the pause
            # are parsed after resume and land in the NEXT interval —
            # the same boundary semantics as the single-ring pump queue.
            self.eng.rings_pause()
            self._emit_rings()
        self._emit_native()
        detached = self.table
        detached.finalize()
        state, _ = super().swap()
        # super() replaced self.table with a fresh Python KeyTable; the
        # native engine keeps the slot space, so re-wrap it post-reset
        self.eng.reset()
        self.table = NativeKeyTable(self.spec, self.eng, self.n_shards)
        if rings:
            self.eng.rings_resume()
        return state, detached

    def query_snapshot(self):
        """Live snapshot: emit natively staged rows first. Rings are NOT
        paused — nothing resets here, so datagrams parsed after this
        instant simply land after the snapshot (the ring-path analogue
        of packet-queue FIFO ordering)."""
        if self.eng.n_rings:
            self._emit_rings()
        self._emit_native()
        return super().query_snapshot()


class NativeShardedAggregator(ShardedAggregator):
    """Mesh-sharded backend fed by the C++ parse/key/stage engine.

    The engine's slot space is shard-aware (dogstatsd.cpp KindTable:
    slot = shard*per_shard + local, same rule as aggregation/host.py), so
    its emitted global slots split into (shard, local) with two vectorized
    numpy ops and bulk-copy into the per-shard staging batchers — the 30x
    C++ host path and the multi-device mesh compose instead of excluding
    each other."""

    def __init__(self, spec: TableSpec, bspec: BatchSpec = BatchSpec(),
                 n_shards: int = 2, compact_every: int = 8,
                 preshard: bool = False, engine=None):
        super().__init__(spec, bspec, n_shards, compact_every)
        # engine reuse across a live reshard — see NativeAggregator
        self.eng = engine if engine is not None \
            else NativeIngest(spec, bspec, n_shards)
        self.table = NativeKeyTable(spec, self.eng, n_shards)
        self._py_processed = 0
        self._py_dropped = 0
        self.preshard = preshard
        self._ps_bounds = np.zeros(4 * (n_shards + 1), np.int32)
        self._alloc_emit_buffers()

    def _alloc_emit_buffers(self):
        """Staging targets for emit_into — the sharded backend re-stages
        emitted rows into per-shard Python Batchers (the per-shard packed
        layout differs from the engine's global slot space), so it keeps
        the array-based emit rather than the single backend's direct
        packed emit. Only the ten native lanes are needed; slot lanes are
        re-sentineled per emit below."""
        b = self.bspec
        self._c_slot = np.empty(b.counter, np.int32)
        self._c_inc = np.zeros(b.counter, np.float32)
        self._g_slot = np.empty(b.gauge, np.int32)
        self._g_val = np.zeros(b.gauge, np.float32)
        self._s_slot = np.empty(b.set, np.int32)
        self._s_reg = np.zeros(b.set, np.int32)
        self._s_rho = np.zeros(b.set, np.uint8)
        self._h_slot = np.empty(b.histo, np.int32)
        self._h_val = np.zeros(b.histo, np.float32)
        self._h_wt = np.zeros(b.histo, np.float32)

    # engine-backed stats (same split as NativeAggregator)
    extra_parse_errors = NativeAggregator.extra_parse_errors
    processed = NativeAggregator.processed
    dropped_capacity = NativeAggregator.dropped_capacity
    feed = NativeAggregator.feed

    _PER_SHARD_FIELD = {"counter": "counter_capacity",
                        "gauge": "gauge_capacity",
                        "status": "status_capacity",
                        "set": "set_capacity",
                        "histo": "histo_capacity"}

    def _local(self, kind: str, slot: int):
        """global slot -> (shard, local). ShardedAggregator reads per-shard
        widths off its Python KeyTable; here the table is a NativeKeyTable
        (no .tables), but the widths are statically the per-shard spec's
        capacities — the C++ engine allocates with the identical
        shard*per_shard+local rule (dogstatsd.cpp KindTable)."""
        per = getattr(self.pspec,
                      self._PER_SHARD_FIELD[KeyTable._table_name(kind)])
        return slot // per, slot % per

    def _split_shards(self, global_slots, per_shard):
        """One-pass shard split of a staged slot lane: a stable argsort
        groups rows by shard (stability preserves arrival order within a
        shard — gauge last-write-wins depends on it), searchsorted finds
        the [start, end) bounds per shard. Replaces the per-shard
        boolean-mask loop, which scanned the whole lane n_shards times.
        Returns (order, local_slots_sorted, bounds)."""
        sh = global_slots // per_shard
        order = np.argsort(sh, kind="stable")
        lo = (global_slots - sh * per_shard).astype(np.int32, copy=False)
        bounds = np.searchsorted(sh, np.arange(self.n_shards + 1),
                                 sorter=order)
        return order, lo[order], bounds

    def _native_lanes(self):
        return (self._c_slot, self._c_inc, self._g_slot, self._g_val,
                self._s_slot, self._s_reg, self._s_rho, self._h_slot,
                self._h_val, self._h_wt)

    def _stage_presharded(self, nc, ng, ns, nh):
        """Bulk-copy a pre-sharded emit (vt_emit_sharded contract: rows
        grouped by owner shard, slots already shard-local, per-kind shard
        bounds in self._ps_bounds) into the per-shard batchers. Contiguous
        slices only — the argsort/searchsorted of _split_shards and the
        local-slot subtraction both happened in C++ during the one pass
        the emit copy already makes."""
        b = self._ps_bounds
        S = self.n_shards
        if nc:
            at = b[0:S + 1]
            for i in range(S):
                if at[i + 1] > at[i]:
                    self.batchers[i].add_counters_bulk(
                        self._c_slot[at[i]:at[i + 1]],
                        self._c_inc[at[i]:at[i + 1]])
        if ng:
            at = b[S + 1:2 * (S + 1)]
            for i in range(S):
                if at[i + 1] > at[i]:
                    self.batchers[i].add_gauges_bulk(
                        self._g_slot[at[i]:at[i + 1]],
                        self._g_val[at[i]:at[i + 1]])
        if ns:
            at = b[2 * (S + 1):3 * (S + 1)]
            for i in range(S):
                if at[i + 1] > at[i]:
                    self.batchers[i].add_sets_bulk(
                        self._s_slot[at[i]:at[i + 1]],
                        self._s_reg[at[i]:at[i + 1]],
                        self._s_rho[at[i]:at[i + 1]])
        if nh:
            at = b[3 * (S + 1):4 * (S + 1)]
            for i in range(S):
                if at[i + 1] > at[i]:
                    self.batchers[i].add_histos_bulk(
                        self._h_slot[at[i]:at[i + 1]],
                        self._h_val[at[i]:at[i + 1]],
                        self._h_wt[at[i]:at[i + 1]])

    def _emit_presharded(self):
        nc, ng, ns, nh = self.eng.emit_sharded(self._native_lanes(),
                                               self._ps_bounds)
        if nc + ng + ns + nh:
            self._stage_presharded(nc, ng, ns, nh)

    def _emit_native(self):
        if self.preshard:
            return self._emit_presharded()
        nc, ng, ns, nh = self.eng.emit_into(
            (self._c_slot, self._c_inc, self._g_slot, self._g_val,
             self._s_slot, self._s_reg, self._s_rho, self._h_slot,
             self._h_val, self._h_wt))
        if nc + ng + ns + nh == 0:
            return
        p = self.pspec
        if nc:
            order, lo, at = self._split_shards(self._c_slot[:nc],
                                               p.counter_capacity)
            inc = self._c_inc[:nc][order]
            for i in range(self.n_shards):
                if at[i + 1] > at[i]:
                    self.batchers[i].add_counters_bulk(
                        lo[at[i]:at[i + 1]], inc[at[i]:at[i + 1]])
        if ng:
            order, lo, at = self._split_shards(self._g_slot[:ng],
                                               p.gauge_capacity)
            val = self._g_val[:ng][order]
            for i in range(self.n_shards):
                if at[i + 1] > at[i]:
                    self.batchers[i].add_gauges_bulk(
                        lo[at[i]:at[i + 1]], val[at[i]:at[i + 1]])
        if ns:
            order, lo, at = self._split_shards(self._s_slot[:ns],
                                               p.set_capacity)
            reg = self._s_reg[:ns][order]
            rho = self._s_rho[:ns][order]
            for i in range(self.n_shards):
                if at[i + 1] > at[i]:
                    self.batchers[i].add_sets_bulk(
                        lo[at[i]:at[i + 1]], reg[at[i]:at[i + 1]],
                        rho[at[i]:at[i + 1]])
        if nh:
            order, lo, at = self._split_shards(self._h_slot[:nh],
                                               p.histo_capacity)
            val = self._h_val[:nh][order]
            wt = self._h_wt[:nh][order]
            for i in range(self.n_shards):
                if at[i + 1] > at[i]:
                    self.batchers[i].add_histos_bulk(
                        lo[at[i]:at[i + 1]], val[at[i]:at[i + 1]],
                        wt[at[i]:at[i + 1]])

    # -- multi-ring reader group (sharded) -----------------------------------
    # Ring staging drains through the pre-sharded emit ONLY (vrm exposes
    # the packed and pre-sharded drains per ring; flush output is
    # byte-identical to the _split_shards path either way — pinned by
    # tests/test_native_preshard.py).
    readers_start = NativeAggregator.readers_start
    admission_set = NativeAggregator.admission_set
    admission_drain = NativeAggregator.admission_drain
    tenant_config = NativeAggregator.tenant_config
    tenant_params = NativeAggregator.tenant_params
    tenant_table = NativeAggregator.tenant_table
    tenant_restore = NativeAggregator.tenant_restore
    tenant_rows_drain = NativeAggregator.tenant_rows_drain
    reader_counters = NativeAggregator.reader_counters
    ring_stats = NativeAggregator.ring_stats
    ring_stats_per_ring = NativeAggregator.ring_stats_per_ring
    readers_stop = NativeAggregator.readers_stop

    def rings_start(self, n_rings: int, fds=None, max_len: int = 65536,
                    ring_cap: int = 65536, pin_cores=None) -> None:
        self.eng.rings_start(n_rings, fds=fds, max_len=max_len,
                             ring_cap=ring_cap, pin_cores=pin_cores)

    def _emit_rings(self) -> bool:
        emitted = False
        for r in range(self.eng.n_rings):
            nc, ng, ns, nh = self.eng.rings_emit_sharded(
                r, self._native_lanes(), self._ps_bounds)
            if nc + ng + ns + nh:
                self._stage_presharded(nc, ng, ns, nh)
                emitted = True
        return emitted

    def pump(self, max_wait_ms: int, max_emits: int = 8) -> List[bytes]:
        """Multi-ring drain into the per-shard batchers (see
        NativeAggregator.pump for the bounding rationale)."""
        if self.eng.n_rings:
            self.eng.rings_wait(max_wait_ms)
            for _ in range(max_emits):
                if not self._emit_rings():
                    break
            return self.eng.drain_specials()
        full, _st = self.eng.pump(max_wait_ms)
        for _ in range(max_emits):
            if not full:
                break
            self._emit_native()
            full, _st = self.eng.pump(0)
        if full:
            self._emit_native()
        return self.eng.drain_specials()

    def swap(self):
        rings = bool(self.eng.n_rings)
        if rings:
            self.eng.rings_pause()
            self._emit_rings()
        self._emit_native()
        detached = self.table
        detached.finalize()
        state, _ = super().swap()
        self.eng.reset()
        self.table = NativeKeyTable(self.spec, self.eng, self.n_shards)
        if rings:
            self.eng.rings_resume()
        return state, detached

    def query_snapshot(self):
        """See NativeAggregator.query_snapshot — same discipline over
        the per-shard staging batchers."""
        if self.eng.n_rings:
            self._emit_rings()
        self._emit_native()
        return super().query_snapshot()
