"""Multi-device aggregation backend: the key table sharded over a device
mesh (veneur_tpu/parallel/sharded.py) behind the same Aggregator interface
the Server uses.

The key space splits across `n_shards` mesh tiles by the reference's
`Digest % numWorkers` rule (host.py assigns slot = shard*per_shard+idx, so
the GLOBAL slot flattening of per-shard flush arrays lines up with the
KeyTable's slot numbers by construction). Each shard has its own staging
Batcher; batches emit for ALL shards together (stacked [1, S, ...]) so one
sharded ingest program serves every step, with each tile's scatters local
to its device.

Config: tpu_n_shards > 1 (or 0 = one shard per local device when several
devices are present). Native C++ staging currently pairs with the
single-device backend; sharded mode uses Python staging (the mesh path is
about device scale-out, not host parse throughput).
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import numpy as np

from veneur_tpu.aggregation.host import Batcher, BatchSpec, KeyTable
from veneur_tpu.aggregation.state import TableSpec
from veneur_tpu.observability import jaxruntime
from veneur_tpu.server.aggregator import (
    _SYNC_EVERY, Aggregator, set_member_bytes)


def per_shard_spec(spec: TableSpec, n_shards: int) -> TableSpec:
    import dataclasses
    for field in ("counter_capacity", "gauge_capacity", "status_capacity",
                  "set_capacity", "histo_capacity"):
        cap = getattr(spec, field)
        if cap % n_shards or cap < n_shards:
            raise ValueError(
                f"tpu_{field} ({cap}) must be a positive multiple of "
                f"tpu_n_shards ({n_shards})")
    return dataclasses.replace(
        spec,
        counter_capacity=spec.counter_capacity // n_shards,
        gauge_capacity=spec.gauge_capacity // n_shards,
        status_capacity=spec.status_capacity // n_shards,
        set_capacity=spec.set_capacity // n_shards,
        histo_capacity=spec.histo_capacity // n_shards)


def _gather_sharded_impl(out, cidx, gidx, stidx, setidx, hidx):
    """Live-row gather over the merged flush's [S, K_per] dense arrays
    (global KeyTable slots are flat indices by construction), packed into
    one flat f32 array — one device->host transfer per flush, same as
    the single-device flush_live_in_packed."""
    import jax.numpy as jnp
    which = {"counter_hi": cidx, "counter_lo": cidx, "gauge": gidx,
             "status": stidx, "set_estimate": setidx}

    def take(key, a):
        flat = a.reshape((-1,) + a.shape[2:])
        return jnp.take(flat, which.get(key, hidx), axis=0, mode="clip")

    return jnp.concatenate([take(k, out[k]).reshape(-1).astype(jnp.float32)
                            for k in sorted(out)])


def _gather_sharded_raw_impl(st, setidx, hidx):
    """Raw sketch state of live rows, packed like the flush gather (one
    transfer; 6-bit packed i32 HLL rows ride as bitcast f32 words — safe
    for the same run-of-set-bits reason as step._pack_outputs)."""
    import jax
    import jax.numpy as jnp

    def take(x, i):
        flat = x.reshape((-1,) + x.shape[3:])   # drop [R=1, S]
        return jnp.take(flat, i, axis=0, mode="clip")

    w = take(st.h_w, hidx)
    out = {
        "hll": take(st.hll, setidx),
        "h_weight": w,
        "h_mean": take(st.h_wm, hidx) / jnp.maximum(w, 1e-30),
        "h_min": take(st.h_min, hidx),
        "h_max": take(st.h_max, hidx),
        "recip_hi": take(st.h_recip_hi, hidx),
        "recip_lo": take(st.h_recip_lo, hidx) + take(st.h_recip_acc, hidx),
    }
    parts = []
    for k in sorted(out):
        a = out[k]
        if a.dtype == jnp.uint8:
            a = jax.lax.bitcast_convert_type(a.reshape((-1, 4)),
                                             jnp.float32)
        elif a.dtype == jnp.int32:
            a = jax.lax.bitcast_convert_type(a, jnp.float32)
        parts.append(a.reshape(-1).astype(jnp.float32))
    return jnp.concatenate(parts)


def _sharded_raw_shapes(pspec, n_set, n_h):
    cells = pspec.centroids + pspec.temp_cells
    f32 = "float32"
    return {"hll": ((n_set, pspec.hll_words), "int32"),
            "h_weight": ((n_h, cells), f32), "h_mean": ((n_h, cells), f32),
            "h_min": ((n_h,), f32), "h_max": ((n_h,), f32),
            "recip_hi": ((n_h,), f32), "recip_lo": ((n_h,), f32)}


import jax as _jax

_gather_sharded = _jax.jit(_gather_sharded_impl)
_gather_sharded_raw = _jax.jit(_gather_sharded_raw_impl)


class ShardedAggregator(Aggregator):
    def __init__(self, spec: TableSpec, bspec: BatchSpec = BatchSpec(),
                 n_shards: int = 2, compact_every: int = 8):
        import jax
        from veneur_tpu.parallel import (
            make_mesh, make_merged_flush, make_sharded_ingest_packed,
            sharded_empty_state)

        self.spec = spec            # total capacities (KeyTable slot space)
        self.pspec = per_shard_spec(spec, n_shards)
        self.bspec = bspec
        self.n_shards = n_shards
        self.compact_every = compact_every

        self.mesh = make_mesh(1, n_shards)
        # packed ingest: each tile's batch ships as one i32 buffer with
        # the compact word in-band — mirrors the single-device backend
        # (one executable, one transfer per step per tile)
        from veneur_tpu.aggregation.step import batch_sizes
        self._sizes = batch_sizes(Batcher(self.pspec, bspec).force_emit())
        self._ingest = make_sharded_ingest_packed(self.mesh, self.pspec,
                                                  self._sizes)
        self._flush = make_merged_flush(self.mesh, self.pspec)
        self._empty = partial(sharded_empty_state, self.pspec, 1, n_shards,
                              self.mesh)
        self.state = self._empty()
        self.table = KeyTable(spec, n_shards)
        self.batchers = self._make_batchers()
        self._hll_slots: List[Tuple[int, int]] = []  # (shard, local_slot)
        self._hll_rows: List[np.ndarray] = []
        self._restore_residuals: list = []  # (batcher, local, lo) tails
        self._steps = 0
        self.processed = 0
        self.dropped_capacity = 0
        # same device-step accounting surface as the single-device
        # Aggregator (observability callbacks read these by getattr):
        # dispatch_ns = host-side dispatch, step_ns = sampled synced
        # wall time (see Aggregator.__init__)
        self.h2d_bytes = 0
        self.step_ns = 0
        self.dispatch_ns = 0
        self.steps_total = 0
        self.steps_synced = 0
        self._init_degrade()

    # -- slot routing --------------------------------------------------------
    def _local(self, kind: str, slot: int) -> Tuple[int, int]:
        """global slot -> (shard, local slot); per-kind shard width."""
        per = self.table.tables[KeyTable._table_name(kind)].per_shard
        return slot // per, slot % per

    def process_metric(self, m) -> None:
        kind = m.type
        slot = self.table.slot_for(kind, m.name, m.tags, m.scope, m.digest,
                                   hostname=m.hostname,
                                   joined_tags=m.joined_tags)
        if slot is None:
            self.dropped_capacity += 1
            return
        if kind in ("histogram", "timer"):
            mt = self.table.meta_for_slot(kind, slot)
            if mt is not None and mt.imported_only:
                mt.imported_only = False
        shard, local = self._local(kind, slot)
        b = self.batchers[shard]
        if kind == "counter":
            b.add_counter(local, float(m.value), m.sample_rate)
        elif kind == "gauge":
            b.add_gauge(local, float(m.value))
        elif kind == "status":
            b.add_status(local, float(m.value))
            mt = self.table.meta_for_slot("status", slot)
            if mt is not None:
                mt.message = m.message
        elif kind == "set":
            member = set_member_bytes(m.value)
            if self._set_admit(member):
                b.add_set(local, member)
        elif kind in ("histogram", "timer"):
            # self-metric timers exempt from degraded sampling (see the
            # base Aggregator.process_metric rationale)
            if m.name.startswith("veneur."):
                rate = m.sample_rate
            else:
                rate = self._histo_admit(m.sample_rate)
            if rate is not None:
                b.add_histo(local, float(m.value), rate)
        self.processed += 1

    def import_metric(self, kind: str, name: str, tags: tuple, scope: int,
                      digest: int, payload: dict) -> None:
        slot = self.table.slot_for(kind, name, tags, scope, digest,
                                   imported=True)
        if slot is None:
            self.dropped_capacity += 1
            return
        shard, local = self._local(kind, slot)
        b = self.batchers[shard]
        if kind == "counter":
            b.add_counter(local, float(payload["value"]), 1.0)
        elif kind == "gauge":
            b.add_gauge(local, float(payload["value"]))
        elif kind == "set":
            regs = payload["registers"]
            if regs.shape[0] != self.pspec.registers:
                raise ValueError("imported HLL register-count mismatch")
            self._hll_slots.append((shard, local))
            self._hll_rows.append(regs)
        elif kind in ("histogram", "timer"):
            means = np.asarray(payload["means"], np.float32)
            weights = np.asarray(payload["weights"], np.float32)
            live = weights > 0
            means, weights = means[live], weights[live]
            b.add_histos_bulk(np.full(len(means), local, np.int32),
                              means, weights)
            recip = payload.get("recip")
            recip_corr = 0.0
            if recip is not None and np.all(means != 0.0):
                recip_corr = float(recip) - float(np.sum(weights / means))
            b.add_histo_stats(local, float(payload.get("min", np.inf)),
                              float(payload.get("max", -np.inf)),
                              recip_corr)
        self.processed += 1

    # -- checkpoint restore (hooks into Aggregator.restore_metric) ----------
    def _restore_lane(self, kind: str, slot: int):
        shard, local = self._local(kind, slot)
        return self.batchers[shard], local

    def _restore_hll(self, slot: int, regs) -> None:
        # staged as (shard, local) for _apply_hll_imports, same as the
        # sharded import path; drained by _restore_drain_hll / swap
        self._hll_slots.append(self._local("set", slot))
        self._hll_rows.append(regs)

    def _restore_emit(self) -> None:
        self._emit_all()

    def _restore_drain_hll(self) -> None:
        self._apply_hll_imports()

    # -- device steps --------------------------------------------------------
    def _make_batchers(self):
        """One staging Batcher per shard; when ANY shard's lane fills, every
        shard emits (padded) so the stacked [1, S] batch stays rectangular
        and one compiled program serves every step."""
        return [Batcher(self.pspec, self.bspec,
                        on_batch=partial(self._on_shard_batch, i))
                for i in range(self.n_shards)]

    def _dispatch_row(self, row):
        """Pack each shard's batch straight into its row of a persistent
        [1, S, W] buffer (pack_batch `out`: no per-step allocation, no
        np.stack pass) and run the fused mesh step; compaction rides the
        in-band control word at the same cadence as the single-device
        backend (Aggregator._on_batch). Two whole [1, S, W] buffers
        alternate so step N+1 packs while step N's transfer is in
        flight."""
        import time

        from veneur_tpu.aggregation.step import pack_batch, packed_layout
        self._steps += 1
        self.steps_total += 1
        dc = self._steps % self.compact_every == 0
        bufs = getattr(self, "_row_bufs", None)
        if bufs is None:
            words = packed_layout(self._sizes)[1]
            bufs = self._row_bufs = [
                np.zeros((1, self.n_shards, words), np.int32),
                np.zeros((1, self.n_shards, words), np.int32), 0]
        flat = bufs[bufs[2]]
        bufs[2] ^= 1
        for i, b in enumerate(row):
            pack_batch(b, dc, out=flat[0, i])
        self.h2d_bytes += flat.nbytes
        t0 = time.perf_counter_ns()
        self.state = self._ingest(self.state, flat)
        dispatch_dt = time.perf_counter_ns() - t0
        self.dispatch_ns += dispatch_dt
        if self.steps_total % _SYNC_EVERY == 0:
            self.step_ns += dispatch_dt + jaxruntime.sync_and_time(
                self.state)
            self.steps_synced += 1

    def _on_shard_batch(self, shard: int, batch):
        self._dispatch_row([batch if i == shard else b.force_emit()
                            for i, b in enumerate(self.batchers)])

    def _emit_all(self):
        if not any(b.pending() for b in self.batchers):
            return
        self._dispatch_row([b.force_emit() for b in self.batchers])

    def _apply_hll_imports(self):
        """Imported HLL rows merge on-device (rare path: only a global
        tier with sharded state receives these). Runs on the pipeline
        thread out of swap(), so it must not materialize the
        [1, S, K, W] table on host — that blocks behind every queued
        ingest step. With the 6-bit packed resident layout the update is
        gather packed words -> unpack -> register max -> repack ->
        scatter-set; duplicate (shard, local) targets are folded on the
        host first (np.maximum.at — register max is order-free) because
        a scatter-SET with duplicate targets is ill-defined, unlike the
        old dense register scatter-max."""
        if not self._hll_slots:
            return
        import jax
        import jax.numpy as jnp
        from veneur_tpu.ops.hll import pack_registers, unpack_registers
        from veneur_tpu.parallel.sharded import state_sharding

        sh = np.array([s for s, _ in self._hll_slots], np.int64)
        loc = np.array([l for _, l in self._hll_slots], np.int64)
        rows = np.stack(self._hll_rows).astype(np.uint8)
        key = sh * (self.pspec.set_capacity + 1) + loc
        uniq, inv = np.unique(key, return_inverse=True)
        folded = np.zeros((len(uniq), rows.shape[1]), np.uint8)
        np.maximum.at(folded, inv, rows)
        sh_u = jnp.asarray((uniq // (self.pspec.set_capacity + 1))
                           .astype(np.int32))
        loc_u = jnp.asarray((uniq % (self.pspec.set_capacity + 1))
                            .astype(np.int32))
        p = self.pspec.hll_precision
        cur = unpack_registers(self.state.hll[0, sh_u, loc_u], precision=p)
        merged = pack_registers(jnp.maximum(cur, jnp.asarray(folded)),
                                precision=p)
        hll = self.state.hll.at[0, sh_u, loc_u].set(merged, mode="drop")
        self.state = self.state._replace(
            hll=jax.device_put(hll, state_sharding(self.mesh)))
        self._hll_slots, self._hll_rows = [], []

    # -- flush ---------------------------------------------------------------
    def swap(self):
        self._emit_all()
        self._apply_hll_imports()
        if self._steps:
            # interval boundary sync (see Aggregator.swap)
            self.step_ns += jaxruntime.sync_and_time(self.state)
            self.steps_synced += 1
        state, table = self.state, self.table
        self.state = self._empty()
        self.table = KeyTable(self.spec, self.n_shards)
        if self._pressure is not None:
            self._pressure.attach(self.table)
        self.batchers = self._make_batchers()
        self._steps = 0
        self._latch_degrade()
        return state, table

    # -- query tier ---------------------------------------------------------
    def query_snapshot(self):
        """Pipeline-thread-only live-interval snapshot (see
        Aggregator.query_snapshot): drain every shard's staging batcher
        and the packed-HLL import queue, then capture references."""
        self._emit_all()
        self._apply_hll_imports()
        return self.state, self.table, self.active_set_shift

    def query_flat_state(self, state):
        """[R=1, S, rows, ...] -> flat [S*rows, ...] views (free
        reshapes, no copy): the KeyTable's global slot numbers ARE flat
        indices into the shard-major layout by construction (slot =
        shard * per_shard + local), so a query gather addresses — and
        moves — only the owner shard's rows."""
        import jax
        return jax.tree.map(lambda x: x.reshape((-1,) + x.shape[3:]),
                            state)

    def compute_flush(self, state, table, percentiles,
                      want_raw: bool = False, history=None):
        import jax.numpy as jnp

        from veneur_tpu.aggregation.step import (
            combine_flush_scalars, flush_live_shapes, live_indices,
            unpack_flush)

        qs = jnp.asarray(percentiles or [0.5], jnp.float32)
        # live-slot gather AFTER the merged flush (same O(live) host
        # boundary as the single-device flush_live): the KeyTable's
        # global slot numbers ARE flat indices into the [S, K_per]
        # reshape by construction (slot = shard * per_shard + local)
        idx = {kind: jnp.asarray(live_indices(table, kind, cap))
               for kind, cap in (("counter", self.spec.counter_capacity),
                                 ("gauge", self.spec.gauge_capacity),
                                 ("status", self.spec.status_capacity),
                                 ("set", self.spec.set_capacity),
                                 ("histogram", self.spec.histo_capacity))}

        packed = np.asarray(_gather_sharded(
            self._flush(state, qs), idx["counter"], idx["gauge"],
            idx["status"], idx["set"], idx["histogram"]))
        out = unpack_flush(packed, flush_live_shapes(
            self.pspec, len(idx["counter"]), len(idx["gauge"]),
            len(idx["status"]), len(idx["set"]), len(idx["histogram"]),
            len(qs)))
        result = combine_flush_scalars(out)
        if want_raw or history is not None:
            from veneur_tpu.aggregation.step import unpack_flush as _unpack
            r = _unpack(np.asarray(_gather_sharded_raw(
                state, idx["set"], idx["histogram"])),
                _sharded_raw_shapes(self.pspec, len(idx["set"]),
                                    len(idx["histogram"])))
            raw = {
                "counter": result["counter"],
                "gauge": result["gauge"],
                "hll": r["hll"],
                "h_mean": r["h_mean"],
                "h_weight": r["h_weight"],
                "h_min": r["h_min"],
                "h_max": r["h_max"],
                "h_recip": r["recip_hi"].astype(np.float64) + r["recip_lo"],
            }
            if history is not None:
                # Host-fed ring write: the sharded flush already
                # materializes result+raw, so the same frame the
                # forwarder/archive sees feeds the standalone
                # write_window jit — byte-identical window bytes to the
                # single-device fused path by construction.
                history.record_frame(table, result, raw)
            if want_raw:
                return result, table, raw
        return result, table
