"""Turn device flush arrays + host slot metadata into InterMetrics.

This is the reference's generateInterMetrics (flusher.go:225-298) plus the
per-sampler Flush methods (samplers/samplers.go:147/230/319/392/511-675),
driven by the scope rules of flusher.go:61-77:

- local instance (forwarding configured): mixed histograms/timers emit
  aggregates only (percentiles=nil); global-scoped metrics and sets emit
  nothing locally (their sketch state is forwarded); local-only
  histograms/timers flush fully, with percentiles.
- global / standalone instance: everything flushes; global-scoped
  histograms emit aggregates from the digest (the reference's global=true
  Flush path), mixed ones from their local scalars.

One deliberate deviation, documented: the reference keeps separate sampler
objects for direct vs imported mixed-scope histograms' local scalars; our
device table has one (min, max, count, sum) row per key, so on a standalone
global instance that both ingests a key directly and imports it, aggregates
include the imported mass (strictly more accurate; percentiles identical).
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Dict, List, Optional

import numpy as np

from veneur_tpu.aggregation.host import (
    KeyTable, SCOPE_GLOBAL, SCOPE_LOCAL)
from veneur_tpu.samplers.intermetric import (
    COUNTER, GAUGE, SINK_ONLY_TAG_PREFIX, STATUS, InterMetric, route_info)

# aggregate name -> (flush-dict key, metric type)
AGGREGATE_FIELDS = {
    "min": ("histo_min", GAUGE),
    "max": ("histo_max", GAUGE),
    "median": ("histo_median", GAUGE),
    "avg": ("histo_avg", GAUGE),
    "count": ("histo_count", COUNTER),
    "sum": ("histo_sum", GAUGE),
    "hmean": ("histo_hmean", GAUGE),
}


def percentile_name(p: float) -> str:
    """reference samplers.go:664: `%s.%dpercentile` with int(p*100)."""
    return f"{int(p * 100)}percentile"


def unique_timeseries(table: KeyTable, is_local: bool) -> int:
    """Count of unique timeseries this interval, per the reference's
    sampling rules (worker.go:300-341 SampleTimeseries): a global instance
    counts everything; a local one counts only what it will NOT forward
    (counters/gauges unless global-scoped; histos/sets/timers only when
    local-only; status always). Exact (slot allocation is per-key), where
    the reference uses an HLL estimate over digests."""
    n = 0
    for kind in ("counter", "gauge", "set", "histogram", "status"):
        for _slot, meta in table.get_meta(kind):
            if not is_local or meta.kind == "status":
                n += 1
            elif meta.kind in ("counter", "gauge"):
                n += meta.scope != SCOPE_GLOBAL
            else:  # histogram / timer / set
                n += meta.scope == SCOPE_LOCAL
    return n


def _prep(meta, hostname):
    """Per-KEY invariants (tag list copy, sink routing, hostname) computed
    once per key per interval: a 100k-name interval emits ~6 metrics per
    key and route_info scans were ~half of generation time. The routing
    test is ONE substring scan of the parser's precomputed joined-tags
    string (the common no-routing case never touches per-tag Python)."""
    jt = meta.joined_tags
    if jt is None:
        jt = ",".join(meta.tags)
    sinks = route_info(meta.tags) if SINK_ONLY_TAG_PREFIX in jt else None
    p = meta._emit_prep = (list(meta.tags), sinks,
                          meta.hostname or hostname)
    return p


@dataclasses.dataclass
class FrameSegment:
    """One homogeneous column group: every row shares the metric type and
    (for compound histo names) the suffix already baked into `names`.
    `metas` holds the originating SlotMeta per row BY REFERENCE — tag
    lists, routing, and hostname are derived lazily, so building a
    segment allocates no per-metric Python objects."""
    names: List[str]
    values: np.ndarray       # float64, len == len(names)
    mtype: str               # COUNTER / GAUGE / STATUS
    metas: List              # SlotMeta per row
    is_status: bool = False  # carry meta.message into InterMetric


@dataclasses.dataclass
class MetricFrame:
    """Columnar flush output — the 10M-key answer to InterMetric lists.

    Materializing one Python object per metric costs ~1.8s per 1.6M
    metrics (measured floor of dataclass construction); at the 10M-key
    north star that is ~20s of host time per interval. A frame carries
    (names, values, type) columns plus SlotMeta references and defers
    everything else, the same pre-sized streaming shape the reference
    uses in Go (flusher.go:169-298). Sinks that declare
    `accepts_frames = True` get the frame; `intermetrics()` materializes
    the exact object list for everything else (order is grouped by
    segment, not interleaved per key — sinks are order-independent)."""
    timestamp: int
    hostname: str
    segments: List[FrameSegment]
    # memoized intermetrics(): several materializing consumers (plugins,
    # object-only sinks via the base-class default) may share one frame —
    # each rebuilding ~per-metric objects would multiply the exact cost
    # the frame exists to avoid. Lock-guarded lazy init: the old "benign"
    # race let N concurrent sink threads each pay the full materialization
    # (and briefly hold N copies of a 10M-object list); now exactly one
    # builds and the rest share it.
    _materialized: object = dataclasses.field(
        default=None, repr=False, compare=False)
    _mat_lock: object = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    def __len__(self):
        return sum(len(s.names) for s in self.segments)

    def rows(self):
        """Yield prepared (name, value, mtype, message, tags, sinks,
        hostname) tuples — THE consumption surface for accepts_frames
        sinks, so per-key prep (tag copy, sink routing, hostname
        fallback) stays inside this module and every sink shares one
        loop instead of reaching into SlotMeta internals."""
        hostname = self.hostname
        for seg in self.segments:
            vals = seg.values.tolist()
            mtype = seg.mtype
            metas = seg.metas
            is_status = seg.is_status
            for i, name in enumerate(seg.names):
                m = metas[i]
                p = m._emit_prep or _prep(m, hostname)
                yield (name, vals[i], mtype,
                       m.message if is_status else "", p[0], p[1], p[2])

    def intermetrics(self) -> List[InterMetric]:
        # double-checked: the unlocked read is safe (attribute store is a
        # single bytecode under the GIL) and keeps the post-build hot path
        # lock-free
        if self._materialized is None:
            with self._mat_lock:
                if self._materialized is None:
                    ts = self.timestamp
                    self._materialized = [
                        InterMetric(name, ts, value, tags, mtype, message,
                                    host, sinks)
                        for name, value, mtype, message, tags, sinks, host
                        in self.rows()]
        return self._materialized


def _simple_segment(metas, vals, mtype, is_local, *, skip_scope=None,
                    keep_scope=None,
                    is_status=False) -> Optional[FrameSegment]:
    """Segment for a scalar kind. On a LOCAL tier, `skip_scope` drops
    that scope (forwarded, not flushed) while `keep_scope` keeps only
    that scope (the sets rule: everything else is forwarded). On a
    global/standalone tier both are ignored — everything flushes."""
    if not metas:
        return None
    n = len(metas)
    vals = np.asarray(vals, np.float64)[:n]
    if is_local and (skip_scope is not None or keep_scope is not None):
        if keep_scope is not None:
            keep = [i for i in range(n)
                    if metas[i][1].scope == keep_scope]
        else:
            keep = [i for i in range(n)
                    if metas[i][1].scope != skip_scope]
        if len(keep) != n:
            mlist = [metas[i][1] for i in keep]
            return FrameSegment([m.name for m in mlist], vals[keep],
                                mtype, mlist, is_status)
    mlist = [m for _s, m in metas]
    return FrameSegment([m.name for m in mlist], vals, mtype, mlist,
                        is_status)


def generate_frame(flush: Dict[str, np.ndarray], table: KeyTable,
                   *, percentiles: List[float], aggregates: List[str],
                   is_local: bool, timestamp: int,
                   hostname: str = "") -> MetricFrame:
    """Columnar twin of generate_intermetrics: identical emission rules
    (scope routing, imported_only suppression, non-finite min/max drops),
    vectorized filters, zero per-metric object construction."""
    segs: List[FrameSegment] = []

    def add(seg):
        if seg is not None and len(seg.names):
            segs.append(seg)

    add(_simple_segment(table.get_meta("counter"), flush["counter"],
                        COUNTER, is_local, skip_scope=SCOPE_GLOBAL))
    add(_simple_segment(table.get_meta("gauge"), flush["gauge"],
                        GAUGE, is_local, skip_scope=SCOPE_GLOBAL))
    add(_simple_segment(table.get_meta("status"), flush["status"],
                        STATUS, is_local, is_status=True))
    # sets have no local part: a local tier forwards the HLL and emits
    # only local-only sets (flusher.go:277-280)
    add(_simple_segment(table.get_meta("set"), flush["set_estimate"],
                        GAUGE, is_local, keep_scope=SCOPE_LOCAL))

    metas = table.get_meta("histogram")
    if metas:
        n = len(metas)
        hcount = np.asarray(flush["histo_count"])[:n]
        mask = hcount > 0
        scopes = imported = None
        if is_local or any(m.imported_only for _s, m in metas):
            scopes = np.fromiter((m.scope for _s, m in metas), np.int8, n)
            imported = np.fromiter((m.imported_only for _s, m in metas),
                                   np.bool_, n)
        if is_local:
            mask &= scopes != SCOPE_GLOBAL
        # aggregate eligibility: imported-only MIXED histos on a global
        # tier emit percentiles only (flusher.go:61-77)
        agg_mask = mask
        if imported is not None:
            agg_mask = mask & (~imported | ((scopes == SCOPE_GLOBAL)
                                            & (not is_local)))
        perc_mask = mask
        if is_local:
            perc_mask = mask & (scopes == SCOPE_LOCAL)

        asel = np.flatnonzero(agg_mask)
        if len(asel):
            base = [metas[i][1].name for i in asel]
            mlist = [metas[i][1] for i in asel]
            for a in dict.fromkeys(aggregates):
                if a not in AGGREGATE_FIELDS:
                    continue
                col = np.asarray(flush[AGGREGATE_FIELDS[a][0]],
                                 np.float64)[asel]
                suf = "." + a
                if a in ("min", "max"):
                    fin = np.isfinite(col)
                    if not fin.all():
                        keep = np.flatnonzero(fin)
                        add(FrameSegment(
                            [base[i] + suf for i in keep], col[keep],
                            AGGREGATE_FIELDS[a][1],
                            [mlist[i] for i in keep]))
                        continue
                add(FrameSegment([b + suf for b in base], col,
                                 AGGREGATE_FIELDS[a][1], mlist))
        if percentiles:
            psel = np.flatnonzero(perc_mask)
            if len(psel):
                base = [metas[i][1].name for i in psel]
                mlist = [metas[i][1] for i in psel]
                hq = np.asarray(flush["histo_quantiles"],
                                np.float64)[psel]
                for pi, p in enumerate(percentiles):
                    suf = "." + percentile_name(p)
                    add(FrameSegment([b + suf for b in base], hq[:, pi],
                                     GAUGE, mlist))
    return MetricFrame(timestamp, hostname, segs)


def generate_intermetrics(flush: Dict[str, np.ndarray], table: KeyTable,
                          *, percentiles: List[float], aggregates: List[str],
                          is_local: bool, timestamp: int,
                          hostname: str = "") -> List[InterMetric]:
    """The emit loops are deliberately flat and allocation-light: values
    cross the numpy boundary once per kind via .tolist() (per-element
    ndarray indexing + float() was ~2x the loop), InterMetric is a slots
    dataclass built with positional args, and scope filters test plain
    ints. A 1M-live-key interval labels in ~1s of host time (the
    reference pre-sizes and streams the same pass in Go,
    flusher.go:169-298)."""
    out: List[InterMetric] = []
    perc = list(percentiles)
    ts = timestamp
    app = out.append

    # flush arrays are COMPACT: row i pairs with get_meta(kind)[i]
    # (aggregator.compute_flush gathers live rows on device)
    metas = table.get_meta("counter")
    if metas:
        vals = np.asarray(flush["counter"]).tolist()
        for i, (_slot, m) in enumerate(metas):
            if is_local and m.scope == SCOPE_GLOBAL:
                continue  # forwarded, not flushed (flusher.go:274-287)
            p = m._emit_prep or _prep(m, hostname)
            app(InterMetric(m.name, ts, vals[i], p[0], COUNTER, "",
                            p[2], p[1]))

    metas = table.get_meta("gauge")
    if metas:
        vals = np.asarray(flush["gauge"]).tolist()
        for i, (_slot, m) in enumerate(metas):
            if is_local and m.scope == SCOPE_GLOBAL:
                continue
            p = m._emit_prep or _prep(m, hostname)
            app(InterMetric(m.name, ts, vals[i], p[0], GAUGE, "",
                            p[2], p[1]))

    metas = table.get_meta("status")
    if metas:
        vals = np.asarray(flush["status"]).tolist()
        for i, (_slot, m) in enumerate(metas):
            p = m._emit_prep or _prep(m, hostname)
            app(InterMetric(m.name, ts, vals[i], p[0], STATUS, m.message,
                            p[2], p[1]))

    metas = table.get_meta("set")
    if metas:
        vals = np.asarray(flush["set_estimate"]).tolist()
        for i, (_slot, m) in enumerate(metas):
            # sets have no local part (flusher.go:277-280): local instances
            # forward the HLL and emit nothing unless the set is local-only
            if is_local and m.scope != SCOPE_LOCAL:
                continue
            p = m._emit_prep or _prep(m, hostname)
            app(InterMetric(m.name, ts, vals[i], p[0], GAUGE, "",
                            p[2], p[1]))

    metas = table.get_meta("histogram")
    if metas:
        hq = np.asarray(flush["histo_quantiles"]).tolist()
        hcount = np.asarray(flush["histo_count"]).tolist()
        # (suffix, value list, type) per aggregate, resolved once
        agg_cols = [("." + a, np.asarray(flush[AGGREGATE_FIELDS[a][0]]
                                         ).tolist(),
                     AGGREGATE_FIELDS[a][1], a in ("min", "max"))
                    for a in dict.fromkeys(aggregates)
                    if a in AGGREGATE_FIELDS]
        psuf = ["." + percentile_name(p) for p in perc]
        isfinite = math.isfinite
        for i, (_slot, m) in enumerate(metas):
            scope = m.scope
            if is_local and scope == SCOPE_GLOBAL:
                continue
            if not hcount[i] > 0:
                continue
            name = m.name
            p = m._emit_prep or _prep(m, hostname)
            tags, sinks, host = p
            # imported-only MIXED histos on a global tier emit percentiles
            # only: their aggregates already flushed on the local instances
            # (flusher.go:61-77 "avoid double counting"); global-scoped
            # ones flush aggregates from the digest (the global=true path).
            if not m.imported_only or (scope == SCOPE_GLOBAL
                                       and not is_local):
                for suf, col, mtype, needs_finite in agg_cols:
                    v = col[i]
                    if needs_finite and not isfinite(v):
                        continue
                    app(InterMetric(name + suf, ts, v, tags, mtype, "",
                                    host, sinks))
            # percentiles: only where they are globally accurate —
            # everywhere on a global/standalone instance, local-only keys
            # on a local one
            if perc and (not is_local or scope == SCOPE_LOCAL):
                row = hq[i]
                for pi, suf in enumerate(psuf):
                    app(InterMetric(name + suf, ts, row[pi], tags, GAUGE,
                                    "", host, sinks))
    return out
