"""Turn device flush arrays + host slot metadata into InterMetrics.

This is the reference's generateInterMetrics (flusher.go:225-298) plus the
per-sampler Flush methods (samplers/samplers.go:147/230/319/392/511-675),
driven by the scope rules of flusher.go:61-77:

- local instance (forwarding configured): mixed histograms/timers emit
  aggregates only (percentiles=nil); global-scoped metrics and sets emit
  nothing locally (their sketch state is forwarded); local-only
  histograms/timers flush fully, with percentiles.
- global / standalone instance: everything flushes; global-scoped
  histograms emit aggregates from the digest (the reference's global=true
  Flush path), mixed ones from their local scalars.

One deliberate deviation, documented: the reference keeps separate sampler
objects for direct vs imported mixed-scope histograms' local scalars; our
device table has one (min, max, count, sum) row per key, so on a standalone
global instance that both ingests a key directly and imports it, aggregates
include the imported mass (strictly more accurate; percentiles identical).
"""

from __future__ import annotations

import math
from typing import Dict, List

import numpy as np

from veneur_tpu.aggregation.host import (
    KeyTable, SCOPE_GLOBAL, SCOPE_LOCAL)
from veneur_tpu.samplers.intermetric import (
    COUNTER, GAUGE, STATUS, InterMetric, route_info)

# aggregate name -> (flush-dict key, metric type)
AGGREGATE_FIELDS = {
    "min": ("histo_min", GAUGE),
    "max": ("histo_max", GAUGE),
    "median": ("histo_median", GAUGE),
    "avg": ("histo_avg", GAUGE),
    "count": ("histo_count", COUNTER),
    "sum": ("histo_sum", GAUGE),
    "hmean": ("histo_hmean", GAUGE),
}


def percentile_name(p: float) -> str:
    """reference samplers.go:664: `%s.%dpercentile` with int(p*100)."""
    return f"{int(p * 100)}percentile"


def unique_timeseries(table: KeyTable, is_local: bool) -> int:
    """Count of unique timeseries this interval, per the reference's
    sampling rules (worker.go:300-341 SampleTimeseries): a global instance
    counts everything; a local one counts only what it will NOT forward
    (counters/gauges unless global-scoped; histos/sets/timers only when
    local-only; status always). Exact (slot allocation is per-key), where
    the reference uses an HLL estimate over digests."""
    n = 0
    for kind in ("counter", "gauge", "set", "histogram", "status"):
        for _slot, meta in table.get_meta(kind):
            if not is_local or meta.kind == "status":
                n += 1
            elif meta.kind in ("counter", "gauge"):
                n += meta.scope != SCOPE_GLOBAL
            else:  # histogram / timer / set
                n += meta.scope == SCOPE_LOCAL
    return n


def generate_intermetrics(flush: Dict[str, np.ndarray], table: KeyTable,
                          *, percentiles: List[float], aggregates: List[str],
                          is_local: bool, timestamp: int,
                          hostname: str = "") -> List[InterMetric]:
    out: List[InterMetric] = []
    perc = list(percentiles)

    # per-KEY invariants (tag list copy, sink routing, hostname) hoisted
    # out of the per-metric emit: a 100k-name interval emits ~6 metrics
    # per key and route_info scans were ~half of generation time
    def emit(meta, name, value, mtype, message=""):
        prep = meta._emit_prep
        if prep is None:
            prep = meta._emit_prep = (list(meta.tags),
                                      route_info(meta.tags),
                                      meta.hostname or hostname)
        out.append(InterMetric(
            name=name, timestamp=timestamp, value=float(value),
            tags=prep[0], type=mtype, message=message,
            hostname=prep[2], sinks=prep[1]))

    # flush arrays are COMPACT: row i pairs with get_meta(kind)[i]
    # (aggregator.compute_flush gathers live rows on device)
    counters = flush["counter"]
    for i, (_slot, meta) in enumerate(table.get_meta("counter")):
        if is_local and meta.scope == SCOPE_GLOBAL:
            continue  # forwarded, not flushed (flusher.go:274-287)
        emit(meta, meta.name, counters[i], COUNTER)

    gauges = flush["gauge"]
    for i, (_slot, meta) in enumerate(table.get_meta("gauge")):
        if is_local and meta.scope == SCOPE_GLOBAL:
            continue
        emit(meta, meta.name, gauges[i], GAUGE)

    status = flush["status"]
    for i, (_slot, meta) in enumerate(table.get_meta("status")):
        emit(meta, meta.name, status[i], STATUS, message=meta.message)

    sets = flush["set_estimate"]
    for i, (_slot, meta) in enumerate(table.get_meta("set")):
        # sets have no local part (flusher.go:277-280): local instances
        # forward the HLL and emit nothing unless the set is local-only
        if is_local and meta.scope != SCOPE_LOCAL:
            continue
        emit(meta, meta.name, sets[i], GAUGE)

    hq = flush["histo_quantiles"]
    hcount = flush["histo_count"]
    agg_arrays = {a: flush[AGGREGATE_FIELDS[a][0]] for a in aggregates
                  if a in AGGREGATE_FIELDS}
    for i, (_slot, meta) in enumerate(table.get_meta("histogram")):
        if is_local and meta.scope == SCOPE_GLOBAL:
            continue
        global_flush = meta.scope == SCOPE_GLOBAL and not is_local
        has_mass = hcount[i] > 0
        # imported-only MIXED histos on a global tier emit percentiles only:
        # their aggregates already flushed on the local instances
        # (flusher.go:61-77 "avoid double counting"); global-scoped ones
        # flush aggregates from the digest (the global=true path).
        emit_aggs = has_mass and (not meta.imported_only or global_flush)
        if emit_aggs:
            for agg, arr in agg_arrays.items():
                v = arr[i]
                if agg in ("min", "max") and not math.isfinite(v):
                    continue
                emit(meta, f"{meta.name}.{agg}", v,
                     AGGREGATE_FIELDS[agg][1])
        # percentiles: only where they are globally accurate — everywhere on
        # a global/standalone instance, local-only keys on a local one
        if perc and (not is_local or meta.scope == SCOPE_LOCAL) and has_mass:
            for pi, p in enumerate(perc):
                emit(meta, f"{meta.name}.{percentile_name(p)}",
                     hq[i, pi], GAUGE)
    return out
