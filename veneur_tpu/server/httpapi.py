"""HTTP API (reference http.go:21-59 Handler + handlers_global.go).

Endpoints: GET /healthcheck, GET /version, GET /builddate, POST /import,
optional POST/GET /quitquitquit (gated on http_quit, server.go:80).

/import accepts a protobuf forwardrpc.MetricList body (optionally
zlib-deflated, matching the reference's deflate support,
handlers_global.go:134-146). The reference's HTTP-era JSON+gob payload is
Go-specific (encoding/gob) and is not portable; the protobuf body carries
identical information through the same import path as gRPC.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
import zlib

from veneur_tpu import __version__ as VERSION

log = logging.getLogger("veneur_tpu.server.http")

BUILD_DATE = "dev"


def start_http_server(server, address) -> "http.server.ThreadingHTTPServer":
    """Mount the API for a veneur_tpu.server.Server; returns the running
    ThreadingHTTPServer (its .server_address has the bound port)."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            log.debug(fmt, *args)

        def _reply(self, code, body=b"", ctype="text/plain"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthcheck":
                self._reply(200, b"ok")
            elif self.path == "/version":
                self._reply(200, VERSION.encode())
            elif self.path == "/builddate":
                self._reply(200, BUILD_DATE.encode())
            elif self.path == "/stats":
                body = json.dumps({
                    "packets_received": server.packets_received,
                    "parse_errors": server.parse_errors
                    + server.aggregator.extra_parse_errors(),
                    "processed": server.aggregator.processed,
                    "flush_count": server.flush_count,
                    "spans_received": server.span_pipeline.spans_received,
                    "spans_dropped": server.span_pipeline.spans_dropped,
                }).encode()
                self._reply(200, body, "application/json")
            elif self.path == "/quitquitquit" and server.cfg.http_quit:
                self._quit()
            else:
                self._reply(404, b"not found")

        def do_POST(self):
            if self.path == "/import":
                length = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(length)
                if self.headers.get("Content-Encoding") == "deflate":
                    try:
                        body = zlib.decompress(body)
                    except zlib.error:
                        self._reply(400, b"bad deflate body")
                        return
                from veneur_tpu.proto import forwardrpc_pb2 as fpb
                try:
                    mlist = fpb.MetricList.FromString(body)
                except Exception:
                    self._reply(400, b"bad MetricList protobuf")
                    return
                server.import_metrics(list(mlist.metrics))
                self._reply(200, b"imported")
            elif self.path == "/quitquitquit" and server.cfg.http_quit:
                self._quit()
            else:
                self._reply(404, b"not found")

        def _quit(self):
            self._reply(200, b"bye")

            def stop():
                server.shutdown()
                if getattr(server, "exit_on_quit", False):
                    import os
                    os._exit(0)  # graceful-exit endpoint ends the process

            threading.Thread(target=stop, daemon=True).start()

    httpd = http.server.ThreadingHTTPServer(address, Handler)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="http-api")
    t.start()
    return httpd
