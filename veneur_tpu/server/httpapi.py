"""HTTP API (reference http.go:21-59 Handler + handlers_global.go).

Endpoints: GET /healthcheck, GET /healthz (liveness), GET /readyz
(readiness — see server/health.py), GET /version, GET /builddate,
POST /import, optional POST/GET /quitquitquit (gated on http_quit,
server.go:80), GET /debug/profile?seconds=N (gated on
profile_capture_enabled: on-demand jax.profiler device trace), and —
gated on watch_enabled — POST /watch, GET /watch, DELETE /watch/<id>,
GET /watch/stream (SSE; see README §Watches).

/import accepts BOTH body formats, optionally zlib-deflated
(handlers_global.go:134-146):

  - the reference's JSON array of JSONMetric with gob/LE/axiomhq value
    bytes (handlers_global.go:115 unmarshalMetricsFromHTTP; decoded by
    veneur_tpu/forward/{jsonmetric,gob}.py) — a reference *local* veneur
    can HTTP-forward straight into this global;
  - a protobuf forwardrpc.MetricList (this framework's compact variant,
    same information as the gRPC path).

Status codes mirror the reference: 202 on success, 400 for bad
deflate/JSON/empty bodies, 415 for unknown Content-Encoding.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
import zlib

from veneur_tpu import __version__ as VERSION

log = logging.getLogger("veneur_tpu.server.http")

BUILD_DATE = "dev"


def _thread_dump() -> bytes:
    """Stacks of every live thread (the operational half of the
    reference's always-mounted pprof endpoints, http.go:51-56)."""
    import sys
    import threading
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return ("\n".join(out) + "\n").encode()


def _sample_profile(seconds: float, hz: float = 97.0) -> bytes:
    """Statistical CPU profile: sample every thread's innermost frames at
    ~hz for `seconds`, report hottest (function, file:line) sites — the
    Python analogue of `GET /debug/pprof/profile?seconds=N`."""
    import sys
    import time as _time
    from collections import Counter
    counts: Counter = Counter()
    samples = 0
    deadline = _time.monotonic() + seconds
    period = 1.0 / hz
    me = __import__("threading").get_ident()
    while _time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            co = frame.f_code
            counts[(co.co_name, f"{co.co_filename}:{frame.f_lineno}")] += 1
        samples += 1
        _time.sleep(period)
    lines = [f"{samples} samples over {seconds:.1f}s "
             f"({hz:.0f}Hz, innermost frame per thread)"]
    for (fn, loc), n in counts.most_common(40):
        lines.append(f"{n / max(samples, 1) * 100:6.1f}%  {fn}  {loc}")
    return ("\n".join(lines) + "\n").encode()


def start_http_server(server, address) -> "http.server.ThreadingHTTPServer":
    """Mount the API for a veneur_tpu.server.Server; returns the running
    ThreadingHTTPServer (its .server_address has the bound port)."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            log.debug(fmt, *args)

        def _reply(self, code, body=b"", ctype="text/plain"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _shutdown_gate(self) -> bool:
            """The ONE shutdown gate for every stateful endpoint
            (/stats, /healthz, /readyz, /query, /debug/profile): a
            tearing-down server must answer 503, not hang — these
            handlers read aggregator/device state that shutdown is
            concurrently draining. Returns True when it replied."""
            if server._shutdown.is_set():
                self._reply(503, b"shutting down")
                return True
            return False

        def do_GET(self):
            if self.path == "/healthcheck":
                self._reply(200, b"ok")
            elif self.path == "/healthz":
                # liveness: restart-worthy failures only (README
                # §Overload & health) — a SHEDDING server is still live
                if self._shutdown_gate():
                    return
                from veneur_tpu.server.health import check_live
                ok, detail = check_live(server)
                self._reply(200 if ok else 503,
                            json.dumps(detail).encode(),
                            "application/json")
            elif self.path == "/readyz":
                # readiness: should peers send NEW traffic here?
                if self._shutdown_gate():
                    return
                from veneur_tpu.server.health import check_ready
                ok, detail = check_ready(server)
                self._reply(200 if ok else 503,
                            json.dumps(detail).encode(),
                            "application/json")
            elif self.path == "/healthcheck/tracing":
                # tracing is always on (reference http.go:44 keeps the
                # endpoint for fleet compatibility)
                self._reply(200, b"ok")
            elif self.path == "/version":
                self._reply(200, VERSION.encode())
            elif self.path == "/builddate":
                self._reply(200, BUILD_DATE.encode())
            elif self.path == "/stats":
                if self._shutdown_gate():
                    return
                body = json.dumps({
                    "packets_received": server.packets_received,
                    "parse_errors": server.parse_errors
                    + server.aggregator.extra_parse_errors(),
                    "processed": server.aggregator.processed,
                    "flush_count": server.flush_count,
                    "spans_received": server.span_pipeline.spans_received,
                    "spans_dropped": server.span_pipeline.spans_dropped,
                    # the full registry, flattened — every counter/gauge
                    # plus timer count/sum, labeled series keyed
                    # name{k=v,...}
                    "telemetry": server.metrics.flat_values(),
                }).encode()
                self._reply(200, body, "application/json")
            elif self.path == "/metrics":
                # Prometheus text exposition of the telemetry registry,
                # scrapeable by cli/prometheus.py (or any Prometheus).
                # Off by default: the endpoint 404s unless configured, so
                # an unaware deployment exposes nothing new.
                if not getattr(server.cfg, "prometheus_metrics_enabled",
                               False):
                    self._reply(404, b"prometheus_metrics_enabled is off")
                    return
                from veneur_tpu.observability import render_prometheus
                server._c_metrics_scrapes.inc()
                self._reply(200, render_prometheus(server.metrics).encode(),
                            "text/plain; version=0.0.4")
            elif self.path == "/debug/pprof/threads":
                self._reply(200, _thread_dump(), "text/plain")
            elif self.path.startswith("/debug/pprof/profile"):
                import math
                from urllib.parse import parse_qs, urlparse
                parsed = urlparse(self.path)
                if parsed.path != "/debug/pprof/profile":
                    self._reply(404, b"not found")
                    return
                q = parse_qs(parsed.query)
                try:
                    seconds = float(q.get("seconds", ["5"])[0])
                except ValueError:
                    seconds = float("nan")
                if not math.isfinite(seconds) or seconds <= 0:
                    self._reply(400, b"bad seconds")
                    return
                self._reply(200, _sample_profile(min(seconds, 60.0)),
                            "text/plain")
            elif self.path.startswith("/debug/profile"):
                # on-demand device trace (jax.profiler). Ordering:
                # shutdown guard first (capture during teardown would
                # block on a dying runtime), then the config gate (an
                # unaware deployment exposes nothing), then parsing.
                import math
                from urllib.parse import parse_qs, urlparse
                parsed = urlparse(self.path)
                if parsed.path != "/debug/profile":
                    self._reply(404, b"not found")
                    return
                if self._shutdown_gate():
                    return
                if not getattr(server.cfg, "profile_capture_enabled",
                               False):
                    self._reply(404, b"profile_capture_enabled is off")
                    return
                q = parse_qs(parsed.query)
                try:
                    seconds = float(q.get("seconds", ["5"])[0])
                except ValueError:
                    seconds = float("nan")
                if not math.isfinite(seconds) or seconds <= 0:
                    self._reply(400, b"bad seconds")
                    return
                from veneur_tpu.observability import jaxruntime
                try:
                    trace_dir = jaxruntime.capture_profile(
                        min(seconds, 60.0))
                except RuntimeError as e:
                    # single-flight: one capture at a time
                    self._reply(409, str(e).encode())
                    return
                except Exception as e:
                    log.warning("profile capture failed: %s", e)
                    self._reply(500, b"profile capture failed")
                    return
                self._reply(200, json.dumps(
                    {"trace_dir": trace_dir,
                     "seconds": min(seconds, 60.0)}).encode(),
                    "application/json")
            elif self.path == "/watch":
                self._handle_watch_list()
            elif self.path == "/watch/stream":
                self._handle_watch_stream()
            elif self.path == "/quitquitquit" and server.cfg.http_quit:
                self._quit()
            else:
                self._reply(404, b"not found")

        def do_POST(self):
            if self.path == "/import":
                # continue the forwarder's trace as a child span
                # (handlers_global.go:126 ExtractRequestChild; falls back
                # to a fresh span when no trace headers arrive)
                from veneur_tpu.trace.opentracing import GLOBAL_TRACER
                from veneur_tpu.trace.tracer import Span
                req_span = GLOBAL_TRACER.extract_request_child(
                    "/import", dict(self.headers.items()),
                    "veneur.opentracing.import")
                if req_span is None:
                    req_span = Span("veneur.opentracing.import",
                                    service="veneur")
                try:
                    self._handle_import()
                finally:
                    req_span.client_finish(server.trace_client)
            elif self.path == "/query":
                self._handle_query()
            elif self.path == "/reshard":
                self._handle_reshard()
            elif self.path == "/watch":
                self._handle_watch_register()
            elif self.path == "/quitquitquit" and server.cfg.http_quit:
                self._quit()
            else:
                self._reply(404, b"not found")

        def do_DELETE(self):
            if self.path.startswith("/watch/"):
                self._handle_watch_delete()
            else:
                self._reply(404, b"not found")

        def _handle_query(self):
            """Batched read API (README §Query tier): answer quantile /
            cardinality / counter reads from resident device state.
            Ordering mirrors /import: shutdown gate first, then the
            config gate (an unaware deployment exposes nothing), then
            the CRITICAL shed — reads are the FIRST load to drop when
            the flush path is fighting for the device."""
            if self._shutdown_gate():
                return
            engine = server.query_engine
            if engine is None:
                self._reply(404, b"query_enabled is off")
                return
            if server._overload is not None:
                from veneur_tpu.reliability.overload import CRITICAL
                if server._overload.state >= CRITICAL:
                    # exact drop accounting: one inc per refused request
                    server._c_query_shed.inc()
                    self._reply(503, b"overloaded: query shed")
                    return
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if not body.strip():
                self._reply(400, b"Received empty /query request")
                return
            try:
                req = json.loads(body)
            except ValueError:
                self._reply(400, b"bad JSON body")
                return
            from veneur_tpu.query import QueryError
            try:
                out = engine.submit(req)
            except QueryError as e:
                self._reply(400, str(e).encode())
                return
            except (TimeoutError, RuntimeError) as e:
                # batcher backlogged / pipeline wedged: tell the
                # dashboard to back off, same contract as import shed
                server._c_query_shed.inc()
                self._reply(503, str(e).encode())
                return
            self._reply(200, json.dumps(out).encode(),
                        "application/json")

        def _watch_gate(self):
            """Shared gate chain for every /watch endpoint, the /query
            ordering: shutdown gate first, then the config gate (an
            unaware deployment exposes nothing). Returns the engine, or
            None when a reply was already sent."""
            if self._shutdown_gate():
                return None
            engine = server.watch_engine
            if engine is None:
                self._reply(404, b"watch_enabled is off")
                return None
            return engine

        def _handle_watch_register(self):
            """POST /watch: register one standing monitor (README
            §Watches). Registration is a host-side registry insert —
            cheap enough that it is NOT shed at overload CRITICAL (the
            EVALUATION is, on the flush side, counted)."""
            engine = self._watch_gate()
            if engine is None:
                return
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if not body.strip():
                self._reply(400, b"Received empty /watch request")
                return
            try:
                req = json.loads(body)
            except ValueError:
                self._reply(400, b"bad JSON body")
                return
            from veneur_tpu.watch import WatchError, WatchLimitError
            try:
                out = engine.register(req)
            except WatchLimitError as e:
                self._reply(429, str(e).encode())
                return
            except WatchError as e:
                self._reply(400, str(e).encode())
                return
            self._reply(201, json.dumps(out).encode(),
                        "application/json")

        def _handle_watch_list(self):
            engine = self._watch_gate()
            if engine is None:
                return
            watches = engine.list_watches()
            self._reply(200, json.dumps(
                {"watches": watches, "active": len(watches)}).encode(),
                "application/json")

        def _handle_watch_delete(self):
            engine = self._watch_gate()
            if engine is None:
                return
            try:
                wid = int(self.path[len("/watch/"):])
            except ValueError:
                self._reply(400, b"want DELETE /watch/<integer id>")
                return
            if engine.delete(wid):
                self._reply(200, json.dumps({"deleted": wid}).encode(),
                            "application/json")
            else:
                self._reply(404, b"no such watch")

        def _handle_watch_stream(self):
            """GET /watch/stream: SSE tail of state transitions. One
            bounded queue per subscriber (drop-oldest, drops counted);
            503 at the subscriber cap and — via _shutdown_gate, shared
            with every stateful endpoint — during shutdown/draining.
            The loop re-checks the shutdown flag each second so a
            draining server sheds open streams promptly."""
            engine = self._watch_gate()
            if engine is None:
                return
            sub = engine.hub.subscribe()
            if sub is None:
                self._reply(503, b"watch_stream_max_subscribers reached")
                return
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                self.wfile.write(b": watch stream open\n\n")
                self.wfile.flush()
                while not server._shutdown.is_set():
                    ev = sub.get(timeout=1.0)
                    if ev is None:
                        # keepalive comment: lets a dead client surface
                        # as BrokenPipeError instead of a leaked thread
                        self.wfile.write(b": keepalive\n\n")
                    else:
                        self.wfile.write(
                            b"data: " + json.dumps(ev).encode()
                            + b"\n\n")
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass   # client went away; unsubscribe below
            finally:
                engine.hub.unsubscribe(sub)

        def _handle_reshard(self):
            """POST /reshard {"n_shards": N}: start a live mesh resize.
            Same gate ordering as /query: shutdown first, then the
            config gate (an unaware deployment exposes nothing). 409
            when a move is already running — the coordinator is
            single-flight by design, so concurrent operators get a
            clean conflict instead of a queued surprise."""
            if self._shutdown_gate():
                return
            if server.reshard is None:
                self._reply(404, b"reshard_enabled is off")
                return
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            try:
                req = json.loads(body)
                n = int(req["n_shards"])
                timeout = req.get("timeout_s")
                if timeout is not None:
                    timeout = float(timeout)
            except (ValueError, KeyError, TypeError):
                self._reply(400, b'want JSON body {"n_shards": N}')
                return
            from veneur_tpu.reshard import ReshardError
            if server.reshard.active:
                self._reply(409, b"a reshard is already in progress")
                return
            try:
                out = server.trigger_reshard(n, timeout=timeout)
            except ReshardError as e:
                self._reply(400, str(e).encode())
                return
            self._reply(200, json.dumps(out).encode(),
                        "application/json")

        def _import_error(self, cause: str) -> None:
            """README §Monitoring: veneur.import.request_error_total
            with the reference's cause tags (handlers_global.go:96,146,
            154,163), through the self-telemetry loop."""
            from veneur_tpu.samplers import ssf_samples
            from veneur_tpu.trace.client import report_one
            report_one(server.trace_client, ssf_samples.count(
                "veneur.import.request_error_total", 1, {"cause": cause}))

        def _import_timing(self, t0_ns: int, part: str) -> None:
            """veneur.import.response_duration_ns tagged part:request/
            merge (handlers_global.go:190, http.go:78)."""
            import time as _time

            from veneur_tpu.samplers import ssf_samples
            from veneur_tpu.trace.client import report_one
            report_one(server.trace_client, ssf_samples.timing(
                "veneur.import.response_duration_ns",
                (_time.perf_counter_ns() - t0_ns) / 1e9, {"part": part}))

        def _handle_import(self):
            import time as _time
            self._import_t0 = _time.perf_counter_ns()
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            encoding = self.headers.get("Content-Encoding", "")
            if encoding == "deflate":
                try:
                    body = zlib.decompress(body)
                except zlib.error:
                    self._import_error("deflate")
                    self._reply(400, b"bad deflate body")
                    return
            elif encoding not in ("", "identity"):
                # reference: unknown encodings are 415
                # (handlers_global.go:150-156)
                self._import_error("unknown_content_encoding")
                self._reply(415, encoding.encode())
                return
            if not body.strip():
                self._reply(400, b"Received empty /import request")
                return
            # route on the declared Content-Type; fall back to a body
            # sniff (json.NewDecoder skips leading whitespace,
            # handlers_global.go:160 — and a protobuf body can
            # legitimately begin 0x0a 0x5b, which lstrip+'[' would
            # misread as JSON)
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                self._import_json(body)
            elif "protobuf" in ctype:
                self._import_protobuf(body)
            elif body.lstrip()[:1] == b"[":
                self._import_json(body)
            else:
                self._import_protobuf(body)

        def _extract_envelope(self, body_env=None):
            """Exactly-once envelope for this import: the wrapped-body
            form wins, else the veneur-source-id/-epoch/-seq headers;
            None when neither is present (legacy sender). Raises
            EnvelopeError on a partial or malformed envelope."""
            from veneur_tpu.forward.envelope import Envelope
            if body_env is not None:
                return Envelope.from_json(body_env)
            return Envelope.from_mapping(self.headers)

        def _reject_envelope(self, e) -> None:
            # every rejection is accounted: this counter is asserted
            # against the fuzz corpus (tests/test_intake_fuzz.py)
            server._c_envelope_rejected.inc()
            self._import_error("envelope")
            self._reply(400, str(e).encode())

        def _import_json(self, body: bytes) -> None:
            """Reference JSONMetric array (handlers_global.go:115), or
            the exactly-once wrapped form {"envelope": {...},
            "metrics": [...]} the enveloped proxy/forward path POSTs."""
            from veneur_tpu.forward.envelope import EnvelopeError
            from veneur_tpu.forward.jsonmetric import from_json_metric
            try:
                jms = json.loads(body)
            except ValueError:
                self._import_error("json")
                self._reply(400, b"bad JSON body")
                return
            body_env = None
            if isinstance(jms, dict):
                body_env = jms.get("envelope")
                jms = jms.get("metrics")
            try:
                envelope = self._extract_envelope(body_env)
            except EnvelopeError as e:
                self._reject_envelope(e)
                return
            if not isinstance(jms, list) or not jms:
                self._reply(400, b"Received empty /import request")
                return
            metrics = []
            for jm in jms:
                try:
                    metrics.append(from_json_metric(jm))
                except Exception as e:
                    # registry counter: atomic under concurrent HTTP
                    # import threads (import_errors is a read-only view)
                    server._c_import_errors.inc()
                    log.warning("bad JSONMetric %s: %s",
                                jm.get("name") if isinstance(jm, dict)
                                else jm, e)
            if not metrics:
                # all-empty/improper: the reference 400s
                # (handlers_global.go:176-186 nonEmpty)
                self._reply(400, b"Received empty or improperly-formed "
                                 b"metrics")
                return
            try:
                ok = server.import_metrics(metrics, envelope=envelope)
            except EnvelopeError as e:
                # window-skip rejection (already counted by the server)
                self._import_error("envelope")
                self._reply(400, str(e).encode())
                return
            if not ok:
                # CRITICAL overload sheds imports: 503 tells the sending
                # tier to retry elsewhere (or later) instead of 202-ing
                # data we discarded. A dedup-suppressed duplicate is NOT
                # a shed — import_metrics acks it True, the 202 below is
                # the ack the sender needs to evict its unit.
                self._reply(503, b"overloaded: import shed")
                return
            self._import_timing(self._import_t0, "request")
            self._reply(202, b"imported")

        def _import_protobuf(self, body: bytes) -> None:
            from veneur_tpu.forward.envelope import EnvelopeError
            from veneur_tpu.proto import forwardrpc_pb2 as fpb
            try:
                envelope = self._extract_envelope()
            except EnvelopeError as e:
                self._reject_envelope(e)
                return
            try:
                mlist = fpb.MetricList.FromString(body)
            except Exception:
                self._import_error("protobuf")
                self._reply(400, b"bad MetricList protobuf")
                return
            try:
                ok = server.import_metrics(list(mlist.metrics),
                                           envelope=envelope)
            except EnvelopeError as e:
                self._import_error("envelope")
                self._reply(400, str(e).encode())
                return
            if not ok:
                self._reply(503, b"overloaded: import shed")
                return
            self._import_timing(self._import_t0, "request")
            self._reply(202, b"imported")

        def _quit(self):
            self._reply(200, b"bye")

            def stop():
                server.shutdown()
                if getattr(server, "exit_on_quit", False):
                    import os
                    os._exit(0)  # graceful-exit endpoint ends the process

            threading.Thread(target=stop, daemon=True).start()

    httpd = http.server.ThreadingHTTPServer(address, Handler)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="http-api")
    t.start()
    return httpd
