"""Single-process aggregation backend: key table + batcher + device state.

The glue between parsed UDPMetrics and the jitted ingest step — the role of
the reference's Worker goroutines (worker.go:265 Work / :344 ProcessMetric),
with N workers replaced by one device table (logical shards assigned by
digest, host.py). Flush performs the map-swap double-buffering of
worker.go:498: the live table/state are detached and replaced, then the
flush math runs on the detached state while new samples accumulate.
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

import numpy as np

from veneur_tpu.aggregation.host import Batcher, BatchSpec, KeyTable
from veneur_tpu.aggregation.state import (TableSpec, empty_state_compiled)
from veneur_tpu.aggregation.step import (
    batch_sizes, ingest_step_packed, pack_batch)
from veneur_tpu.observability import jaxruntime
from veneur_tpu.samplers.parser import UDPMetric
from veneur_tpu.utils.hashing import fnv1a_64, splitmix64


def set_member_bytes(value) -> bytes:
    """The ONE place the set-member encoding policy lives (used by the
    single-process and sharded process_metric paths): surrogateescape
    round-trips NON-UTF-8 member bytes back to the original wire bytes —
    the parser decoded them that way, a plain encode() raises
    UnicodeEncodeError (which would kill the pipeline thread: one
    corrupt datagram = DoS, found by differential fuzz), and the
    restored bytes hash identically to the C++ engine's raw-byte
    MetroHash."""
    return value if isinstance(value, bytes) else str(value).encode(
        "utf-8", "surrogateescape")


# sampled device-sync cadence for step_ns (see __init__ accounting
# comment); every backend's dispatch loop shares it
_SYNC_EVERY = 64


class Aggregator:
    # optional tables.pressure.TablePressure shared across intervals;
    # class attribute so every backend (ShardedAggregator skips this
    # __init__) starts without one
    _pressure = None

    def __init__(self, spec: TableSpec, bspec: BatchSpec = BatchSpec(),
                 n_shards: int = 1, compact_every: int = 8):
        self.spec = spec
        self.bspec = bspec
        self.n_shards = n_shards
        self.compact_every = compact_every
        self.table = KeyTable(spec, n_shards)
        self.batcher = Batcher(spec, bspec, on_batch=self._on_batch)
        self.state = empty_state_compiled(spec)
        self._steps = 0
        # staged HLL import rows (merged via ops.hll.merge_rows)
        self._hll_slots: list = []
        self._hll_rows: list = []
        # checkpoint-restore residuals: (batcher, slot, lo) counter tails
        # applied in a SECOND ingest step (restore_flush)
        self._restore_residuals: list = []
        # stats (reference self-telemetry counters)
        self.processed = 0
        self.dropped_capacity = 0
        self.h2d_bytes = 0  # packed ingest bytes shipped to the device
        # device-step accounting for /metrics (observability/):
        # dispatch_ns is host-side dispatch wall time (XLA execution is
        # async, so this is NOT device time); step_ns is the honest
        # synced number, sampled every _SYNC_EVERY steps and at swap()
        # via jaxruntime.sync_and_time. steps_total is monotonic
        # (_steps resets every swap); steps_synced counts the samples
        # behind step_ns.
        self.step_ns = 0
        self.dispatch_ns = 0
        self.steps_total = 0
        self.steps_synced = 0
        # persistent pack targets, two per lane-size signature: batch N+1
        # packs into one buffer while batch N's h2d + donated step is
        # still in flight against the other (pack_batch `out` contract)
        self._pack_bufs: dict = {}
        self._init_degrade()

    def _init_degrade(self) -> None:
        """Degraded-aggregation state (reliability/overload.py). Every
        backend __init__ must call this — ShardedAggregator builds its
        own state and does not run Aggregator.__init__.

        Under SHEDDING+ the OverloadController pushes these knobs; the
        defaults (1.0 / 0) are branch-predicted no-ops on the hot path.
        Timers: admit a fraction p of samples and scale the recorded
        sample_rate by p — staged weight becomes 1/(rate·p), so the
        correction is exact in expectation and needs no latch."""
        self.degraded_timer_rate = 1.0
        self._degrade_seq = 0
        # Sets: admit a member iff the low k bits of fnv1a_64(member)
        # are zero (rate 2^-k, deterministic per member so repeats stay
        # idempotent) and multiply the flushed estimate by 2^k. The
        # shift LATCHES at swap — pending applies from the next interval
        # and last_set_shift is the shift that governed the interval
        # just detached (the flush worker reads it for the correction);
        # a mid-interval change would make the 2^k correction wrong for
        # members admitted before the change.
        self.pending_set_shift = 0
        self.active_set_shift = 0
        self.last_set_shift = 0
        # degradation drop accounting (veneur.overload.degraded_samples
        # _total): samples represented statistically, not lost rows
        self.degraded_timer_skipped = 0
        self.degraded_set_skipped = 0

    def extra_parse_errors(self) -> int:
        """Parse errors counted below the Python layer (native engine)."""
        return 0

    def set_pressure(self, pressure) -> None:
        """Install a tables.pressure.TablePressure: the live table and
        every subsequent interval's fresh KeyTable (swap) get it
        attached. Python key tables only — the native engine's C++ maps
        keep exact counted drops instead (absorbed by the next grow)."""
        self._pressure = pressure
        if pressure is not None:
            pressure.attach(self.table)

    # -- degraded aggregation (shared by the sharded backend) ---------------
    def _histo_admit(self, sample_rate: float):
        """Effective sample rate for one timer/histogram sample under
        degradation, or None when the sample is skipped. The roll is a
        deterministic splitmix64 counter sequence (reproducible tests,
        no RNG state), and the admitted samples carry rate·p so the
        flushed count/percentile weights stay unbiased."""
        p = self.degraded_timer_rate
        if p >= 1.0:
            return sample_rate
        self._degrade_seq += 1
        if (splitmix64(self._degrade_seq) >> 11) * (1.0 / (1 << 53)) >= p:
            self.degraded_timer_skipped += 1
            return None
        return sample_rate * p

    def _set_admit(self, member: bytes) -> bool:
        """Hash-prefix member subsample at rate 2^-active_set_shift."""
        k = self.active_set_shift
        if k <= 0:
            return True
        if fnv1a_64(member) & ((1 << k) - 1):
            self.degraded_set_skipped += 1
            return False
        return True

    def _latch_degrade(self) -> None:
        """Interval boundary: promote the pending set shift and expose
        the one that governed the detached interval. Called from every
        backend's swap() ON the pipeline thread, before new samples
        land in the fresh table."""
        self.last_set_shift = self.active_set_shift
        self.active_set_shift = self.pending_set_shift

    # -- ingest -------------------------------------------------------------
    def _on_batch(self, batch):
        # one packed H2D transfer per step; compaction rides the same
        # program via the control word (step.py pack_batch rationale)
        self._steps += 1
        self.steps_total += 1
        sizes = batch_sizes(batch)
        bufs = self._pack_bufs.get(sizes)
        if bufs is None:
            from veneur_tpu.aggregation.step import packed_layout
            words = packed_layout(sizes)[1]
            # [buf_a, buf_b, next_index]: allocated once per size
            # signature, alternated every step (double buffering — the
            # step dispatched last turn may still be reading its buffer)
            bufs = self._pack_bufs[sizes] = [
                np.zeros(words, np.int32), np.zeros(words, np.int32), 0]
        flat = bufs[bufs[2]]
        bufs[2] ^= 1
        pack_batch(batch, self._steps % self.compact_every == 0, out=flat)
        self.h2d_bytes += flat.nbytes
        t0 = time.perf_counter_ns()
        self.state = ingest_step_packed(
            self.state, flat, spec=self.spec, sizes=sizes)
        dispatch_dt = time.perf_counter_ns() - t0
        self.dispatch_ns += dispatch_dt
        if self.steps_total % _SYNC_EVERY == 0:
            # sampled sync: dispatch + wait-until-ready = true step wall
            # time (covers the queued tail, which is the point)
            self.step_ns += dispatch_dt + jaxruntime.sync_and_time(
                self.state)
            self.steps_synced += 1

    def process_metric(self, m: UDPMetric) -> None:
        """reference worker.go:344 ProcessMetric: switch on type+scope,
        upsert, sample."""
        kind = m.type
        slot = self.table.slot_for(kind, m.name, m.tags, m.scope, m.digest,
                                   hostname=m.hostname,
                                   joined_tags=m.joined_tags)
        if slot is None:
            self.dropped_capacity += 1
            return
        if kind in ("histogram", "timer"):
            mt = self.table.meta_for_slot(kind, slot)
            if mt is not None and mt.imported_only:
                mt.imported_only = False
        if kind == "counter":
            self.batcher.add_counter(slot, float(m.value), m.sample_rate)
        elif kind == "gauge":
            self.batcher.add_gauge(slot, float(m.value))
        elif kind == "status":
            self.batcher.add_status(slot, float(m.value))
            # keep the latest message on the slot metadata (O(1);
            # reference StatusCheck.Sample keeps last message,
            # samplers.go:312)
            mt = self.table.meta_for_slot("status", slot)
            if mt is not None:
                mt.message = m.message
        elif kind == "set":
            member = set_member_bytes(m.value)
            if self._set_admit(member):
                self.batcher.add_set(slot, member)
        elif kind in ("histogram", "timer"):
            # self-metric timers are exempt from degraded sampling: the
            # admission layer never sheds veneur.*, and blurring the
            # operator's own latency telemetry during an incident
            # defeats the point of bounded degradation. (Sets get no
            # such exemption — their 2^shift correction is applied
            # per-interval to every set row at flush, so a row staged
            # unsubsampled would be over-corrected.)
            if m.name.startswith("veneur."):
                rate = m.sample_rate
            else:
                rate = self._histo_admit(m.sample_rate)
            if rate is not None:
                self.batcher.add_histo(slot, float(m.value), rate)
        self.processed += 1

    # -- import path (global tier) ------------------------------------------
    def import_metric(self, kind: str, name: str, tags: tuple, scope: int,
                      digest: int, payload: dict) -> None:
        """Merge one forwarded metric's sketch state (the reference's
        Worker.ImportMetricGRPC switch, worker.go:438-495). payload keys by
        kind: counter/gauge 'value'; set 'registers' (np.uint8[R]);
        histogram/timer 'means','weights' (+ optional 'min','max','recip')."""
        slot = self.table.slot_for(kind, name, tags, scope, digest,
                                   imported=True)
        if slot is None:
            self.dropped_capacity += 1
            return
        if kind == "counter":
            self.batcher.add_counter(slot, float(payload["value"]), 1.0)
        elif kind == "gauge":
            self.batcher.add_gauge(slot, float(payload["value"]))
        elif kind == "set":
            regs = payload["registers"]
            if regs.shape[0] != self.spec.registers:
                # peer configured with a different hll_precision; sketch
                # registers don't interoperate across precisions
                raise ValueError(
                    f"imported HLL has {regs.shape[0]} registers, "
                    f"table expects {self.spec.registers}")
            self._hll_slots.append(slot)
            self._hll_rows.append(regs)
            if len(self._hll_slots) >= 128:
                self._flush_hll_imports()
        elif kind in ("histogram", "timer"):
            means = np.asarray(payload["means"], np.float32)
            weights = np.asarray(payload["weights"], np.float32)
            # digest merge = re-add centroids (samplers.go:726 -> tdigest
            # Merge), with the wire's exact min/max/reciprocalSum replacing
            # the re-add's approximation: the stats lane carries the
            # imported recip minus what the centroid re-add will add.
            live = weights > 0
            means, weights = means[live], weights[live]
            # bulk-stage the centroid re-add: a per-centroid Python call
            # costs ~230 calls per imported digest and dominated the
            # global tier's import throughput (BASELINE config 4)
            self.batcher.add_histos_bulk(
                np.full(len(means), slot, np.int32), means, weights)
            mn = float(payload.get("min", np.inf))
            mx = float(payload.get("max", -np.inf))
            recip = payload.get("recip")
            recip_corr = 0.0
            if recip is not None and np.all(means != 0.0):
                recip_corr = float(recip) - float(np.sum(weights / means))
            self.batcher.add_histo_stats(slot, mn, mx, recip_corr)
        self.processed += 1

    # -- checkpoint restore (persistence/restore.py) ------------------------
    def _restore_lane(self, kind: str, slot: int):
        """(batcher, staging slot) for a restored key; the sharded
        backend overrides with its per-shard routing."""
        return self.batcher, slot

    def _restore_hll(self, slot: int, regs) -> None:
        """Stage restored HLL registers for max-merge, same as the
        import path."""
        self._hll_slots.append(slot)
        self._hll_rows.append(regs)
        if len(self._hll_slots) >= 128:
            self._flush_hll_imports()

    def _restore_emit(self) -> None:
        self.batcher.emit()

    def restore_metric(self, kind: str, name: str, tags: tuple, scope: int,
                       digest: int, payload: dict, hostname: str = "",
                       message: str = "", imported_only: bool = False,
                       joined_tags=None) -> None:
        """Fold one checkpointed key back in through the merge lanes
        (never by overwriting state): counter add, gauge/status
        last-write-wins, HLL max, digest centroid re-add — the
        import_metric machinery plus the host-side metadata
        (hostname/message/joined_tags) a snapshot preserves and a
        forwarded metric does not. Callers finish with restore_flush()."""
        slot = self.table.slot_for(kind, name, tags, scope, digest,
                                   hostname=hostname,
                                   imported=imported_only,
                                   joined_tags=joined_tags)
        if slot is None:
            self.dropped_capacity += 1
            return
        b, local = self._restore_lane(kind, slot)
        if kind == "counter":
            # two-float split: the staging lane is f32, but the
            # checkpointed count is the f64 hi+lo fold. Stage hi now and
            # defer lo to restore_flush's second ingest step — a
            # same-batch scatter-add would re-round hi+lo to f32 and
            # lose exactly the bits the split carries.
            value = float(payload["value"])
            hi = float(np.float32(value))
            b.add_counter(local, hi, 1.0)
            lo = value - hi
            if lo != 0.0:
                self._restore_residuals.append((b, local, lo))
        elif kind == "gauge":
            b.add_gauge(local, float(payload["value"]))
        elif kind == "status":
            b.add_status(local, float(payload["value"]))
            mt = self.table.meta_for_slot("status", slot)
            if mt is not None:
                mt.message = message
        elif kind == "set":
            regs = np.asarray(payload["registers"], np.uint8)
            if regs.shape[0] != self.spec.registers:
                raise ValueError(
                    f"restored HLL has {regs.shape[0]} registers, table "
                    f"expects {self.spec.registers}")
            self._restore_hll(slot, regs)
        elif kind in ("histogram", "timer"):
            # identical merge math to import_metric: re-add live
            # centroids, exact min/max/recip via the stats lane
            means = np.asarray(payload["means"], np.float32)
            weights = np.asarray(payload["weights"], np.float32)
            live = weights > 0
            means, weights = means[live], weights[live]
            b.add_histos_bulk(
                np.full(len(means), local, np.int32), means, weights)
            mn = float(payload.get("min", np.inf))
            mx = float(payload.get("max", -np.inf))
            recip = payload.get("recip")
            recip_corr = 0.0
            if recip is not None and len(means) and np.all(means != 0.0):
                recip_corr = float(recip) - float(np.sum(weights / means))
            b.add_histo_stats(local, mn, mx, recip_corr)
        self.processed += 1

    def restore_flush(self) -> None:
        """Materialize a fold_snapshot pass: emit the hi-part batches,
        then the counter lo residuals in a separate step (see the split
        rationale in restore_metric), then drain staged HLL rows."""
        self._restore_emit()
        if self._restore_residuals:
            for b, local, lo in self._restore_residuals:
                b.add_counter(local, lo, 1.0)
            self._restore_residuals = []
            self._restore_emit()
        self._restore_drain_hll()

    def _restore_drain_hll(self) -> None:
        while self._hll_slots:
            self._flush_hll_imports()

    def _flush_hll_imports(self):
        if not self._hll_slots:
            return
        from veneur_tpu.ops.hll import merge_rows_packed
        import jax.numpy as jnp
        b = 128
        slots = np.full(b, self.spec.set_capacity, np.int32)
        rows = np.zeros((b, self.spec.registers), np.uint8)
        n = min(len(self._hll_slots), b)
        slots[:n] = self._hll_slots[:n]
        rows[:n] = np.stack(self._hll_rows[:n])
        self.state = self.state._replace(
            hll=merge_rows_packed(self.state.hll, jnp.asarray(slots),
                                  jnp.asarray(rows),
                                  precision=self.spec.hll_precision))
        self._hll_slots, self._hll_rows = (self._hll_slots[b:],
                                           self._hll_rows[b:])

    # -- flush --------------------------------------------------------------
    def swap(self):
        """Map-swap (worker.go:498): detach live state+table, reset fresh.
        This is the ONLY flush work that must run on the pipeline thread;
        everything downstream operates on the detached (immutable) interval
        and can run on a flush thread while new samples accumulate."""
        self.batcher.emit()
        while self._hll_slots:
            self._flush_hll_imports()
        if self._steps:
            # interval boundary sync: step_ns is never 0 after a flush
            # that ingested, even when _SYNC_EVERY never fired
            self.step_ns += jaxruntime.sync_and_time(self.state)
            self.steps_synced += 1
        state, table = self.state, self.table
        self.state = empty_state_compiled(self.spec)
        self.table = KeyTable(self.spec, self.n_shards)
        if self._pressure is not None:
            self._pressure.attach(self.table)
        self._steps = 0
        self._latch_degrade()
        return state, table

    # -- query tier ---------------------------------------------------------
    def query_snapshot(self):
        """Pipeline-thread-only: a coherent read view of the LIVE
        interval for the query tier (veneur_tpu/query/) — swap()'s
        staging drain (batcher emit + packed-HLL import fold) WITHOUT
        the detach. Every sample admitted before this call is folded
        into the returned state; JAX immutability makes the returned
        reference a frozen snapshot while ingest keeps replacing
        self.state underneath. Returns (state, table, active_set_shift)
        — the LIVE shift, because the latched-shift correction the
        flush applies has not happened yet for this interval."""
        self.batcher.emit()
        while self._hll_slots:
            self._flush_hll_imports()
        return self.state, self.table, self.active_set_shift

    def query_flat_state(self, state):
        """Query-tier state view with flat [rows, ...] leading dims;
        the single-device layout already is one."""
        return state

    def compute_flush(self, state, table, percentiles: List[float],
                      want_raw: bool = False, history=None
                      ) -> Tuple[Dict[str, np.ndarray], KeyTable]:
        """Flush math on a detached interval (safe off the pipeline thread:
        JAX arrays are immutable and dispatch is thread-safe). Output
        arrays are COMPACT: row i pairs with table.get_meta(kind)[i]
        (flush_live gathers live rows on device, so only O(live) bytes
        cross the host boundary). With want_raw, also returns the live
        rows' mergeable sketch state (numpy) for forwarding.

        With `history` (a history.HistoryWriter), each block runs the
        FUSED flush+history program instead: the interval's values land
        in their ring column inside the flush launch itself — same
        packed outputs, zero extra launches (ISSUE 18 tentpole). The
        ring is donated through the blocks and committed back to the
        writer with the interval's window metadata."""
        from veneur_tpu.aggregation.step import (
            FLUSH_BLOCK_ROWS, FLUSH_KEY_KIND, combine_flush_scalars,
            flush_live_hist_packed, flush_live_in_packed,
            flush_live_shapes, live_slots, pack_bucket_chunks,
            pack_flush_inputs, pad_bucket, unpack_flush)

        # No fold/compact pass here: ingest folds accumulators in-program
        # (step.py ingest_core), and the quantile kernel argsorts cells
        # per row (ops/tdigest.py _quantiles_one), so unmerged temp cells
        # are just extra exact centroids — compacting the FULL table
        # before flush cost ~2s of device time per interval at 2^17
        # capacity for no accuracy gain (temps unmerged are strictly more
        # precise; forwarding re-adds centroids either way).
        perc = percentiles or [0.5]
        spec = self.spec
        caps = [spec.counter_capacity, spec.gauge_capacity,
                spec.status_capacity, spec.set_capacity,
                spec.histo_capacity]
        slots = [live_slots(table, k) for k in
                 ("counter", "gauge", "status", "set", "histogram")]
        lens = [len(s) for s in slots]
        n_blocks = max(1, max(
            -(-n // min(pad_bucket(n, cap), FLUSH_BLOCK_ROWS))
            for n, cap in zip(lens, caps)))
        # Per-kind buckets sized to SPREAD each kind's rows evenly over
        # all n_blocks invocations (ceil(n/n_blocks), padded): a kind
        # smaller than the block-count driver never runs full-padding
        # garbage blocks — e.g. 7M counters + 1M timers tiles as 57
        # blocks of 128k counters x 18k timers, not 57 x 128k timers of
        # which 49 are pure waste on the expensive quantile kernel.
        buckets = tuple(min(pad_bucket(-(-n // n_blocks), cap),
                            FLUSH_BLOCK_ROWS)
                        for n, cap in zip(lens, caps))
        shapes = flush_live_shapes(spec, *buckets, len(perc),
                                   want_raw=want_raw)
        # Tiled flush (VERDICT r04 #2): every invocation reuses ONE
        # block-shaped executable — compile cost is bounded by the block
        # size, never by live cardinality. n_blocks == 1 is the steady
        # small-table case: same shapes as the old single-shot path. All
        # blocks are dispatched before any is materialized, so the
        # device pipelines them.
        if history is not None:
            from veneur_tpu.history.writer import SENTINEL
            plan = history.plan_flush(table)
            hist = history.begin_flush(plan)
            try:
                packs = []
                for i in range(n_blocks):
                    hflat = np.concatenate(
                        pack_bucket_chunks(plan.dests, buckets, i,
                                           fill=SENTINEL)
                        + [np.asarray([plan.col], np.int32)])
                    p, hist = flush_live_hist_packed(
                        state, pack_flush_inputs(
                            perc, pack_bucket_chunks(slots, buckets, i)),
                        hist, hflat, spec=spec, hspec=history.spec,
                        n_q=len(perc), buckets=buckets,
                        want_raw=want_raw, clear=(i == 0))
                    packs.append(p)
            except BaseException:
                history.abort_flush()
                raise
            history.commit_flush(plan, hist)
        else:
            packs = [
                flush_live_in_packed(
                    state, pack_flush_inputs(
                        perc, pack_bucket_chunks(slots, buckets, i)),
                    spec=spec, n_q=len(perc), buckets=buckets,
                    want_raw=want_raw)
                for i in range(n_blocks)]
        pieces = [unpack_flush(np.asarray(p), shapes) for p in packs]
        out = {}
        for key, kind_i in ((k, FLUSH_KEY_KIND[k]) for k in pieces[0]):
            b, n = buckets[kind_i], lens[kind_i]
            rows = [p[key][:min(b, n - i * b)]
                    for i, p in enumerate(pieces) if n - i * b > 0]
            out[key] = (np.concatenate(rows) if rows
                        else pieces[0][key][:0])
        result = combine_flush_scalars(out)
        if want_raw:
            raw = {
                "counter": result["counter"],
                "gauge": result["gauge"],
                "hll": result.pop("raw_hll"),
                "h_mean": result.pop("raw_h_mean"),
                "h_weight": result.pop("raw_h_weight"),
                "h_min": result["histo_min"],
                "h_max": result["histo_max"],
                "h_recip": np.asarray(out["histo_recip_hi"], np.float64)
                + np.asarray(out["histo_recip_lo"], np.float64),
            }
            return result, table, raw
        return result, table

    def flush(self, percentiles: List[float], want_raw: bool = False
              ) -> Tuple[Dict[str, np.ndarray], KeyTable]:
        """swap + compute in one call (single-threaded callers, tests)."""
        state, table = self.swap()
        return self.compute_flush(state, table, percentiles, want_raw)
