"""Single-process aggregation backend: key table + batcher + device state.

The glue between parsed UDPMetrics and the jitted ingest step — the role of
the reference's Worker goroutines (worker.go:265 Work / :344 ProcessMetric),
with N workers replaced by one device table (logical shards assigned by
digest, host.py). Flush performs the map-swap double-buffering of
worker.go:498: the live table/state are detached and replaced, then the
flush math runs on the detached state while new samples accumulate.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from veneur_tpu.aggregation.host import Batcher, BatchSpec, KeyTable
from veneur_tpu.aggregation.state import TableSpec, empty_state
from veneur_tpu.aggregation.step import (
    compact, flush_compute, fold_scalars, ingest_step)
from veneur_tpu.samplers import parser
from veneur_tpu.samplers.parser import UDPMetric


class Aggregator:
    def __init__(self, spec: TableSpec, bspec: BatchSpec = BatchSpec(),
                 n_shards: int = 1, compact_every: int = 32,
                 fold_every: int = 64):
        self.spec = spec
        self.bspec = bspec
        self.n_shards = n_shards
        self.compact_every = compact_every
        self.fold_every = fold_every
        self.table = KeyTable(spec, n_shards)
        self.batcher = Batcher(spec, bspec, on_batch=self._on_batch)
        self.state = empty_state(spec)
        self._steps = 0
        # stats (reference self-telemetry counters)
        self.processed = 0
        self.dropped_capacity = 0

    # -- ingest -------------------------------------------------------------
    def _on_batch(self, batch):
        self.state = ingest_step(self.state, batch, spec=self.spec)
        self._steps += 1
        if self._steps % self.compact_every == 0:
            self.state = compact(self.state, spec=self.spec)
        if self._steps % self.fold_every == 0:
            self.state = fold_scalars(self.state)

    def process_metric(self, m: UDPMetric) -> None:
        """reference worker.go:344 ProcessMetric: switch on type+scope,
        upsert, sample."""
        kind = m.type
        slot = self.table.slot_for(kind, m.name, m.tags, m.scope, m.digest,
                                   hostname=m.hostname)
        if slot is None:
            self.dropped_capacity += 1
            return
        if kind == "counter":
            self.batcher.add_counter(slot, float(m.value), m.sample_rate)
        elif kind == "gauge":
            self.batcher.add_gauge(slot, float(m.value))
        elif kind == "status":
            self.batcher.add_status(slot, float(m.value))
            # keep the latest message on the slot metadata (O(1);
            # reference StatusCheck.Sample keeps last message,
            # samplers.go:312)
            mt = self.table.meta_for_slot("status", slot)
            if mt is not None:
                mt.message = m.message
        elif kind == "set":
            member = m.value if isinstance(m.value, bytes) else str(
                m.value).encode()
            self.batcher.add_set(slot, member)
        elif kind in ("histogram", "timer"):
            self.batcher.add_histo(slot, float(m.value), m.sample_rate)
        self.processed += 1

    # -- flush --------------------------------------------------------------
    def flush(self, percentiles: List[float]
              ) -> Tuple[Dict[str, np.ndarray], KeyTable]:
        """Map-swap (worker.go:498): detach live state+table, reset fresh,
        then run the flush computation on the detached interval."""
        import jax.numpy as jnp

        self.batcher.emit()
        state, table = self.state, self.table
        self.state = empty_state(self.spec)
        self.table = KeyTable(self.spec, self.n_shards)
        self._steps = 0

        state = fold_scalars(state)
        state = compact(state, spec=self.spec)
        qs = jnp.asarray(percentiles or [0.5], jnp.float32)
        out = flush_compute(state, qs, spec=self.spec)
        return {k: np.asarray(v) for k, v in out.items()}, table
