from veneur_tpu.server.server import Server  # noqa: F401
