"""The server daemon: listeners → parse → aggregate → flush → sinks.

Maps the reference's Server (server.go:83 struct, :771 Start, :1303 Serve):

- UDP/TCP statsd listeners with SO_REUSEPORT reader sharding
  (networking.go:19 StartStatsd, socket_linux.go:26).
- HandleMetricPacket prefix dispatch: `_e{` → event, `_sc` → service
  check, else metric (server.go:939-988).
- One pipeline thread owning the device table (the N worker goroutines of
  worker.go collapse into one jitted scatter program; logical shards are
  slot ranges).
- Flush ticker with per-flush deadline and the crash-only FlushWatchdog
  (server.go:853-890, :900-935).
- Sinks flushed in parallel threads with a WaitGroup-equivalent barrier,
  then plugins (flusher.go:105-131).
"""

from __future__ import annotations

import logging
import os
import queue
import socket
import ssl
import threading
import time
from typing import List, Optional

from veneur_tpu.aggregation.host import BatchSpec
from veneur_tpu.aggregation.state import TableSpec
from veneur_tpu.config import Config
from veneur_tpu.forward.envelope import FRESH, Envelope, EnvelopeError
from veneur_tpu.reliability.faults import FAULTS, FLUSH_WORKER
from veneur_tpu.reliability.policy import (OPEN, CircuitBreaker,
                                           CircuitOpenError, RetryPolicy)
from veneur_tpu.samplers import parser, ssf_samples
from veneur_tpu.samplers.intermetric import InterMetric
from veneur_tpu.sinks.base import ResilientSink, dispatch_flush
from veneur_tpu.trace.client import report_one
from veneur_tpu.query.snapshot import PipelineRequest
from veneur_tpu.server.aggregator import Aggregator
from veneur_tpu.server.flusher import generate_intermetrics

log = logging.getLogger("veneur_tpu.server")

_STOP = object()    # pipeline-queue sentinel: drain and exit
MAX_UDP_SSF = 65536


class FlushRequest:
    """One flush command traveling pipeline thread → flush worker.

    Waiters observe THIS request's completion — not "any flush", which
    let a ticker flush satisfy a manual trigger's wait and return before
    the caller's data reached the sinks (the round-2 bench failure mode).
    `ok` is False when the flush was deferred under backpressure, failed,
    or (for the waiter) timed out; `detail` says which."""

    __slots__ = ("done", "ok", "detail")

    def __init__(self):
        self.done = threading.Event()
        self.ok = False
        self.detail = ""

    def finish(self, ok: bool, detail: str = "") -> None:
        self.ok = ok
        self.detail = detail
        self.done.set()

    def wait(self, timeout: float) -> bool:
        """True iff the flush completed successfully within `timeout`."""
        if not self.done.wait(timeout):
            self.detail = f"timed out after {timeout:.0f}s"
            return False
        return self.ok


class _ImportBatch(list):
    """Queue item carrying forwarded metricpb.Metrics into the pipeline
    thread (the ImportMetricChan of reference worker.go:55)."""


class _ImportBytes(bytes):
    """Queue item carrying a RAW serialized forwardrpc.MetricList for the
    native import decoder (NativeAggregator.import_pb_bytes): the gRPC
    thread never pays Python protobuf deserialization."""


class _SpanMetricBatch(list):
    """Queue item carrying span-extracted UDPMetrics (ssfmetrics loop-back
    into L3, SURVEY §2.5)."""


def resolve_addr(addr: str):
    """reference protocol/addr.go:18 ResolveAddr: scheme://host:port with
    schemes udp/tcp/unix(gram)."""
    from urllib.parse import urlparse
    u = urlparse(addr)
    if u.scheme in ("udp", "udp4", "udp6", "tcp", "tcp4", "tcp6"):
        # u.port is only touched here: an abstract unix name like
        # '@veneur:ssf' parses as netloc with a non-numeric "port" and
        # would raise
        port = u.port if u.port is not None else 8126
        kind = "udp" if u.scheme.startswith("udp") else "tcp"
        return (kind, (u.hostname or "127.0.0.1", port))
    if u.scheme in ("unix", "unixgram"):
        # netloc survives for abstract-namespace paths ('@name' parses as
        # URL userinfo) and the schemeless-path form 'unixgram:path'
        return (u.scheme, u.netloc + u.path)
    raise ValueError(f"unsupported listener scheme in {addr!r}")


def unix_bind_address(path: str) -> str:
    """'@name' -> Linux abstract-namespace address; shared by the server
    bind and the emit client so both mangle identically."""
    return "\0" + path[1:] if path.startswith("@") else path


def tick_delay(interval: float, now: float) -> float:
    """Seconds until the next wall-clock multiple of `interval`
    (reference server.go:866 CalculateTickDelay; pinned by its test's
    11:45:26.371 @ 10s → 3.629s case)."""
    return interval - (now % interval)


def _native_available() -> bool:
    from veneur_tpu import native
    return native.available()


def spec_from_config(cfg: Config) -> TableSpec:
    return TableSpec(
        counter_capacity=cfg.tpu_counter_capacity,
        gauge_capacity=cfg.tpu_gauge_capacity,
        status_capacity=cfg.tpu_status_capacity,
        set_capacity=cfg.tpu_set_capacity,
        histo_capacity=cfg.tpu_histo_capacity,
        compression=float(cfg.tpu_digest_compression),
        cells_per_k=int(cfg.tpu_digest_cells_per_k),
        exact_extremes=int(cfg.tpu_digest_exact_extremes))


class Server:
    def __init__(self, cfg: Config, metric_sinks: Optional[List] = None,
                 span_sinks: Optional[List] = None,
                 plugins: Optional[List] = None):
        self.cfg = cfg
        self.interval = cfg.parse_interval()
        self.hostname = cfg.hostname
        self.tags = list(cfg.tags)
        # fused ingest kernel gate (ops/pallas_ingest.py): None restores
        # probe gating (kernel on TPU, XLA chain on CPU), False forces
        # the chain everywhere. Set before any aggregator compiles.
        from veneur_tpu.ops import pallas_ingest
        pallas_ingest.set_enabled(
            None if cfg.pallas_ingest_enabled else False)
        agg_args = dict(
            spec=spec_from_config(cfg),
            bspec=BatchSpec(counter=cfg.tpu_batch_counter,
                            gauge=cfg.tpu_batch_gauge,
                            status=cfg.tpu_batch_status,
                            set=cfg.tpu_batch_set,
                            histo=cfg.tpu_batch_histo),
            n_shards=max(1, cfg.tpu_n_shards) if cfg.tpu_n_shards else 1,
            compact_every=cfg.tpu_compact_every)
        self._native = False
        self._native_readers_active = False
        n_shards = agg_args["n_shards"]
        if cfg.tpu_n_shards == 0:
            # auto: one shard per accelerator when several are attached
            # (virtual CPU meshes stay single-shard unless explicitly
            # configured — tests opt in via tpu_n_shards)
            import jax
            devices = jax.devices()
            if len(devices) > 1 and devices[0].platform != "cpu":
                n_shards = len(devices)
        self._collective_registered = ""
        if cfg.collective_enabled:
            # collective global tier: the mesh-resident backend
            # (collective/tier.py) over (tpu_n_replicas, shards); takes
            # routed absorbs from co-located locals and replica-merges on
            # device at flush
            from veneur_tpu.collective import tier as collective_tier
            n_replicas = max(1, cfg.tpu_n_replicas)
            if cfg.tpu_n_shards == 0:
                import jax
                n_shards = max(1, len(jax.devices()) // n_replicas)
            spec = agg_args["spec"]
            while n_shards > 1 and any(
                    getattr(spec, f) % n_shards
                    for f in ("counter_capacity", "gauge_capacity",
                              "status_capacity", "set_capacity",
                              "histo_capacity")):
                n_shards -= 1
            agg_args["n_shards"] = n_shards
            self.aggregator = collective_tier.CollectiveGlobalTier(
                n_replicas=n_replicas, **agg_args)
            collective_tier.register(cfg.collective_group, self.aggregator)
            self._collective_registered = cfg.collective_group
        else:
            self.aggregator, self._native = self._make_aggregator(n_shards)
        self.metric_sinks = list(metric_sinks or [])
        self.span_sinks = list(span_sinks or [])
        self.plugins = list(plugins or [])

        # span pipeline: metric-extraction sink always first
        # (server.go:409, ssfmetrics always prepended)
        from veneur_tpu.server.spans import SpanPipeline
        from veneur_tpu.sinks.ssfmetrics import MetricExtractionSink
        extraction = MetricExtractionSink(
            self.process_span_metrics,
            indicator_timer_name=cfg.indicator_span_timer_name,
            objective_timer_name=cfg.objective_span_timer_name)
        # tag-frequency heavy hitters (count-min over the span firehose);
        # reports per-interval top-K through the self-telemetry loop-back
        self.tag_frequency = None
        if cfg.tag_frequency_enabled:
            from veneur_tpu.sinks.tagfreq import TagFrequencySink
            from veneur_tpu.trace.client import report_batch
            self.tag_frequency = TagFrequencySink(
                report=lambda samples: report_batch(self.trace_client,
                                                    samples),
                tag_keys=cfg.tag_frequency_tag_keys,
                top_k=cfg.tag_frequency_top_k,
                depth=cfg.tag_frequency_depth,
                width=cfg.tag_frequency_width,
                batch_size=cfg.tag_frequency_batch_size)
            self.span_sinks.append(self.tag_frequency)
        # bare tags map to empty values (parser.go:694 ParseTagSliceToMap)
        common_tags = {t.split(":", 1)[0]: (t.split(":", 1)[1]
                                            if ":" in t else "")
                       for t in cfg.tags}
        self.span_pipeline = SpanPipeline(
            [extraction] + self.span_sinks,
            capacity=cfg.span_channel_capacity or 100,
            num_workers=max(1, cfg.num_span_workers),
            common_tags=common_tags,
            report_samples=self._report_span_worker_samples)
        # after the span pipeline exists: exclusion rules wire BOTH sink
        # kinds (server.go:1467 setSinkExcludedTags)
        self._wire_excluded_tags()

        # self-telemetry: a channel trace client into our own span pipeline
        # (trace.NewChannelClient, server.go:309-313) — self-spans re-enter
        # the pipeline and are extracted back to metrics by ssfmetrics
        from veneur_tpu.trace.client import ChannelBackend, Client
        self.trace_client = Client(ChannelBackend(self.span_pipeline))
        self._last_stats = {}
        self._unique_ts = None

        self.event_samples = []       # EventWorker buffer (worker.go:527)
        self._event_lock = threading.Lock()
        self.packet_queue: "queue.Queue" = queue.Queue(maxsize=4096)
        # detached flush intervals; drained by the dedicated flush thread
        # (flusher.go:105-115 runs on its own goroutine — the pipeline/worker
        # threads never wait on sinks). Bounded: each job holds a detached
        # device-state snapshot, so a backlogged flush worker must drop
        # intervals rather than grow without limit.
        self._flush_jobs: "queue.Queue" = queue.Queue(maxsize=4)
        self.last_flush = time.time()
        self.last_flush_done = time.time()
        # slow-sink containment (flush-worker thread only)
        self._sink_threads: dict = {}

        # -- telemetry registry (veneur_tpu/observability/) ---------------
        # THE source of truth for self-observation: /stats, the
        # self-metric flush, and GET /metrics all read it. The scattered
        # integer attributes it replaces live on as read-only properties
        # (parse_errors, imported_total, ...) so embedders and tests keep
        # their read surface unchanged; every write goes through an
        # atomic Counter.inc() — which also fixes the lost-increment race
        # on imported_total (+= from the gRPC and HTTP import threads).
        from veneur_tpu.observability import TelemetryRegistry, jaxruntime
        self.metrics = TelemetryRegistry(
            timer_compression=float(cfg.self_timer_compression or 50.0))
        self._flush_trace = bool(cfg.flush_trace_enabled)
        M = self.metrics
        self._c_parse_errors = M.counter(
            "veneur.parse_errors_total",
            "statsd/SSF payloads that failed to parse (Python layer)")
        self._c_import_errors = M.counter(
            "veneur.import.errors_total",
            "imported metrics rejected by /import or gRPC ingest")
        self._c_internal_errors = M.counter(
            "veneur.pipeline.internal_errors_total",
            "work items caught by the pipeline thread's backstop")
        self._c_imported = M.counter(
            "veneur.import.metrics_total",
            "metrics accepted from the forward/import tier")
        self._c_forward_errors = M.counter(
            "veneur.forward.error_total", "failed forward sends")
        self._c_forward_sends = M.counter(
            "veneur.forward.sends_total", "completed forward sends")
        self._c_forward_retries = M.counter(
            "veneur.forward.retries_total", "forward send retry attempts")
        # exactly-once forwarding (forward/envelope.py) — registered even
        # with the dedup window off so the inventory is stable
        self._c_dup_suppressed = M.counter(
            "veneur.forward.dup_suppressed_total",
            "already-folded forward intervals suppressed by the dedup "
            "window (duplicates are still acked so senders evict)")
        self._c_envelope_rejected = M.counter(
            "veneur.forward.envelope_rejected_total",
            "forward imports rejected for malformed or out-of-bound "
            "(source_id, epoch, seq) envelopes — never folded")
        # collective tier absorb path (collective/tier.py) — registered
        # even with the tier off so the inventory is stable
        self._c_coll_rows = M.counter(
            "veneur.collective.absorbed_rows_total",
            "forwardable rows handed to the co-located collective tier "
            "as device arrays instead of gRPC")
        self._c_coll_errors = M.counter(
            "veneur.collective.absorb_errors_total",
            "co-located collective absorbs that failed (the interval "
            "falls back to the wire forward path)")
        self._c_flush_count = M.counter(
            "veneur.flush.completed_total",
            "flush intervals run to completion (success or failure)")
        self._c_intervals_deferred = M.counter(
            "veneur.flush.intervals_deferred_total",
            "intervals deferred because the flush worker was backlogged")
        self._c_sink_skips = M.counter(
            "veneur.flush.skipped_total",
            "per-sink interval flushes skipped (slow sink / open circuit)")
        self._c_metrics_scrapes = M.counter(
            "veneur.metrics.scrapes_total", "GET /metrics scrapes served")
        self._t_flush_phase = M.timer(
            "veneur.flush.phase_duration_ns",
            "per-phase flush wall time, sketched by the in-house t-digest",
            labelnames=("phase",))
        self._t_sink_flush = M.timer(
            "veneur.sink.flush_duration_ns",
            "one sink flush call, success or failure",
            labelnames=("sink",))
        # co-located collective tier phase accounting — registered even
        # with the tier off so the inventory is stable; injected into
        # the tier at attach time (set_phase_timer) so the tier module
        # stays registry-free
        self._t_coll_phase = M.timer(
            "veneur.collective.phase_duration_ns",
            "collective tier phase wall time: stage, all_to_all_route, "
            "replica_merge, flush",
            labelnames=("phase",))
        # native ring emit latency, observed as a per-flush delta
        # average of the C++ emit_packed counters (zero hot-path cost)
        self._t_ring_emit = M.timer(
            "veneur.ring.emit_packed_duration_ns",
            "average packed-emit call latency over the last flush "
            "interval (C++ vt_emit_packed, steady_clock)")
        self._ring_emit_prev = (0, 0)
        # durability layer (veneur_tpu/persistence/) — registered even
        # with checkpointing off so the inventory is stable; they just
        # stay zero
        self._c_ckpt_writes = M.counter(
            "veneur.checkpoint.writes_total",
            "checkpoint snapshots durably written")
        self._c_ckpt_bytes = M.counter(
            "veneur.checkpoint.bytes",
            "serialized snapshot bytes written (manifest + chunks)")
        self._c_ckpt_restores = M.counter(
            "veneur.checkpoint.restores_total",
            "snapshots folded into a starting server")
        self._c_ckpt_corrupt = M.counter(
            "veneur.checkpoint.corrupt_total",
            "snapshots rejected by checksum/schema validation and "
            "quarantined")
        self._t_ckpt_write = M.timer(
            "veneur.checkpoint.write_duration_ns",
            "one checkpoint serialize+fsync on the writer thread")
        # TCP statsd hardening (README §Overload & health) — registered
        # even with the caps off so the inventory is stable
        self._c_tcp_rejected = M.counter(
            "veneur.tcp.rejected_total",
            "TCP statsd connections refused at tcp_max_connections")
        self._c_tcp_idle_closed = M.counter(
            "veneur.tcp.idle_closed_total",
            "TCP statsd connections closed at the idle deadline")
        # on-device query tier (veneur_tpu/query/) — registered even
        # with the tier off so the inventory is stable
        self._c_query_requests = M.counter(
            "veneur.query.requests_total",
            "individual queries accepted by POST /query (one request "
            "body may carry several)")
        self._c_query_batched = M.counter(
            "veneur.query.batched_launches_total",
            "device launches the query batcher coalesced concurrent "
            "reads into")
        self._c_query_shed = M.counter(
            "veneur.query.shed_total",
            "queries shed with 503: overload CRITICAL or shutdown "
            "(exact drop accounting — one inc per refused request)")
        self._t_query = M.timer(
            "veneur.query.duration_ns",
            "end-to-end batched query service time: snapshot round-trip "
            "+ device launch + response assembly")
        # elastic live resharding (veneur_tpu/reshard/) — registered even
        # with the feature off so the inventory is stable
        self._c_reshard_moves = M.counter(
            "veneur.reshard.moves_total",
            "live mesh resizes completed (drain + transfer + cutover)")
        self._c_reshard_rows_moved = M.counter(
            "veneur.reshard.rows_moved_total",
            "rows whose owner shard changed under a resize and were "
            "folded into the new mesh exactly once")
        self._c_reshard_failed = M.counter(
            "veneur.reshard.failed_total",
            "resizes abandoned: transfer timeout, fold failure after "
            "replays, or invalid target")
        self._c_reshard_stale = M.counter(
            "veneur.reshard.stale_reads_total",
            "queries answered during a transfer from the serving table "
            "before all moved rows folded (stale-bounded by one flush "
            "interval)")
        self._t_reshard = M.timer(
            "veneur.reshard.duration_ns",
            "one live resize end to end: drain swap through final fold")
        # streaming watch tier (veneur_tpu/watch/) — registered even
        # with the tier off so the inventory is stable
        self._g_watch_active = M.gauge(
            "veneur.watch.active",
            "standing watches currently registered, by watch kind",
            labelnames=("kind",))
        self._c_watch_evaluated = M.counter(
            "veneur.watch.evaluated_total",
            "watch evaluations performed (one per active watch per "
            "evaluated interval)", labelnames=("kind",))
        self._c_watch_fired = M.counter(
            "veneur.watch.fired_total",
            "watch state transitions into ALERT", labelnames=("kind",))
        self._c_watch_suppressed = M.counter(
            "veneur.watch.suppressed_total",
            "breaches that did not transition (debounce pending or "
            "hysteresis hold) plus per-watch evaluations lost to a "
            "skipped interval — overload CRITICAL, backlog drop-oldest, "
            "or an engine failure (exact accounting)",
            labelnames=("kind",))
        self._c_watch_notify_dropped = M.counter(
            "veneur.watch.notify_dropped_total",
            "transition notifications lost: SSE subscriber queue "
            "drop-oldest + terminal webhook failures (one inc per lost "
            "event)", labelnames=("kind",))
        self._c_watch_eval_ns = M.counter(
            "veneur.watch.eval_ns_total",
            "watch-engine-thread time per evaluated interval: selector "
            "resolution + the one fused device evaluation + state "
            "machine steps (off the flush path by construction)")
        # on-device history tier (veneur_tpu/history/) — registered even
        # with the tier off so the inventory is stable
        self._c_history_writes = M.counter(
            "veneur.history.writes_total",
            "per-key window values written into the history ring (one "
            "per live key per flushed interval)")
        self._c_history_evictions = M.counter(
            "veneur.history.evictions_total",
            "ring rows reclaimed from their least-recently-flushed key "
            "plus window writes turned away with every row in current "
            "use (the ring is a bounded cache)")
        self._c_history_range_queries = M.counter(
            "veneur.history.range_queries_total",
            "range queries planned against the ring (each POST /query "
            "item carrying a range counts once)")
        self._g_history_hbm_bytes = M.gauge(
            "veneur.history.hbm_bytes",
            "device-resident bytes of the history ring "
            "(history.HistorySpec.hbm_bytes for the configured "
            "geometry; 0 while the tier is off)")
        jaxruntime.install()
        # h2d_bytes high-water at the last flush report, for per-interval
        # byte tags on the flush trace (flush worker thread only)
        self._h2d_reported = 0

        # per-metric-sink flush accounting for the sink.* conventions
        # (sinks/sinks.go:11-29), accumulated by sink flush threads
        self._sink_stats_lock = threading.Lock()
        self._sink_flush_stats: dict = {}
        # README: veneur.flush.error_total, per sink like the other
        # sink.* conventions (an untagged total can't say WHICH sink)
        self._sink_flush_errors: dict = {}
        # (duration_ns, n_metrics) per forward POST, success or failure;
        # guarded by _sink_stats_lock with the other flush telemetry
        self._forward_stats: list = []

        # -- resilience layer (veneur_tpu/reliability/) -------------------
        # All knobs default off: no policy, no breakers, no spill — every
        # egress path keeps the reference's single-attempt drop-on-failure
        # behavior byte for byte.
        from veneur_tpu.utils.hashing import fnv1a_64
        self.retry_policy = None
        if cfg.sink_retry_max > 0:
            # hostname-derived seed: deterministic per instance, but a
            # fleet's retry storms decorrelate across hosts
            self.retry_policy = RetryPolicy(
                max_retries=cfg.sink_retry_max,
                base_ms=cfg.sink_retry_base_ms,
                seed=fnv1a_64(cfg.hostname.encode()))
        # one breaker per sink INSTANCE, shared between the fan-out gate
        # and the sink's own ResilientSink harness so veneur.circuit.state
        # reads a single state machine per destination
        self._sink_breakers: dict = {}      # id(sink) -> CircuitBreaker
        self._forward_breaker = None
        if cfg.circuit_failure_threshold > 0:
            for s in self.metric_sinks + self.span_sinks:
                self._sink_breakers[id(s)] = CircuitBreaker(
                    cfg.circuit_failure_threshold, cfg.circuit_cooldown_s)
            if cfg.is_local and cfg.forward_address:
                self._forward_breaker = CircuitBreaker(
                    cfg.circuit_failure_threshold, cfg.circuit_cooldown_s)
        if self.retry_policy is not None or self._sink_breakers:
            for s in self.metric_sinks + self.span_sinks:
                if isinstance(s, ResilientSink):
                    s.configure_resilience(self.retry_policy,
                                           self._sink_breakers.get(id(s)))
        self.forward_spill = None
        if cfg.forward_spill_max_bytes > 0:
            from veneur_tpu.reliability.spill import ForwardSpillBuffer
            self.forward_spill = ForwardSpillBuffer(
                cfg.forward_spill_max_bytes, cfg.forward_spill_max_age_s)

        # -- exactly-once forwarding (veneur_tpu/forward/envelope.py) -----
        # Off by default (forward_dedup_window == 0): no envelopes, no
        # dedup state — the at-least-once semantics above stay untouched.
        # With a window, this server deduplicates every enveloped import
        # it receives; a LOCAL with a forward_address additionally mints
        # a source identity and ack-gates its spill buffer (the spill
        # becomes the durable send queue — see reliability/spill.py).
        self._dedup = None
        # participant row in the attached collective tier, assigned by
        # the tier on first successful absorb (stable for process life)
        self._collective_participant = None
        self._fwd_source_id = None
        self._fwd_epoch = 0
        self._fwd_next_seq = 0
        self._fwd_acked_seq = -1
        self._fwd_meta_lock = threading.Lock()
        self._fwd_send_lock = threading.Lock()
        if cfg.forward_dedup_window > 0:
            from veneur_tpu.forward.envelope import (DedupWindow,
                                                     mint_source_id)
            self._dedup = DedupWindow(
                cfg.forward_dedup_window,
                max_sources=cfg.forward_dedup_max_sources)
            if cfg.is_local and cfg.forward_address:
                self._fwd_source_id = mint_source_id()
                if self.forward_spill is None:
                    # ack-gating needs the spill as its send queue even
                    # when the merge-on-retry buffer wasn't configured
                    from veneur_tpu.reliability.spill import (
                        ForwardSpillBuffer)
                    self.forward_spill = ForwardSpillBuffer(
                        32 << 20, cfg.forward_spill_max_age_s)

        # -- overload management (veneur_tpu/reliability/overload.py) -----
        # Off by default: no controller object, and every hot-path gate
        # is a single `is not None` check.
        self._overload = None
        self._restore_complete = not (cfg.checkpoint_dir
                                      and cfg.restore_on_start)
        # -- multi-tenant fairness (veneur_tpu/reliability/tenancy.py) ----
        # Off by default: no identity extraction anywhere. With tenancy
        # on, the TenantFairness ledger exists even without the overload
        # controller — identity and accounting are useful on their own;
        # the fairness buckets only bite at SHEDDING+ via the controller.
        self.tenancy = None
        self._tenant_restore_entries = None
        if cfg.tenant_enabled:
            from veneur_tpu.reliability.tenancy import TenantFairness
            self.tenancy = TenantFairness(
                tag=cfg.tenant_tag,
                weights=cfg.tenant_weights,
                base_rate=cfg.tenant_fair_rate,
                burst_mult=cfg.tenant_fair_burst_mult,
                quarantine_max_keys=cfg.tenant_quarantine_max_keys,
                quarantine_decay=cfg.tenant_quarantine_decay,
                quarantine_readmit_frac=cfg.tenant_quarantine_readmit_frac)
        if cfg.overload_enabled:
            from veneur_tpu.reliability.overload import OverloadController
            self._overload = OverloadController(
                signals=self._overload_signals,
                enter_pressured=cfg.overload_enter_pressured,
                enter_shedding=cfg.overload_enter_shedding,
                enter_critical=cfg.overload_enter_critical,
                exit_margin=cfg.overload_exit_margin,
                hold_s=cfg.overload_hold_s,
                admit_rate=cfg.overload_admit_rate,
                admit_burst=cfg.overload_admit_burst,
                timer_sample_rate=cfg.overload_timer_sample_rate,
                set_shift=cfg.overload_set_shift,
                shed_priority_tags=cfg.shed_priority_tags,
                tenancy=self.tenancy)

        # -- elastic live resharding (veneur_tpu/reshard/) ----------------
        # Off by default: no coordinator, and the flush-path gate is a
        # single `is not None` check. The collective tier manages its own
        # mesh layout, so the two are mutually exclusive.
        self._resharding = False
        self.reshard = None
        if cfg.reshard_enabled and not cfg.collective_enabled:
            from veneur_tpu.reshard import ReshardCoordinator
            self.reshard = ReshardCoordinator(self)

        # -- self-adjusting key tables (veneur_tpu/tables/) ---------------
        # Off by default: no manager, no pressure ladder, and the flush
        # path's grow gate is a single `is not None` check. Growth
        # composes with the collective tier only through config
        # capacities (the tier does not resize live), so the manager is
        # not armed there either.
        self.tables = None
        if cfg.table_grow_enabled and not cfg.collective_enabled:
            from veneur_tpu.tables import TableManager, TablePressure
            self.tables = TableManager(
                self.aggregator.spec,
                n_shards=getattr(self.aggregator, "n_shards", 1),
                max_capacity=cfg.table_max_capacity,
                idle_ttl_s=cfg.table_idle_ttl_s)
            if not self._native:
                # pressure ladder rides the Python key tables; the C++
                # engine keeps exact counted drops (absorbed by the
                # next grow) instead
                pressure = TablePressure(
                    salsa_enabled=cfg.table_salsa_enabled)
                self.tables.pressure = pressure
                self.aggregator.set_pressure(pressure)

        # -- TCP statsd hardening -----------------------------------------
        # live-connection accounting for tcp_max_connections; the idle
        # deadline lives in _tcp_conn
        self._tcp_conn_lock = threading.Lock()
        self._tcp_conns_live = 0

        # -- durability layer (veneur_tpu/persistence/) -------------------
        # Off by default (empty checkpoint_dir): no writer thread, no
        # extra work anywhere in the flush path.
        self._ckpt_writer = None
        self._flushes_since_ckpt = 0
        if cfg.checkpoint_dir:
            from veneur_tpu.persistence import CheckpointWriter
            self._ckpt_writer = CheckpointWriter(
                cfg.checkpoint_dir, retain=max(1, cfg.checkpoint_retain),
                write_timer=self._t_ckpt_write,
                bytes_counter=self._c_ckpt_bytes,
                writes_counter=self._c_ckpt_writes)
        # fan-out retry counts per sink (plain sinks only; ResilientSink
        # sinks count their own), under _sink_stats_lock
        self._fanout_retries: dict = {}
        self._packets_received = 0
        self._packets_dropped_py = 0
        self._packets_toolong_py = 0
        # orders shutdown's reader-counter fold against concurrent
        # packets_received/packets_dropped reads on the flush thread
        self._reader_fold_lock = threading.Lock()
        self._shutdown = threading.Event()
        # created eagerly when configured: _emit_stats_address is called
        # from both the flush worker and the span-flush thread, and a
        # lazy-init race would leak a socket
        self._stats_sock: Optional[socket.socket] = None
        self._stats_dest = None
        if cfg.stats_address:
            from veneur_tpu.utils.statsd_emit import parse_addr
            try:
                self._stats_dest = parse_addr(cfg.stats_address)
                self._stats_sock = socket.socket(socket.AF_INET,
                                                 socket.SOCK_DGRAM)
            except ValueError as e:
                # a typo'd stats_address degrades the mirror, never the
                # server (the lazy path tolerated this; keep that)
                log.warning("bad stats_address %r: %s; stats mirror "
                            "disabled", cfg.stats_address, e)
        self._unix_locks: List[tuple] = []   # (lock_fd, lock_path, sock_path)
        self._threads: List[threading.Thread] = []
        self._pipeline_thread: Optional[threading.Thread] = None
        self._flush_thread: Optional[threading.Thread] = None
        self._aux_threads: List[threading.Thread] = []
        self._aux_lock = threading.Lock()
        self._sockets: List[socket.socket] = []
        self._forward_client = None
        self._grpc_server = None
        self.grpc_port = None
        self._httpd = None
        self.http_port = None
        # -- on-device history tier (veneur_tpu/history/) ------------------
        # Off by default: no ring in HBM, flushes run the plain program.
        # Server-scoped on purpose: the writer's key index outlives
        # interval tables AND live reshards (windows are addressed by
        # key identity, not by slot or shard).
        self.history = None
        if cfg.history_enabled:
            from veneur_tpu.history import HistorySpec, HistoryWriter
            hspec = HistorySpec.for_table(
                spec_from_config(cfg),
                windows=cfg.history_windows,
                tiers=cfg.history_decimation_tiers,
                max_keys=cfg.history_max_keys)
            self.history = HistoryWriter(
                hspec, interval_s=self.interval,
                c_writes=self._c_history_writes,
                c_evictions=self._c_history_evictions,
                c_range=self._c_history_range_queries,
                g_hbm=self._g_history_hbm_bytes)
        # -- on-device query tier (veneur_tpu/query/) ---------------------
        # Off by default: no batcher thread, POST /query answers 404.
        self.query_engine = None
        if cfg.query_enabled:
            from veneur_tpu.query import QueryEngine
            self.query_engine = QueryEngine(
                self, max_batch=cfg.query_max_batch,
                timeout_ms=cfg.query_timeout_ms,
                requests=self._c_query_requests,
                batched=self._c_query_batched,
                duration=self._t_query,
                stale_reads=self._c_reshard_stale,
                history=self.history)
        # -- streaming watch tier (veneur_tpu/watch/) ---------------------
        # Off by default: no engine thread, /watch endpoints answer 404.
        self.watch_engine = None
        if cfg.watch_enabled:
            from veneur_tpu.watch import WatchEngine
            self.watch_engine = WatchEngine(
                self, max_active=cfg.watch_max_active,
                max_subscribers=cfg.watch_stream_max_subscribers,
                webhook_url=cfg.watch_webhook_url,
                retry_policy=self.retry_policy,
                evaluated=self._c_watch_evaluated,
                fired=self._c_watch_fired,
                suppressed=self._c_watch_suppressed,
                dropped=self._c_watch_notify_dropped,
                eval_ns=self._c_watch_eval_ns,
                active=self._g_watch_active,
                history=self.history)
        # last: every attribute a collector closes over now exists
        self._register_collectors()

    def _make_aggregator(self, n_shards: int, engine=None, spec=None):
        """Build the single-process backend for `n_shards` from the
        current config. Returns (aggregator, is_native). Used at startup
        and by the reshard coordinator's drain phase — which passes the
        OLD aggregator's C++ engine so reader rings/sockets keep feeding
        the same handle across the rebuild (the staged shard map was
        applied inside the drain swap). tables/growth.py additionally
        passes `spec` (grown per-kind capacities) at its swap-boundary
        rebuild. The collective tier has its own construction path in
        __init__ and does not resize live."""
        cfg = self.cfg
        agg_args = dict(
            spec=spec if spec is not None else spec_from_config(cfg),
            bspec=BatchSpec(counter=cfg.tpu_batch_counter,
                            gauge=cfg.tpu_batch_gauge,
                            status=cfg.tpu_batch_status,
                            set=cfg.tpu_batch_set,
                            histo=cfg.tpu_batch_histo),
            n_shards=max(1, int(n_shards)),
            compact_every=cfg.tpu_compact_every)
        native = cfg.native_ingest and (engine is not None
                                        or _native_available())
        if agg_args["n_shards"] > 1:
            # device scale-out: sharded mesh backend (parallel/sharded.py);
            # C++ staging composes with the mesh when native_ingest is on
            if native:
                from veneur_tpu.server.native_aggregator import (
                    NativeShardedAggregator)
                return NativeShardedAggregator(
                    preshard=cfg.native_preshard_enabled, engine=engine,
                    **agg_args), True
            from veneur_tpu.server.sharded_aggregator import (
                ShardedAggregator)
            return ShardedAggregator(**agg_args), False
        if native:
            from veneur_tpu.server.native_aggregator import NativeAggregator
            return NativeAggregator(engine=engine, **agg_args), True
        return Aggregator(**agg_args), False

    def _register_collectors(self) -> None:
        """Read-through registry collectors for values owned elsewhere:
        packet counters folded from the C++ reader group, aggregator
        device accounting, the reliability layer's breakers and spill
        buffer, process-wide JAX compile telemetry. Evaluated only at
        collect time (/metrics scrape, /stats, self-metric flush) — zero
        hot-path cost. Native-engine sub-Python parse errors are NOT
        read here (the engine's stats call must not interleave with
        feed(); they reach self-telemetry via the pipeline-thread
        snapshot instead)."""
        from veneur_tpu.observability import jaxruntime
        from veneur_tpu.reliability.faults import FAULTS
        M = self.metrics
        M.callback("veneur.packets_received_total",
                   lambda: self.packets_received, kind="counter",
                   help="datagrams delivered (Python + C++ readers)")
        M.callback("veneur.packets_dropped_total",
                   lambda: self.packets_dropped, kind="counter",
                   help="datagrams lost to backpressure after delivery")
        M.callback("veneur.packet.error_toolong_total",
                   lambda: self.packets_toolong, kind="counter",
                   help="datagrams dropped whole: over metric_max_length")
        M.callback("veneur.worker.metrics_processed_total",
                   lambda: self.aggregator.processed, kind="counter",
                   help="metrics staged into the device table")
        M.callback("veneur.worker.metrics_dropped_total",
                   lambda: self.aggregator.dropped_capacity, kind="counter",
                   help="metrics dropped at table capacity")
        M.callback("veneur.spans_received_total",
                   lambda: self.span_pipeline.spans_received, kind="counter",
                   help="SSF spans accepted by the span pipeline")
        M.callback("veneur.device.h2d_bytes_total",
                   lambda: getattr(self.aggregator, "h2d_bytes", 0),
                   kind="counter",
                   help="packed ingest bytes shipped host-to-device")
        M.callback("veneur.device.step_ns_total",
                   lambda: getattr(self.aggregator, "step_ns", 0),
                   kind="counter",
                   help="device ingest-step wall time including the "
                        "sampled block_until_ready sync (host side)")
        M.callback("veneur.device.dispatch_ns_total",
                   lambda: getattr(self.aggregator, "dispatch_ns", 0),
                   kind="counter",
                   help="device ingest-step dispatch-only wall time — "
                        "async enqueue cost, no sync (host side)")
        M.callback("veneur.device.steps_total",
                   lambda: getattr(self.aggregator, "steps_total", 0),
                   kind="counter", help="device ingest steps dispatched")
        M.callback("veneur.device.steps_synced_total",
                   lambda: getattr(self.aggregator, "steps_synced", 0),
                   kind="counter",
                   help="ingest steps that ran a block_until_ready sync "
                        "(1-in-N sample plus swap boundaries)")
        M.callback("veneur.device.hbm_bytes_in_use",
                   jaxruntime.hbm_bytes_in_use, labelnames=("device",),
                   help="live device memory per accelerator "
                        "(memory_stats; absent on backends without it)")
        M.callback("veneur.device.hbm_bytes_peak",
                   jaxruntime.hbm_bytes_peak, labelnames=("device",),
                   help="peak device memory per accelerator "
                        "(memory_stats; absent on backends without it)")
        # native ring (C++ vr_stats snapshot; mutex-guarded counters +
        # relaxed parser atomics, safe to read while the pipeline emits)
        M.callback("veneur.ring.depth",
                   lambda: float(self._ring_stats().get("ring_depth", 0)),
                   help="parsed datagrams waiting in the native ring")
        M.callback("veneur.ring.depth_highwater",
                   lambda: float(
                       self._ring_stats().get("ring_highwater", 0)),
                   help="deepest the native ring has been since start")
        M.callback("veneur.ring.pump_batches_total",
                   lambda: float(
                       self._ring_stats().get("pump_batches", 0)),
                   kind="counter",
                   help="non-empty batches drained by pipeline_pump")
        M.callback("veneur.ring.buffer_swap_stalls_total",
                   lambda: float(self._ring_stats().get("pump_stalls", 0)),
                   kind="counter",
                   help="pump drains that hit the staging-buffer cap "
                        "(double-buffer swap had to wait on the device)")
        M.callback("veneur.ring.emit_packed_total",
                   lambda: float(
                       self._ring_stats().get("emit_packed_calls", 0)),
                   kind="counter",
                   help="packed-emit calls made by the C++ engine")
        M.callback("veneur.ring.emit_packed_ns_total",
                   lambda: float(
                       self._ring_stats().get("emit_packed_ns", 0)),
                   kind="counter",
                   help="wall time inside C++ vt_emit_packed")
        # per-ring family (multi-ring engine only; empty single-ring).
        # The unlabeled veneur.ring.* names above stay the EXACT
        # cross-ring aggregates — sums, with depth_highwater as the
        # per-ring max — so dashboards keyed on them keep working.
        M.callback("veneur.ring.per_ring_depth",
                   lambda: self._collect_per_ring("ring_depth"),
                   labelnames=("ring",),
                   help="parsed datagrams waiting, per native ring")
        M.callback("veneur.ring.per_ring_depth_highwater",
                   lambda: self._collect_per_ring("ring_highwater"),
                   labelnames=("ring",),
                   help="deepest each native ring has been since start")
        M.callback("veneur.ring.per_ring_datagrams_total",
                   lambda: self._collect_per_ring("datagrams"),
                   kind="counter", labelnames=("ring",),
                   help="datagrams accepted per native ring")
        M.callback("veneur.ring.per_ring_dropped_total",
                   lambda: self._collect_per_ring("ring_dropped"),
                   kind="counter", labelnames=("ring",),
                   help="ring-overflow drops per native ring")
        M.callback("veneur.ring.per_ring_parse_batches_total",
                   lambda: self._collect_per_ring("pump_batches"),
                   kind="counter", labelnames=("ring",),
                   help="datagram parse batches per ring worker")
        M.callback("veneur.ring.per_ring_stalls_total",
                   lambda: self._collect_per_ring("pump_stalls"),
                   kind="counter", labelnames=("ring",),
                   help="lane-full parser stalls per native ring")
        M.callback("veneur.ring.per_ring_emit_packed_total",
                   lambda: self._collect_per_ring("emit_packed_calls"),
                   kind="counter", labelnames=("ring",),
                   help="packed arena-row emits per native ring")
        M.callback("veneur.jax.compiles_total", jaxruntime.compiles_total,
                   kind="counter",
                   help="XLA backend compiles observed, process-wide")
        M.callback("veneur.jax.compile_time_ns_total",
                   jaxruntime.compile_time_ns_total, kind="counter",
                   help="wall time spent inside XLA backend compiles")
        M.callback("veneur.faults.injected_total",
                   lambda: FAULTS.injected_total, kind="counter",
                   help="chaos faults fired by the process-global injector")
        # reliability layer (PR 1) — the same collectors
        # _report_self_metrics deltas against, so JSON stats, the
        # self-metric flush, and /metrics can never disagree
        M.callback("veneur.sink.retries_total", self._collect_sink_retries,
                   kind="counter", labelnames=("sink",),
                   help="egress retries per destination "
                        "(fan-out + sink harness + forward)")
        M.callback("veneur.sink.posts_skipped_open_total",
                   self._collect_posts_skipped, kind="counter",
                   labelnames=("sink",),
                   help="sink network calls refused by an open circuit")
        M.callback("veneur.circuit.state", self._collect_circuit_state,
                   kind="gauge", labelnames=("sink",),
                   help="breaker state: 0 closed / 1 half-open / 2 open")
        M.callback("veneur.circuit.opens_total",
                   self._collect_circuit_opens, kind="counter",
                   labelnames=("sink",),
                   help="closed-to-open breaker transitions")
        M.callback("veneur.forward.spill_bytes",
                   lambda: (self.forward_spill.bytes
                            if self.forward_spill is not None else None),
                   help="mergeable sketch bytes awaiting re-forward")
        M.callback("veneur.forward.spill.spilled_total",
                   lambda: (self.forward_spill.spilled_total
                            if self.forward_spill is not None else None),
                   kind="counter",
                   help="metrics spilled after failed forwards")
        M.callback("veneur.forward.spill.dropped_total",
                   lambda: (self.forward_spill.dropped_total
                            if self.forward_spill is not None else None),
                   kind="counter",
                   help="spilled metrics dropped at the cap or max age")
        M.callback("veneur.forward.acked_seq",
                   lambda: (float(self._fwd_acked_seq)
                            if self._fwd_source_id is not None
                            and self._fwd_acked_seq >= 0 else None),
                   help="highest sequence number the receiving tier has "
                        "acked in the current epoch")
        M.callback("veneur.dedup.window_evictions_total",
                   lambda: (float(self._dedup.evictions)
                            if self._dedup is not None else None),
                   kind="counter",
                   help="dedup streams evicted at the "
                        "forward_dedup_max_sources LRU bound")
        M.callback("veneur.checkpoint.age_s",
                   lambda: (time.time() - self._ckpt_writer.last_write_ts
                            if self._ckpt_writer is not None
                            and self._ckpt_writer.last_write_ts else None),
                   help="seconds since the last durable checkpoint")
        # overload management — None/[] while the controller is disabled
        # keeps the series out of the exposition, the same
        # absent-when-off convention as spill/checkpoint above
        M.callback("veneur.overload.state",
                   lambda: (float(self._overload.state)
                            if self._overload is not None else None),
                   help="health state: 0 healthy / 1 pressured / "
                        "2 shedding / 3 critical")
        M.callback("veneur.overload.pressure",
                   lambda: (self._overload.pressure
                            if self._overload is not None else None),
                   help="max normalized pressure signal, 0..1")
        M.callback("veneur.overload.shed_total",
                   lambda: (self._overload.shed_snapshot()
                            if self._overload is not None else []),
                   kind="counter", labelnames=("class",),
                   help="samples refused by admission control or flush "
                        "protection, by priority class")
        M.callback("veneur.overload.admitted_total",
                   lambda: (float(self._overload.admitted_total)
                            if self._overload is not None else None),
                   kind="counter",
                   help="packets admitted past the overload controller")
        M.callback("veneur.overload.degraded_flushes_total",
                   lambda: (float(self._overload.degraded_flushes)
                            if self._overload is not None else None),
                   kind="counter",
                   help="flushes published with degraded aggregation "
                        "corrections or CRITICAL fan-out filtering")
        M.callback("veneur.overload.degraded_samples_total",
                   self._collect_degraded_samples, kind="counter",
                   labelnames=("kind",),
                   help="samples statistically subsumed (not staged) by "
                        "degraded timer sampling / set subsampling")
        # multi-tenant fairness — [] while tenancy is disabled keeps the
        # labeled families out of the exposition entirely
        M.callback("veneur.tenant.admitted_total",
                   lambda: (self.tenancy.admitted_snapshot()
                            if self.tenancy is not None else []),
                   kind="counter", labelnames=("tenant",),
                   help="datagrams admitted past admission, by tenant")
        M.callback("veneur.tenant.shed_total",
                   lambda: (self.tenancy.shed_snapshot()
                            if self.tenancy is not None else []),
                   kind="counter", labelnames=("tenant",),
                   help="datagrams refused by admission, by tenant")
        M.callback("veneur.tenant.quarantined",
                   lambda: (self.tenancy.quarantined_snapshot()
                            if self.tenancy is not None else []),
                   labelnames=("tenant",),
                   help="1 while the tenant is demoted to aggregate-only "
                        "rollup rows by the tag-explosion detector")
        M.callback("veneur.tenant.demoted_rows_total",
                   lambda: (self.tenancy.demoted_rows_snapshot()
                            if self.tenancy is not None else []),
                   kind="counter", labelnames=("tenant",),
                   help="rows collapsed onto per-tenant rollup keys "
                        "while quarantined (exact)")
        # self-adjusting key tables — [] while growth is disabled keeps
        # the labeled families out of the exposition entirely
        M.callback("veneur.table.grows_total",
                   lambda: (self.tables.grows_snapshot()
                            if self.tables is not None else []),
                   kind="counter", labelnames=("kind",),
                   help="capacity grow swaps executed at the flush "
                        "boundary, by table kind")
        M.callback("veneur.table.capacity",
                   lambda: (self.tables.capacity_snapshot(
                            self.aggregator.spec)
                            if self.tables is not None else []),
                   labelnames=("kind",),
                   help="current per-kind key-table capacity (rows)")
        M.callback("veneur.table.evicted_total",
                   lambda: (self.tables.evicted_snapshot()
                            if self.tables is not None else []),
                   kind="counter", labelnames=("kind",),
                   help="keys reclaimed by the idle-TTL census "
                        "(table_idle_ttl_s), exact")
        M.callback("veneur.table.merged_cells_total",
                   lambda: (self.tables.pressure.merged_snapshot()
                            if self.tables is not None
                            and self.tables.pressure is not None else []),
                   kind="counter", labelnames=("kind",),
                   help="distinct long-tail keys redirected into SALSA "
                        "merge cells under table pressure (exact; "
                        "additive error bounded by the cell total)")
        M.callback("veneur.table.demoted_rows_total",
                   lambda: (self.tables.pressure.demoted_snapshot()
                            if self.tables is not None
                            and self.tables.pressure is not None else []),
                   kind="counter", labelnames=("kind",),
                   help="tag variants collapsed onto per-key-family "
                        "rollup rows by the explosion detector (exact)")

    # -- registry collector helpers -----------------------------------------
    def _ring_stats(self) -> dict:
        """Native ring snapshot, or {} on servers without the C++
        engine (collectors then read their zero defaults)."""
        fn = getattr(self.aggregator, "ring_stats", None)
        return fn() if fn is not None else {}

    def _collect_per_ring(self, key: str):
        """Labeled sample list for one per-ring stat: [((ring,), v)].
        Empty (no exposition rows) outside multi-ring mode. Allocation
        happens at collection cadence only — never on the ingest path."""
        fn = getattr(self.aggregator, "ring_stats_per_ring", None)
        if fn is None:
            return []
        return [((str(i),), float(st.get(key, 0)))
                for i, st in enumerate(fn())]

    def _poll_ring_telemetry(self) -> None:
        """Flush-interval poll: turn the cumulative C++ emit counters
        into one per-interval average-latency observation. Runs on the
        flush worker thread only (the prev-tuple needs no lock)."""
        st = self._ring_stats()
        calls = int(st.get("emit_packed_calls", 0))
        ns = int(st.get("emit_packed_ns", 0))
        pc, pn = self._ring_emit_prev
        if calls > pc:
            self._t_ring_emit.observe((ns - pn) / (calls - pc))
        self._ring_emit_prev = (calls, ns)

    def _breaker_list(self):
        out = [(s.name, self._sink_breakers[id(s)])
               for s in self.metric_sinks + self.span_sinks
               if id(s) in self._sink_breakers]
        if self._forward_breaker is not None:
            out.append(("forward", self._forward_breaker))
        return out

    def _collect_circuit_state(self):
        # fold same-named sink instances to the WORST state — duplicate
        # label sets are invalid exposition
        by_name: dict = {}
        for name, b in self._breaker_list():
            by_name[name] = max(by_name.get(name, 0), b.state)
        return [((name,), float(v)) for name, v in sorted(by_name.items())]

    def _collect_circuit_opens(self):
        by_name: dict = {}
        for name, b in self._breaker_list():
            by_name[name] = by_name.get(name, 0) + b.opens_total
        return [((name,), float(v)) for name, v in sorted(by_name.items())
                if v]

    def _collect_sink_retries(self):
        totals: dict = {}
        with self._sink_stats_lock:
            for name, n in self._fanout_retries.items():
                totals[name] = totals.get(name, 0) + n
        fwd = self._c_forward_retries.value()
        if fwd:
            totals["forward"] = totals.get("forward", 0) + fwd
        for s in self.metric_sinks + self.span_sinks:
            if isinstance(s, ResilientSink):
                own = s.reliability_counters()[0]
            else:
                own = getattr(s, "retries_total", 0)
            if own:
                totals[s.name] = totals.get(s.name, 0) + own
        return [((name,), float(n)) for name, n in sorted(totals.items())]

    def _collect_posts_skipped(self):
        totals: dict = {}
        for s in self.metric_sinks + self.span_sinks:
            if isinstance(s, ResilientSink):
                n = s.reliability_counters()[1]
                if n:
                    totals[s.name] = totals.get(s.name, 0) + n
        return [((name,), float(n)) for name, n in sorted(totals.items())]

    def _collect_degraded_samples(self):
        if self._overload is None:
            return []
        return [(("timer",),
                 float(getattr(self.aggregator, "degraded_timer_skipped", 0))),
                (("set",),
                 float(getattr(self.aggregator, "degraded_set_skipped", 0)))]

    # -- overload pressure signals ------------------------------------------
    def _overload_signals(self):
        """One {name: pressure} sample, each normalized to [0, 1] against
        that resource's capacity. The controller takes the max: one
        saturated resource IS an overloaded server. Every signal is
        defensive — a broken source reads 0 for a tick rather than
        killing the poller."""
        sig: dict = {}
        try:
            sig["packet_queue"] = (self.packet_queue.qsize()
                                   / max(1, self.packet_queue.maxsize))
        except Exception as e:
            log.debug("overload signal packet_queue failed: %s", e)
        try:
            sig["flush_jobs"] = (self._flush_jobs.qsize()
                                 / max(1, self._flush_jobs.maxsize))
        except Exception as e:
            log.debug("overload signal flush_jobs failed: %s", e)
        try:
            # flush lag against the same staleness budget the watchdog
            # and /healthz use; 1.0 == "watchdog would fire now"
            stale = time.time() - min(self.last_flush, self.last_flush_done)
            missed = self.cfg.flush_watchdog_missed_flushes
            budget = (missed * self.interval if missed and missed > 0
                      else 10.0 * self.interval + 60.0)
            sig["flush_lag"] = max(0.0, stale / budget)
        except Exception as e:
            log.debug("overload signal flush_lag failed: %s", e)
        try:
            # key-table capacity drops since the previous poll: any delta
            # means rows are ALREADY being lost, so saturate immediately
            drops = self.aggregator.dropped_capacity
            prev = getattr(self, "_ov_prev_capacity_drops", None)
            self._ov_prev_capacity_drops = drops
            if prev is not None and drops > prev:
                sig["capacity_drops"] = 1.0
            else:
                sig["capacity_drops"] = 0.0
        except Exception as e:
            log.debug("overload signal capacity_drops failed: %s", e)
        try:
            if self.forward_spill is not None \
                    and self.cfg.forward_spill_max_bytes > 0:
                sig["spill_bytes"] = (self.forward_spill.bytes
                                      / self.cfg.forward_spill_max_bytes)
        except Exception as e:
            log.debug("overload signal spill_bytes failed: %s", e)
        try:
            # an open forward breaker parks the server in PRESSURED
            # (0.75 sits between enter_pressured and enter_shedding at
            # the default thresholds): peers should stop sending, but
            # local traffic is still being aggregated fine
            if self._forward_breaker is not None \
                    and self._forward_breaker.state == OPEN:
                sig["forward_breaker"] = 0.75
        except Exception as e:
            log.debug("overload signal forward_breaker failed: %s", e)
        try:
            w = self._ckpt_writer
            if w is not None and w.last_write_ts:
                cadence = max(1, self.cfg.checkpoint_interval_flushes)
                budget = 10.0 * cadence * self.interval + 60.0
                sig["checkpoint_age"] = ((time.time() - w.last_write_ts)
                                         / budget)
        except Exception as e:
            log.debug("overload signal checkpoint_age failed: %s", e)
        try:
            # native datagram ring: the packet_queue signal reads ~0 when
            # UDP rides the C++ ring, so ring depth is the native path's
            # queue-pressure analogue; any ring overflow since the last
            # poll means datagrams are ALREADY being lost — saturate,
            # same policy as capacity_drops
            if self._native_readers_active:
                rcs = self.aggregator.reader_counters()
                sig["native_ring"] = rcs["ring_depth"] / 65536.0
                drops = rcs["ring_dropped"]
                prev = getattr(self, "_ov_prev_ring_drops", None)
                self._ov_prev_ring_drops = drops
                if prev is not None and drops > prev:
                    sig["native_ring"] = 1.0
        except Exception as e:
            log.debug("overload signal native_ring failed: %s", e)
        return sig

    def _sync_native_admission(self, ov) -> None:
        """Push the controller's statsd admission knobs into the C++
        reader ring and fold its exact per-class decisions back into the
        controller's counters. Gated on overload_native_admission (off =
        prior behavior: the native path bypasses admission entirely)."""
        if not (self._native_readers_active
                and self.cfg.overload_native_admission):
            return
        try:
            state, rate, burst, tags = ov.native_admission_params()
            self.aggregator.admission_set(True, state, rate, burst, tags)
            # the drain's "tenants" sub-dict routes through
            # fold_native_counts into the tenancy ledger, so per-tenant
            # counts ride the same exactly-once fold as the class counts
            ov.fold_native_counts(self.aggregator.admission_drain())
            self._sync_native_tenancy(drain=False)
        except Exception as e:
            log.warning("native admission sync failed: %s", e)

    def _push_tenant_config(self) -> None:
        """One-time tenant push-down, BEFORE rings start (the tag is
        read lock-free on the C++ admission path): create the engine
        table, replay checkpointed quarantine state, seed weights."""
        ten = self.tenancy
        fn = getattr(self.aggregator, "tenant_config", None)
        if ten is None or fn is None:
            return
        try:
            fn(**ten.native_config())
            if self._tenant_restore_entries:
                self.aggregator.tenant_restore(
                    self._tenant_restore_entries)
                self._tenant_restore_entries = None
            base_rate, weights = ten.native_params()
            self.aggregator.tenant_params(base_rate, weights)
        except Exception as e:
            log.warning("tenant config push-down failed: %s", e)

    def _sync_native_tenancy(self, drain: bool) -> None:
        """Per-tick tenant sync with the C++ engine: push base rate +
        weights, refresh the quarantine mirror from the engine table.
        With `drain`, also fold the per-tenant admission deltas into
        the tenancy ledger directly — used only when no overload
        controller owns the admission_drain fold (tenancy without
        overload, or overload_native_admission off)."""
        ten = self.tenancy
        if ten is None or not self._native_readers_active \
                or not hasattr(self.aggregator, "tenant_params"):
            return
        try:
            base_rate, weights = ten.native_params()
            self.aggregator.tenant_params(base_rate, weights)
            ten.update_table(self.aggregator.tenant_table())
            if drain:
                drained = self.aggregator.admission_drain()
                if self._overload is not None:
                    self._overload.fold_native_counts(drained)
                elif drained.get("tenants"):
                    ten.fold_native(drained["tenants"])
        except Exception as e:
            log.warning("tenant sync failed: %s", e)

    # -- tag exclusion wiring (server.go:1467-1510) -------------------------
    def _wire_excluded_tags(self):
        base: List[str] = []
        per_sink: dict = {}
        for entry in self.cfg.tags_exclude:
            parts = entry.split("|")
            if len(parts) == 1:
                base.append(entry)
            else:
                for sink_name in parts[1:]:
                    per_sink.setdefault(sink_name, []).append(parts[0])
        for sink in self.metric_sinks:
            sink.set_excluded_tags(base + per_sink.get(sink.name, []))
        # span sinks that opt in get the same rules (server.go:1467
        # setSinkExcludedTags wires BOTH sink kinds)
        for sink in self.span_pipeline.span_sinks:
            if hasattr(sink, "set_excluded_tags"):
                sink.set_excluded_tags(base + per_sink.get(sink.name, []))

    # -- ingest path --------------------------------------------------------
    def handle_metric_packet(self, packet: bytes) -> None:
        """reference server.go:939 HandleMetricPacket."""
        if not packet:
            return
        try:
            if packet.startswith(b"_e{"):
                sample = parser.parse_event(packet)
                with self._event_lock:
                    self.event_samples.append(sample)
            elif packet.startswith(b"_sc"):
                m = parser.parse_service_check(packet)
                self.aggregator.process_metric(m)
            else:
                m = parser.parse_metric(packet)
                self.aggregator.process_metric(m)
        except parser.ParseError as e:
            self._c_parse_errors.inc()
            log.debug("bad packet %r: %s", packet[:64], e)

    def _process_packets(self, data: bytes) -> None:
        """reference server.go:1081 processMetricPacket + SplitBytes. With
        the native engine, the whole buffer (splitting included) is handled
        in C++; only events/service checks come back up."""
        if self._overload is not None \
                and not self._overload.admit(data, "statsd"):
            # shed BEFORE the parse — the cost being refused is the
            # parse+stage itself. Counted per-class in
            # veneur.overload.shed_total. Native ring traffic never
            # reaches this path; with overload_native_admission the SAME
            # decision runs inside the C++ reader ring (vr_admission_set
            # push-down, exact counters folded back per poll), so the
            # shedding guarantees hold there too instead of being
            # bypassed.
            return
        if self._native:
            for special in self.aggregator.feed(data):
                self.handle_metric_packet(special)
            return
        for line in data.split(b"\n"):
            if line:
                self.handle_metric_packet(line)

    def _pipeline_loop(self):
        """The single device-owning thread (all worker goroutines in one).
        With the native reader group, UDP datagrams bypass packet_queue
        entirely: C++ threads recvmmsg into a ring, and pump() drains it
        here (parse + stage + batch dispatch) with the GIL released while
        idle. packet_queue still carries control items and the non-UDP
        listeners' data."""
        while True:
            # re-checked each pass: start() flips the flag after binding
            # the UDP sockets, which happens after this thread launches
            if self._native_readers_active:
                for special in self.aggregator.pump(20):
                    # through the backstop like every other work item (a
                    # special is one event/service-check line; the extra
                    # native feed() round-trip just re-classifies it)
                    self._dispatch_item(special)
                while True:
                    try:
                        item = self.packet_queue.get_nowait()
                    except queue.Empty:
                        break
                    if item is _STOP:
                        return
                    self._dispatch_item(item)
            else:
                try:
                    item = self.packet_queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                if item is _STOP:
                    return
                self._dispatch_item(item)

    def _dispatch_item(self, item):
        try:
            self._dispatch_item_inner(item)
        except Exception as e:
            # the pipeline thread must NEVER die to a data-plane
            # exception: two fuzz-found bug classes (set members, event
            # datagrams) escaped the ParseError-only catch below and
            # silently wedged the server — the backstop for the NEXT
            # unknown class is here, at the single place every work item
            # passes through (the native pump path routes its specials
            # here too). Counted and logged with traceback; a flush
            # request that died mid-handling must still release its
            # waiter instead of letting trigger_flush block out its
            # whole budget.
            self._c_internal_errors.inc()
            log.exception("pipeline item failed (server continues); "
                          "item=%r", type(item).__name__)
            if isinstance(item, (FlushRequest, PipelineRequest)):
                item.finish(False, f"internal error: {e}")

    def _dispatch_item_inner(self, item):
        if isinstance(item, FlushRequest):
            self._handle_flush_request(item)
        elif isinstance(item, PipelineRequest):
            # query-tier snapshot/launch visits: FIFO position in this
            # queue is exactly the read-your-writes boundary, and a
            # launch dispatched here precedes any later donating ingest
            # step (veneur_tpu/query/snapshot.py)
            item.run(self.aggregator)
        elif isinstance(item, _ImportBytes):
            t0 = time.perf_counter_ns()
            n, errs = self.aggregator.import_pb_bytes(bytes(item))
            self._c_imported.inc(n)
            if errs:
                self._c_import_errors.inc(errs)
            report_one(self.trace_client, ssf_samples.timing(
                "veneur.import.response_duration_ns",
                (time.perf_counter_ns() - t0) / 1e9, {"part": "merge"}))
        elif isinstance(item, _ImportBatch):
            from veneur_tpu.forward.convert import import_into
            # counted here on the single pipeline thread, not in the
            # multi-threaded gRPC handler, so concurrent imports can't
            # lose increments (importsrv/server.go:130 import.metrics_total)
            t0 = time.perf_counter_ns()
            self._c_imported.inc(len(item))
            for metric in item:
                try:
                    import_into(self.aggregator, metric)
                except Exception as e:
                    # counted into self-telemetry so a mixed fleet sees
                    # incompatible payloads (e.g. foreign sketch bytes)
                    # instead of silently losing them
                    self._c_import_errors.inc()
                    log.warning("bad imported metric %s: %s",
                                metric.name, e)
            # README §Monitoring: import.response_duration_ns part:merge
            # (http.go:78 — time spent handing metrics to workers);
            # helpers imported at module top — this is the serialized
            # pipeline thread, no per-batch sys.modules hits
            report_one(self.trace_client, ssf_samples.timing(
                "veneur.import.response_duration_ns",
                (time.perf_counter_ns() - t0) / 1e9, {"part": "merge"}))
        elif isinstance(item, _SpanMetricBatch):
            for m in item:
                self.aggregator.process_metric(m)
        else:
            self._process_packets(item)

    def _handle_flush_request(self, req: FlushRequest) -> None:
        """Pipeline-thread half of a flush: ONLY the state/table swap; all
        downstream work (device flush math, intermetric generation, sink
        fan-out, plugins) runs on the flush worker so ingest never stalls
        behind a slow sink (flusher.go:105-115 semantics)."""
        # Backpressure check BEFORE the swap: when the flush worker is
        # backlogged the interval simply extends in device state — nothing
        # is discarded (the reference never drops aggregated data short of
        # a crash, flusher.go:28-131; the watchdog remains the backstop
        # for a fully wedged worker). Only the pipeline thread puts jobs,
        # so full() → put_nowait cannot race into queue.Full.
        if self._flush_jobs.full():
            self._c_intervals_deferred.inc()
            log.warning("flush worker backlogged; interval deferred "
                        "(state retained)")
            req.finish(False, "deferred: flush worker backlogged")
            return
        # A flush landing mid-reshard completes the remaining migration
        # folds synchronously FIRST (we are on the pipeline thread, so
        # folding here races nothing): flush output then covers the whole
        # drained interval, and the transition is bounded at one flush
        # boundary by construction.
        if self.reshard is not None and self.reshard.active:
            self.reshard.complete_pending_folds(
                self.aggregator,
                float(self.cfg.reshard_transfer_timeout_s))
        now = time.time()
        self.last_flush = now
        # self-adjusting key tables: a due capacity change executes AT
        # this swap boundary (tables/growth.py — the one sanctioned grow
        # site), so the grow pause IS the swap pause. Serialized against
        # resharding: while a reshard owns the swap boundary, planning
        # is deferred to the next flush (trigger_table_grow rejects with
        # 409 instead).
        grow_targets = None
        if self.tables is not None and not self.reshard_active:
            try:
                grow_targets = self.tables.plan(self.aggregator)
            except Exception:
                log.exception("table grow planning failed; interval "
                              "flushes at current capacities")
        # the interval's OWNING aggregator rides the flush job: after a
        # grow the detached interval's flush math must run against the
        # OLD spec's backend, not the freshly installed one
        agg = self.aggregator
        # the ingest-drain phase: how long the interval's device state
        # takes to detach from the hot path (the only flush work that
        # blocks ingest) — timed here, surfaced as the flush trace's
        # first child span and the phase=ingest_drain timer
        swap_t0 = time.perf_counter_ns()
        try:
            if grow_targets:
                from veneur_tpu.tables import grow_swap, grown_spec
                state, table, agg = grow_swap(
                    self, grown_spec(agg.spec, grow_targets))
            else:
                state, table = self.aggregator.swap()
        except Exception as e:
            log.exception("flush swap failed")
            req.finish(False, f"swap failed: {e}")
            return
        swap_ns = time.perf_counter_ns() - swap_t0
        if grow_targets:
            self.tables.note_grow(grow_targets, swap_ns)
        self._t_flush_phase.observe(swap_ns, phase="ingest_drain")
        # snapshot pipeline-owned counters here: the native engine's
        # stats call isn't safe to interleave with feed()
        stats = {
            "swap_ns": swap_ns,
            "h2d_bytes": getattr(self.aggregator, "h2d_bytes", 0),
            "packets_received": self.packets_received,
            "packets_dropped": self.packets_dropped,
            "packets_toolong": self.packets_toolong,
            "parse_errors": self.parse_errors
            + self.aggregator.extra_parse_errors(),
            "processed": self.aggregator.processed + 0,
            "dropped": self.aggregator.dropped_capacity,
            "import_errors": self.import_errors,
            "internal_errors": self.internal_errors,
            "imported_total": self.imported_total,
            "forward_errors": self.forward_errors,
            "spans_received": self.span_pipeline.spans_received,
            "span_chan_cap_hits": self.span_pipeline.chan_cap_hits,
            "intervals_deferred": self.flush_intervals_deferred,
            "sink_flushes_skipped": self.sink_flushes_skipped,
            # set-subsample shift that was ACTIVE for the interval just
            # detached (latched by swap) — the flush worker multiplies
            # set estimates by 2^shift to undo the member subsampling
            "set_shift": getattr(self.aggregator, "last_set_shift", 0),
        }
        self._flush_jobs.put_nowait((agg, state, table, stats, now, req))

    # -- listeners ----------------------------------------------------------
    def _bind_unix(self, sock: socket.socket, path: str) -> None:
        """Bind a unix socket with the reference's ownership semantics
        (networking.go:286-302 acquireLockForSocket + :304 abstract):
        '@name' is the Linux abstract namespace — no filesystem presence,
        no lock; pathname sockets take an exclusive flock on
        '<path>.lock' (two veneurs must never share a socket file),
        clear any stale socket, and are chmod'd 0666 so any local
        process can emit."""
        if path.startswith("@"):
            sock.bind(unix_bind_address(path))
            return
        import fcntl
        lock_path = path + ".lock"
        lock_fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(lock_fd)
            raise RuntimeError(
                f"lock file {lock_path!r} for socket {path!r} is held by "
                "another process already")
        self._unix_locks.append((lock_fd, lock_path, path))
        if os.path.exists(path):
            os.unlink(path)
        sock.bind(path)
        os.chmod(path, 0o666)

    def _release_unix_locks(self) -> None:
        for lock_fd, lock_path, sock_path in self._unix_locks:
            try:
                os.unlink(sock_path)
            except OSError:
                pass
            # the .lock file itself is deliberately NOT unlinked: flock
            # mutual exclusion only holds if every contender locks the
            # same inode; unlinking would let a starting server create a
            # fresh inode while another holds the old one — two owners
            try:
                os.close(lock_fd)   # closing releases the flock
            except OSError:
                pass
        self._unix_locks = []

    def _udp_reader(self, sock: socket.socket):
        # buffer is metric_max_length+1 so an over-limit datagram is
        # detectable by length and dropped WHOLE with a counter — the
        # reference's "toolong" guard (server.go:800 pool sizing,
        # :1082 processMetricPacket). A directly-constructed Config
        # (tests/embedding) leaves the field 0 — the YAML reader is what
        # applies the 4096 default — so 0 means the UDP datagram bound.
        limit = self.cfg.metric_max_length or 65536
        bufsize = limit + 1
        sock.settimeout(0.5)  # lets readers observe shutdown and release fd
        # Several reader threads (one per bound socket) share the fold
        # counters with the shutdown fold and the property readers. The
        # fold is batched per recv-loop iteration: one blocking recv,
        # then drain whatever else the kernel already has (bounded), then
        # ONE lock acquisition for the whole batch — at num_readers > 1
        # the per-datagram acquisition made the shared lock the hot
        # loop's serialization point.
        batch_cap = 64
        while not self._shutdown.is_set():
            try:
                data = sock.recv(bufsize)
            except socket.timeout:
                continue
            except OSError:
                return
            batch = [data]
            sock.setblocking(False)
            try:
                while len(batch) < batch_cap:
                    batch.append(sock.recv(bufsize))
            except OSError:
                pass  # EAGAIN: kernel queue drained (or socket closing —
                #       the next blocking recv surfaces a real error)
            finally:
                sock.settimeout(0.5)
            received = len(batch)
            toolong = dropped = 0
            for data in batch:
                if len(data) > limit:
                    toolong += 1
                    continue
                try:
                    self.packet_queue.put(data, timeout=1.0)
                except queue.Full:
                    dropped += 1  # backpressure drop, counted
            with self._reader_fold_lock:
                self._packets_received += received
                self._packets_toolong_py += toolong
                self._packets_dropped_py += dropped

    @property
    def packets_received(self) -> int:
        """Python-read packets plus the native reader group's datagrams
        (C++ counters are mutex-guarded; readable from any thread)."""
        with self._reader_fold_lock:
            n = self._packets_received
            if self._native_readers_active:
                n += self.aggregator.reader_counters()["datagrams"]
        return n

    @property
    def packets_dropped(self) -> int:
        """Datagrams lost to backpressure after the kernel delivered them:
        the native ring's overflow or the Python path's queue.Full drops."""
        with self._reader_fold_lock:
            n = self._packets_dropped_py
            if self._native_readers_active:
                n += self.aggregator.reader_counters()["ring_dropped"]
        return n

    @property
    def packets_toolong(self) -> int:
        """Whole datagrams dropped for exceeding metric_max_length
        (reference packet.error_total{reason:toolong})."""
        with self._reader_fold_lock:
            n = self._packets_toolong_py
            if self._native_readers_active:
                n += self.aggregator.reader_counters()["toolong"]
        return n

    # -- registry-backed compatibility accessors ----------------------------
    # The plain counter attributes these replaced were read by embedders,
    # tests and httpapi; keep the names as int views over the registry.

    @property
    def parse_errors(self) -> int:
        return int(self._c_parse_errors.value())

    @property
    def import_errors(self) -> int:
        return int(self._c_import_errors.value())

    @property
    def internal_errors(self) -> int:
        return int(self._c_internal_errors.value())

    @property
    def imported_total(self) -> int:
        return int(self._c_imported.value())

    @property
    def forward_errors(self) -> int:
        return int(self._c_forward_errors.value())

    @property
    def forward_sends_total(self) -> int:
        return int(self._c_forward_sends.value())

    @property
    def forward_retries_total(self) -> int:
        return int(self._c_forward_retries.value())

    @property
    def flush_count(self) -> int:
        return int(self._c_flush_count.value())

    @property
    def flush_intervals_deferred(self) -> int:
        return int(self._c_intervals_deferred.value())

    @property
    def sink_flushes_skipped(self) -> int:
        return int(self._c_sink_skips.value())

    def _ssf_udp_reader(self, sock: socket.socket):
        """One SSF span protobuf per datagram (server.go:1125
        ReadSSFPacketSocket -> HandleTracePacket)."""
        from veneur_tpu.protocol.wire import parse_ssf
        bufsize = self.cfg.trace_max_length_bytes or MAX_UDP_SSF
        sock.settimeout(0.5)
        while not self._shutdown.is_set():
            try:
                data = sock.recv(bufsize)
            except socket.timeout:
                continue
            except OSError:
                return
            if not data:
                continue
            try:
                span = parse_ssf(data)
            except Exception:
                self._c_parse_errors.inc()
                continue
            self.span_pipeline.handle_span(span, ssf_format="packet")

    def _ssf_stream_listener(self, sock: socket.socket):
        """Framed SSF stream (server.go:1160 ReadSSFStreamSocket)."""
        sock.settimeout(0.5)
        while not self._shutdown.is_set():
            try:
                conn, _ = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._ssf_stream_conn, args=(conn,),
                             daemon=True).start()

    def _ssf_stream_conn(self, conn):
        """Buffered frame reader: framing errors (bad version, oversized
        length) poison the stream and close it (wire.go IsFramingError), but
        a corrupt protobuf body inside a well-formed frame is recoverable —
        the frame boundary is intact, so keep reading (server.go:1186).
        The 0.5s recv timeout lets the thread observe shutdown."""
        import struct
        from veneur_tpu.protocol.wire import MAX_SSF_PACKET_LENGTH, parse_ssf
        buf = b""
        conn.settimeout(0.5)
        with conn:
            while not self._shutdown.is_set():
                try:
                    data = conn.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not data:
                    return
                buf += data
                while len(buf) >= 5:
                    if buf[0] != 0:
                        self._c_parse_errors.inc()
                        return  # unknown frame version: poisoned
                    (length,) = struct.unpack(">I", buf[1:5])
                    if length > MAX_SSF_PACKET_LENGTH:
                        self._c_parse_errors.inc()
                        return  # oversized frame: poisoned
                    if len(buf) < 5 + length:
                        break
                    body, buf = buf[5:5 + length], buf[5 + length:]
                    try:
                        span = parse_ssf(body)
                    except Exception:
                        self._c_parse_errors.inc()
                        continue
                    self.span_pipeline.handle_span(span,
                                                   ssf_format="framed")

    def _tcp_listener(self, sock: socket.socket, tls_ctx):
        """reference server.go:1283 ReadTCPSocket: newline-delimited metrics
        over stream conns, optional TLS with client-cert auth."""
        sock.settimeout(0.5)
        while not self._shutdown.is_set():
            try:
                conn, _ = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # connection cap BEFORE spawning a thread: each conn costs a
            # reader thread, and an accept flood must degrade to refused
            # connections (counted, retryable) rather than thread
            # exhaustion
            cap = self.cfg.tcp_max_connections
            if cap and cap > 0:
                with self._tcp_conn_lock:
                    if self._tcp_conns_live >= cap:
                        over = True
                    else:
                        over = False
                        self._tcp_conns_live += 1
                if over:
                    self._c_tcp_rejected.inc()
                    log.warning("TCP statsd connection refused: "
                                "tcp_max_connections=%d reached", cap)
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
            else:
                with self._tcp_conn_lock:
                    self._tcp_conns_live += 1
            conn.settimeout(5.0)
            if tls_ctx is not None:
                try:
                    conn = tls_ctx.wrap_socket(conn, server_side=True)
                except ssl.SSLError as e:
                    log.warning("TLS handshake failed: %s", e)
                    with self._tcp_conn_lock:
                        self._tcp_conns_live -= 1
                    continue
            t = threading.Thread(target=self._tcp_conn, args=(conn,),
                                 daemon=True)
            t.start()

    def _tcp_conn(self, conn):
        buf = b""
        limit = self.cfg.metric_max_length
        idle_limit = self.cfg.tcp_idle_timeout_s
        if idle_limit and idle_limit > 0:
            # wake often enough to notice the deadline: the 5.0s recv
            # timeout set at accept only bounds ONE recv, so a slowloris
            # peer trickling a byte per timeout held the thread forever
            conn.settimeout(min(5.0, idle_limit))
        last_data = time.monotonic()
        try:
            with conn:
                while not self._shutdown.is_set():
                    try:
                        data = conn.recv(65536)
                    except socket.timeout:
                        # idle conns stay open (server.go ReadTCPSocket)
                        # unless an idle deadline is configured
                        if idle_limit and idle_limit > 0 and \
                                time.monotonic() - last_data >= idle_limit:
                            self._c_tcp_idle_closed.inc()
                            log.info("TCP statsd connection closed: idle "
                                     "for %.1fs (deadline %.1fs)",
                                     time.monotonic() - last_data,
                                     idle_limit)
                            return
                        continue
                    except OSError:
                        return
                    if not data:
                        break
                    last_data = time.monotonic()
                    buf += data
                    *lines, buf = buf.split(b"\n")
                    for line in lines:
                        if len(line) > limit:
                            self._c_parse_errors.inc()
                            continue
                        if line:
                            self.packet_queue.put(line)
                    if len(buf) > limit:
                        # oversized line w/o newline: drop conn
                        self._c_parse_errors.inc()
                        return
        finally:
            with self._tcp_conn_lock:
                self._tcp_conns_live -= 1

    def _tls_context(self):
        if not (self.cfg.tls_key and self.cfg.tls_certificate):
            return None
        import tempfile
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        cert = key = None
        try:
            cert = self._write_temp(tempfile, self.cfg.tls_certificate)
            key = self._write_temp(tempfile, self.cfg.tls_key)
            ctx.load_cert_chain(cert, key)
        finally:
            # never leave key material on disk
            for path in (cert, key):
                if path:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
        if self.cfg.tls_authority_certificate:
            ctx.load_verify_locations(
                cadata=self.cfg.tls_authority_certificate)
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    @staticmethod
    def _write_temp(tempfile, pem: str) -> str:
        f = tempfile.NamedTemporaryFile("w", suffix=".pem", delete=False)
        f.write(pem)
        f.close()
        return f.name

    def start(self):
        """reference server.go:771 Start + networking.go:19 StartStatsd."""
        # chaos: env overrides config; both use the reliability/faults.py
        # spec grammar. Armed BEFORE any listener or flush thread exists
        # so the very first interval can be faulted.
        fault_spec = (os.environ.get("VENEUR_FAULT_INJECTION", "")
                      or self.cfg.fault_injection)
        if fault_spec:
            FAULTS.configure(fault_spec)
        if self.cfg.sentry_dsn:
            from veneur_tpu.utils import crash
            crash.setup(self.cfg.sentry_dsn)
            crash.hook_threads()
        if self.cfg.enable_profiling:
            # reference server.go:1337 pkg/profile CPU profile; dumped as
            # pstats at shutdown
            import cProfile
            self._profiler = cProfile.Profile()
            self._profiler.enable()
        if self.cfg.mutex_profile_fraction or self.cfg.block_profile_rate:
            # accepted for config-surface compat (server.go:331-344 sets
            # Go runtime profiling rates); CPython has no mutex/block
            # profiler to arm — say so instead of silently ignoring
            log.warning(
                "mutex_profile_fraction/block_profile_rate are Go-runtime "
                "knobs with no CPython equivalent; ignored "
                "(use enable_profiling for the cProfile CPU profile)")
        for sink in self.metric_sinks + self.span_sinks:
            sink.start()
        # durable restart: fold the newest valid checkpoint into the
        # (still-empty) first interval BEFORE any ingest thread exists —
        # restore merges through the same sketch ops as live traffic, so
        # samples arriving after this point land on top losslessly
        if self._ckpt_writer is not None and self.cfg.restore_on_start:
            self._restore_from_checkpoint()
            self._restore_complete = True  # /readyz gates on this
        if self._overload is not None:
            # the poller pushes the degradation knobs into the aggregator
            # each tick; active_set_shift latches at the next swap so the
            # 2^k flush correction always matches what was staged
            def _push_degrade(ov):
                self.aggregator.degraded_timer_rate = ov.degraded_timer_rate()
                self.aggregator.pending_set_shift = ov.degraded_set_shift()
                # native ring admission rides the same poll tick: push
                # the current state/bucket knobs down, fold the exact
                # per-class decisions made since the last tick back up
                self._sync_native_admission(ov)

            self._overload.start(self.cfg.overload_poll_interval_s,
                                 on_poll=_push_degrade)
        t = threading.Thread(target=self._pipeline_loop, daemon=True,
                             name="pipeline")
        t.start()
        self._pipeline_thread = t
        self._threads.append(t)
        fw = threading.Thread(target=self._flush_worker, daemon=True,
                              name="flush-worker")
        fw.start()
        self._flush_thread = fw

        # C++ recvmmsg readers when the native engine is active: socket
        # reads and parsing never touch the GIL (the Python per-datagram
        # recv -> queue.put loop capped ingest around 6k datagrams/s and
        # dropped 31% of BASELINE config 1's replay)
        use_native_readers = (self._native and self.cfg.native_udp_readers
                              and hasattr(self.aggregator, "readers_start"))
        # multi-ring scale-out: with reader_rings > 1 each ring owns its
        # SO_REUSEPORT socket, so the bind fan-out follows reader_rings
        # (kernel flow-hashes datagrams across the group; one fd -> one
        # ring -> one parser core, no cross-core handoff)
        n_rings = max(1, self.cfg.reader_rings) if use_native_readers else 1
        udp_fanout = max(1, self.cfg.num_readers, n_rings)
        native_reader_fds = []
        for addr in self.cfg.statsd_listen_addresses:
            kind, target = resolve_addr(addr)
            if kind == "udp":
                for reader_i in range(udp_fanout):
                    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                    if udp_fanout > 1 and hasattr(
                            socket, "SO_REUSEPORT"):
                        sock.setsockopt(socket.SOL_SOCKET,
                                        socket.SO_REUSEPORT, 1)
                        # a :0 address must resolve ONCE: re-binding port
                        # 0 per reader yields N distinct ephemeral ports
                        # and no kernel sharding (reference
                        # networking.go:44-55 reuses the first socket's
                        # concrete address for the rest of the group)
                        if reader_i == 1 and target[1] == 0:
                            target = self._sockets[-1].getsockname()
                    if self.cfg.read_buffer_size_bytes > 0:
                        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                        self.cfg.read_buffer_size_bytes)
                    # else: keep the kernel default — SO_RCVBUF=0 clamps
                    # to the ~2KB minimum and a loopback burst of a few
                    # dozen datagrams already overruns it (read_config
                    # applies the 2MiB default; direct Config() users
                    # must not get a lossy listener)
                    sock.bind(target)
                    self._sockets.append(sock)
                    if use_native_readers:
                        native_reader_fds.append(sock.fileno())
                    else:
                        rt = threading.Thread(target=self._udp_reader,
                                              args=(sock,), daemon=True)
                        rt.start()
                        self._threads.append(rt)
            elif kind == "tcp":
                sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                sock.bind(target)
                sock.listen(128)
                self._sockets.append(sock)
                lt = threading.Thread(target=self._tcp_listener,
                                      args=(sock, self._tls_context()),
                                      daemon=True)
                lt.start()
                self._threads.append(lt)
            elif kind == "unixgram":
                # datagram statsd (networking.go:145 startStatsdUnix:
                # ListenUnixgram)
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
                self._bind_unix(sock, target)
                self._sockets.append(sock)
                rt = threading.Thread(target=self._udp_reader, args=(sock,),
                                      daemon=True)
                rt.start()
                self._threads.append(rt)
            elif kind == "unix":
                # stream statsd: newline-delimited metrics over
                # SOCK_STREAM, same read loop as TCP minus TLS (the
                # reference supports only unixgram statsd and panics on
                # unix:// — networking.go:29; accepting the stream form
                # here is a strict superset)
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                self._bind_unix(sock, target)
                sock.listen(128)
                self._sockets.append(sock)
                lt = threading.Thread(target=self._tcp_listener,
                                      args=(sock, None), daemon=True)
                lt.start()
                self._threads.append(lt)

        if native_reader_fds:
            # tenant identity/quarantine live in the multi-ring engine's
            # admission path: config must land before any ring thread
            # exists, and a 1-ring tenant config still routes through the
            # vrm engine (force_rings) instead of the tenant-blind vr one
            if self.tenancy is not None:
                self._push_tenant_config()
            # +1 so the kernel flags (MSG_TRUNC) any datagram OVER the
            # limit; the C++ reader drops it whole and counts toolong —
            # the same guard as the Python reader / the reference
            self.aggregator.readers_start(
                native_reader_fds,
                max_len=(self.cfg.metric_max_length or 65536) + 1,
                n_rings=n_rings,
                pin_cores=list(self.cfg.reader_pin_cores) or None,
                force_rings=self.tenancy is not None)
            self._native_readers_active = True
            # arm ring admission from the first datagram — the poller's
            # first tick is up to poll_interval away
            if self._overload is not None:
                self._sync_native_admission(self._overload)
            else:
                self._sync_native_tenancy(drain=False)

        # SSF span listeners (networking.go:198 StartSSF)
        self.span_pipeline.start()
        for addr in self.cfg.ssf_listen_addresses:
            kind, target = resolve_addr(addr)
            if kind in ("udp", "unixgram"):
                if kind == "udp":
                    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                    sock.bind(target)
                else:
                    sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
                    self._bind_unix(sock, target)
                self._sockets.append(sock)
                rt = threading.Thread(target=self._ssf_udp_reader,
                                      args=(sock,), daemon=True)
                rt.start()
                self._threads.append(rt)
            elif kind in ("unix", "tcp"):
                if kind == "unix":
                    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    self._bind_unix(sock, target)
                else:
                    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                    sock.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_REUSEADDR, 1)
                    sock.bind(target)
                sock.listen(64)
                self._sockets.append(sock)
                lt = threading.Thread(target=self._ssf_stream_listener,
                                      args=(sock,), daemon=True)
                lt.start()
                self._threads.append(lt)

        ft = threading.Thread(target=self._flush_ticker, daemon=True,
                              name="flush-ticker")
        ft.start()
        self._threads.append(ft)

        if self.cfg.flush_watchdog_missed_flushes > 0:
            wt = threading.Thread(target=self._watchdog, daemon=True,
                                  name="flush-watchdog")
            wt.start()
            self._threads.append(wt)

        # HTTP API (reference server.go:1303 Serve + http.go Handler)
        if self.cfg.http_address:
            from veneur_tpu.server.httpapi import start_http_server
            kind, target = resolve_addr(
                self.cfg.http_address if "//" in self.cfg.http_address
                else f"tcp://{self.cfg.http_address}")
            if kind != "tcp":
                raise ValueError(
                    f"http_address must be tcp, got {self.cfg.http_address!r}")
            self._httpd = start_http_server(self, target)
            self.http_port = self._httpd.server_address[1]

        # global-tier import server (reference importsrv/, server.go:753-762)
        if self.cfg.grpc_address:
            from veneur_tpu.forward import rpc
            _, target = resolve_addr(
                self.cfg.grpc_address
                if "//" in self.cfg.grpc_address
                else f"tcp://{self.cfg.grpc_address}")
            native_import = hasattr(self.aggregator, "import_pb_bytes")
            # with a dedup window the service runs the exactly-once
            # contract: envelopes parsed from metadata, malformed ones
            # rejected (INVALID_ARGUMENT), and a shed import NACKed
            # (RESOURCE_EXHAUSTED) so the sender keeps its unit staged
            self._grpc_server, self.grpc_port = rpc.serve(
                self.import_bytes if native_import
                else self.import_metrics,
                f"{target[0]}:{target[1]}", raw=native_import,
                with_metadata=self._dedup is not None,
                on_reject=self._c_envelope_rejected.inc)
        # forwarding client, dialed once at start (server.go:843-851);
        # http(s):// addresses take the HTTP /import path unless
        # forward_use_grpc forces gRPC (flusher.go:84-95 dispatch)
        if self.cfg.forward_address:
            from veneur_tpu.forward.rpc import (
                ForwardClient, HTTPForwardClient)
            addr = self.cfg.forward_address
            is_http = addr.startswith(("http://", "https://"))
            if is_http and not self.cfg.forward_use_grpc:
                # no retry_policy here: _send_forward wraps BOTH client
                # kinds uniformly (and counts retries); the client-level
                # hook stays for embedders driving the client directly
                self._forward_client = HTTPForwardClient(addr)
            else:
                for prefix in ("http://", "https://", "grpc://", "tcp://"):
                    if addr.startswith(prefix):
                        addr = addr[len(prefix):]
                # with retries configured, queue RPCs while the channel
                # (re)connects instead of failing fast — a reconnect after
                # UNAVAILABLE then succeeds within the same flush
                self._forward_client = ForwardClient(
                    addr, wait_for_ready=self.cfg.sink_retry_max > 0)
        self._redact_secrets()

    _SECRET_FIELDS = (
        # the reference's list (server.go:741-747) ...
        "sentry_dsn", "tls_key", "datadog_api_key", "signalfx_api_key",
        "lightstep_access_token", "aws_access_key_id",
        "aws_secret_access_key",
        # ... plus this config surface's other credential fields
        "trace_lightstep_access_token", "splunk_hec_token")

    def _redact_secrets(self) -> None:
        """Scrub credentials from the retained config once every consumer
        (sinks, TLS context, crash reporter — all built by now) holds its
        own copy (server.go:741-747): anything that later dumps state
        (debug endpoints, crash reports, logs) cannot leak keys. The
        server redacts its OWN shallow copy — the caller's Config object
        stays intact, so reusing it for another server keeps working."""
        import dataclasses as _dc
        self.cfg = _dc.replace(self.cfg)
        for f in self._SECRET_FIELDS:
            if getattr(self.cfg, f, ""):
                setattr(self.cfg, f, "REDACTED")
        if self.cfg.signalfx_per_tag_api_keys:
            self.cfg.signalfx_per_tag_api_keys = [
                {"name": d.get("name", ""), "api_key": "REDACTED"}
                for d in self.cfg.signalfx_per_tag_api_keys]

    def _dedup_check(self, envelope) -> Optional[bool]:
        """Exactly-once admission for one enveloped import batch. Runs
        AFTER overload admission (a shed batch must not mark the window:
        the sender re-sends and would read 'duplicate' for data that was
        never folded) and BEFORE the enqueue, which cannot fail.

        Returns None = fold it (fresh, or dedup/envelope off), True =
        suppress but ACK (already folded, or past the window's staleness
        bound — acking lets the sender evict; NACKing would replay
        forever). Raises EnvelopeError (counted) for envelopes the
        window refuses to accept at all."""
        if self._dedup is None or envelope is None:
            return None
        try:
            verdict = self._dedup.observe(envelope)
        except EnvelopeError:
            self._c_envelope_rejected.inc()
            raise
        if verdict == FRESH:
            return None
        self._c_dup_suppressed.inc()
        return True

    def import_metrics(self, metrics: List, envelope=None) -> bool:
        """gRPC import entry: enqueue onto the pipeline thread
        (importsrv/server.go:102 SendMetrics → IngestMetrics). Returns
        False when CRITICAL overload sheds the batch (HTTP callers turn
        that into a 503, the enveloped gRPC service into
        RESOURCE_EXHAUSTED, so the sender retries elsewhere/later)."""
        if self._overload is not None \
                and not self._overload.admit_import(len(metrics)):
            return False
        if self._dedup_check(envelope):
            return True
        self.packet_queue.put(_ImportBatch(metrics))
        self._trace_import_absorb(envelope, rows=len(metrics))
        return True

    def import_bytes(self, data: bytes, envelope=None) -> bool:
        """Raw-bytes gRPC import entry (native decode path): the
        pipeline thread hands the serialized MetricList straight to the
        C++ importer. Same CRITICAL-shed contract as import_metrics."""
        if self._overload is not None \
                and not self._overload.admit_import():
            return False
        if self._dedup_check(envelope):
            return True
        self.packet_queue.put(_ImportBytes(data))
        self._trace_import_absorb(envelope, nbytes=len(data))
        return True

    def _trace_import_absorb(self, envelope, rows=None, nbytes=None):
        """Wire-side half of the cross-tier flush trace: when the
        sender's envelope carries trace context, record an absorb span
        parented onto ITS flush.forward span — the receiving tier's
        span pipeline then holds one connected tree per interval. A
        legacy / untraced envelope (no context) records nothing."""
        if envelope is None \
                or getattr(envelope, "trace_id", None) is None:
            return
        from veneur_tpu.trace.tracer import Span
        sp = Span("veneur.import.absorb", service="veneur",
                  trace_id=envelope.trace_id,
                  parent_id=envelope.parent_span_id)
        sp.set_tag("source_id", envelope.source_id)
        if rows is not None:
            sp.set_tag("rows", str(rows))
        if nbytes is not None:
            sp.set_tag("bytes", str(nbytes))
        sp.client_finish(self.trace_client)

    def process_span_metrics(self, metrics: List) -> None:
        """Extraction-sink loop-back: span-derived UDPMetrics re-enter the
        aggregation pipeline (ssfmetrics/metrics.go:65-69 routing)."""
        self.packet_queue.put(_SpanMetricBatch(metrics))

    def local_addr(self, index: int = 0):
        return self._sockets[index].getsockname()

    # -- flush orchestration ------------------------------------------------
    def _flush_ticker(self):
        if self.cfg.synchronize_with_interval:
            # align the first tick to a wall-clock multiple of the
            # interval for downstream bucketing convenience
            # (server.go:866-870 CalculateTickDelay)
            if self._shutdown.wait(tick_delay(self.interval, time.time())):
                return
            self.trigger_flush(wait=False)
        while not self._shutdown.wait(self.interval):
            self.trigger_flush(wait=False)

    def trigger_flush(self, wait: bool = True,
                      timeout: Optional[float] = None):
        """Enqueue a flush on the pipeline thread (the ticker of
        server.go:853-890).

        With wait=True (the reference tests' manual-flush idiom), blocks
        until THIS request's flush completed and returns True on success,
        False on deferral/failure/timeout — never silently. The default
        timeout is generous because the first flush on a real TPU compiles
        the swap/flush programs (tens of seconds); callers that can't
        tolerate that pass their own.

        With wait=False returns the FlushRequest, so a caller can observe
        this specific flush later (req.wait / req.ok / req.detail)."""
        req = FlushRequest()
        self.packet_queue.put(req)
        if not wait:
            return req
        budget = timeout if timeout is not None else max(
            2 * self.interval, 120.0)
        ok = req.wait(budget)
        if not ok:
            log.warning("manual flush did not complete: %s", req.detail)
        return ok

    @property
    def reshard_active(self) -> bool:
        return self.reshard is not None and self.reshard.active

    def trigger_reshard(self, new_n_shards: int, wait: bool = True,
                        timeout: Optional[float] = None):
        """Resize the mesh live to `new_n_shards` (veneur_tpu/reshard/).
        With wait=True blocks until the transfer completed and returns
        its summary dict; with wait=False returns the live transfer
        handle (observe via .done / .summary()). Raises ReshardError
        when the feature is off, a move is already in progress, the
        target is invalid, or the transfer failed."""
        if self.reshard is None:
            from veneur_tpu.reshard import ReshardError
            raise ReshardError("resharding is disabled "
                               "(reshard_enabled: false)")
        return self.reshard.resize(new_n_shards, wait=wait,
                                   timeout_s=timeout)

    def trigger_table_grow(self, targets: dict, wait: bool = True,
                           timeout: Optional[float] = None):
        """Force a per-kind key-table capacity change at the next flush
        boundary (tables/growth.py executes it inside the swap quiesce
        — there is no other grow site, by lint). Raises GrowConflict
        (.status == 409) while a reshard owns the swap boundary:
        capacity changes serialize behind mesh moves, never interleave.
        With wait=True returns the flush result like trigger_flush."""
        from veneur_tpu.tables.growth import GrowConflict
        if self.tables is None:
            raise RuntimeError("table growth is disabled "
                               "(table_grow_enabled: false)")
        if self.reshard_active:
            raise GrowConflict("grow rejected: reshard in progress "
                               "owns the swap boundary (retry after)")
        self.tables.force(targets)
        return self.trigger_flush(wait=wait, timeout=timeout)

    def _checkpoint_interval(self, agg, flush_arrays, table, raw,
                             ts) -> None:
        """Assemble this interval's snapshot from the flush outputs and
        hand it to the async writer. `agg` owns the detached interval
        (its spec sizes the snapshot arrays — across a grow boundary
        that is the OLD spec, not self.aggregator's). Containment: a
        checkpoint that cannot be built degrades durability, never the
        flush."""
        ck_t0 = time.perf_counter_ns()
        try:
            from veneur_tpu.persistence import build_snapshot
            spill_bytes, spill_n = None, 0
            if self.forward_spill is not None:
                spill_bytes = self.forward_spill.to_bytes()
                spill_n = len(self.forward_spill)
            n_shards = getattr(agg, "n_shards", 1)
            snap = build_snapshot(
                agg.spec, table, flush_arrays, raw,
                agg_kind="sharded" if n_shards > 1 else "single",
                n_shards=n_shards, interval_ts=ts,
                hostname=self.hostname, spill=spill_bytes,
                spill_entries=spill_n,
                forward_meta=self._forward_meta_snapshot(),
                watches=self._watch_snapshot(),
                history=self._history_snapshot(),
                tenants=self._tenant_snapshot(),
                keytables=self._tables_snapshot())
            self._ckpt_writer.submit(snap)
        except Exception:
            log.exception("checkpoint snapshot build failed; interval "
                          "not checkpointed")
        self._t_flush_phase.observe(time.perf_counter_ns() - ck_t0,
                                    phase="checkpoint_build")

    def _watch_snapshot(self) -> Optional[dict]:
        """Watch registrations + firing state for the checkpoint's
        sidecar chunk. None (chunk omitted) when the tier is off or no
        watches are registered."""
        if self.watch_engine is None:
            return None
        return self.watch_engine.snapshot()

    def _tables_snapshot(self) -> Optional[dict]:
        """Key-table growth state (LIVE per-kind capacities + exact
        accounting) for the checkpoint's "keytables" sidecar chunk — a
        restore re-grows to these capacities BEFORE folding rows. None
        (chunk omitted) when growth is off."""
        if self.tables is None:
            return None
        return self.tables.snapshot_state(self.aggregator.spec)

    def _tenant_snapshot(self) -> Optional[dict]:
        """Tenant quarantine state (engine table mirror + exact
        demoted-row totals) for the checkpoint's sidecar chunk. None
        (chunk omitted) when tenancy is off."""
        if self.tenancy is None:
            return None
        return self.tenancy.snapshot_state()

    def _history_snapshot(self) -> Optional[dict]:
        """History ring (device arrays + host key index) for the
        checkpoint's sidecar chunks. None (chunks omitted) when the
        tier is off or the ring has not armed yet."""
        if self.history is None or not self.history.armed:
            return None
        return self.history.snapshot()

    def _forward_meta_snapshot(self) -> Optional[dict]:
        """Exactly-once forwarding state for the checkpoint: the sender
        identity (source_id + epoch + next seq) and/or this receiver's
        dedup window. None (chunk omitted) when the feature is off."""
        if self._fwd_source_id is None and self._dedup is None:
            return None
        meta: dict = {}
        if self._fwd_source_id is not None:
            with self._fwd_meta_lock:
                meta.update({"source_id": self._fwd_source_id,
                             "epoch": self._fwd_epoch,
                             "next_seq": self._fwd_next_seq})
        if self._dedup is not None:
            meta["dedup"] = self._dedup.snapshot()
        return meta

    def _restore_forward_meta(self, meta: dict) -> None:
        """Adopt a checkpoint's forwarding identity. The epoch BUMPS by
        one with seq reset: seqs minted after the checkpoint died with
        the process, and reusing them for NEW data would make the
        receiver suppress it as duplicates. Spill units restored
        alongside keep their ORIGINAL old-epoch envelopes — those are
        replays of already-possibly-folded payloads, exactly what the
        receiver's window for the old epoch knows how to suppress."""
        try:
            sid = str(meta.get("source_id") or "")
            if self._fwd_source_id is not None and sid:
                Envelope(sid, int(meta.get("epoch", 0)), 0).validate()
                with self._fwd_meta_lock:
                    self._fwd_source_id = sid
                    self._fwd_epoch = int(meta.get("epoch", 0)) + 1
                    self._fwd_next_seq = 0
                    self._fwd_acked_seq = -1
            if self._dedup is not None and meta.get("dedup"):
                self._dedup.restore(meta["dedup"])
        except (EnvelopeError, TypeError, ValueError) as e:
            log.warning("ignoring malformed forward metadata in "
                        "checkpoint: %s", e)

    def _restore_from_checkpoint(self) -> None:
        """Fold the newest valid snapshot into the live aggregator.
        Corrupt snapshots are quarantined and counted inside
        restore_latest; any other failure cold-starts — a bad checkpoint
        must never keep the server from serving."""
        from veneur_tpu.persistence import (fold_snapshot, restore_latest,
                                            restore_spill)
        try:
            found = restore_latest(self.cfg.checkpoint_dir,
                                   on_corrupt=self._c_ckpt_corrupt.inc)
            if found is None:
                log.info("no restorable checkpoint under %s; cold start",
                         self.cfg.checkpoint_dir)
                return
            snap, path = found
            if snap.get("keytables") and self.tables is not None:
                # re-grow to the checkpoint's per-kind capacities BEFORE
                # folding (startup: the pipeline is not running, so the
                # swap boundary is trivially quiescent). fold_snapshot
                # is capacity-independent either way — adopting first
                # just restores the headroom the process had.
                from veneur_tpu.tables import adopt_capacities
                kt = snap["keytables"]
                try:
                    adopt_capacities(self, dict(kt.get("capacities")
                                                or {}))
                    self.tables.restore_state(kt)
                except Exception:
                    log.exception("keytables sidecar not adopted; "
                                  "restoring at config capacities")
            fwd_meta = snap.get("forward") or None
            # skip re-folding forward-ONLY rows iff their payloads travel
            # via the spill replay instead: the snapshot was written by
            # an exactly-once sender (it staged the export BEFORE the
            # checkpoint, so the spill chunk holds those rows under their
            # envelopes) and this server will replay that spill. Folding
            # them too would re-export the same data under a fresh seq
            # the receiver cannot correlate — a guaranteed double-count.
            skip_fwd = (fwd_meta is not None
                        and fwd_meta.get("source_id")
                        and self._fwd_source_id is not None
                        and self.forward_spill is not None)
            n = fold_snapshot(self.aggregator, snap,
                              skip_forwarded=bool(skip_fwd))
            if self.forward_spill is not None and snap.get("spill"):
                restore_spill(self.forward_spill, snap["spill"])
            if fwd_meta:
                self._restore_forward_meta(fwd_meta)
            if snap.get("watches") and self.watch_engine is not None:
                # registrations + firing state: monitors keep their
                # debounce streaks and ALERT holds across the restart
                self.watch_engine.restore(snap["watches"])
            if snap.get("history") and self.history is not None:
                # windowed lookback survives the restart byte-exact;
                # a spec mismatch keeps the fresh ring (history is a
                # cache of flushed intervals, never source of truth)
                self.history.restore(snap["history"])
            if snap.get("tenants") and self.tenancy is not None:
                # quarantine state survives the restart: the entries are
                # stashed here and pushed into the engine right after
                # tenant_config creates its table (rings start later in
                # start(), so demotion resumes from the first datagram)
                self._tenant_restore_entries = \
                    self.tenancy.restore_state(snap["tenants"])
            self._c_ckpt_restores.inc()
            log.info("restored %d metrics from %s (interval_ts=%d)",
                     n, path, snap["interval_ts"])
        except Exception:
            log.exception("checkpoint restore failed; cold start")

    def _flush_worker(self):
        """Dedicated flush thread: drains detached intervals and runs the
        full flush fan-out. Serializes overlapping flushes; a slow sink
        delays at most the NEXT flush, never ingest."""
        while True:
            job = self._flush_jobs.get()
            if job is _STOP:
                return
            agg, state, table, stats, swapped_at, req = job
            ok, detail = True, ""
            try:
                self._do_flush(agg, state, table, stats, swapped_at)
            except Exception as e:
                # a failed flush must never kill the flush thread; state
                # was already swapped, next interval starts clean
                ok, detail = False, f"{type(e).__name__}: {e}"
                log.exception("flush failed")
            finally:
                self.last_flush_done = time.time()
                self._c_flush_count.inc()
                req.finish(ok, detail)

    def _do_flush(self, agg, state, table, stats, swapped_at):
        # `agg` is the backend that OWNED the detached interval — it is
        # self.aggregator except for the interval detached by a table
        # grow swap, whose flush math must run at the old spec
        # chaos hook: a fault here exercises the failed-flush containment
        # in _flush_worker (state already swapped; next interval clean)
        FAULTS.inject(FLUSH_WORKER)
        flush_t0 = time.perf_counter()
        # tenant ledger/mirror sync when the overload poller isn't
        # already folding it each tick (tenancy without the controller,
        # or native admission push-down disabled)
        if self.tenancy is not None and not (
                self._overload is not None
                and self.cfg.overload_native_admission):
            self._sync_native_tenancy(drain=True)
        # stamp with the interval's swap time, not the job's run time — a
        # queued interval must not shift into the next time bucket
        ts = int(swapped_at)
        # every flush stage is wrapped in a self-span reported through the
        # channel trace client, so the span tree re-enters our own span
        # pipeline and is visible to span sinks (flusher.go:29
        # tracer.StartSpan("flush") + StartSpanFromContext per stage)
        from veneur_tpu.trace.tracer import Span
        root = Span("flush", service="veneur")
        trace = self._flush_trace
        swap_ns = int(stats.get("swap_ns", 0))
        # h2d bytes shipped THIS interval (the aggregator counter is
        # lifetime-cumulative; the flush worker is the only reader of
        # _h2d_reported, so the delta needs no lock)
        h2d_total = int(stats.get("h2d_bytes", 0))
        h2d_delta = max(0, h2d_total - self._h2d_reported)
        self._h2d_reported = h2d_total
        if trace:
            # the swap already ran on the pipeline thread before this job
            # was queued; backdate the root by its duration and replay it
            # as the first child so the trace covers the whole interval
            root.start_ns -= swap_ns
            drain = root.child("flush.ingest_drain", start_ns=root.start_ns)
            drain.set_tag("h2d_bytes", str(h2d_delta))
            if self.trace_client is not None:
                self.trace_client.record(
                    drain.finish(root.start_ns + swap_ns))

        def stage(name):
            return root.child(f"flush.{name}")

        dev_t0 = time.perf_counter_ns()
        sp = stage("device_update")
        raw = None
        # a due checkpoint rides the forward path's raw sketch outputs —
        # same want_raw host transfer, zero checkpoint-only device reads
        ckpt_due = (self._ckpt_writer is not None
                    and self._flushes_since_ckpt + 1
                    >= max(1, self.cfg.checkpoint_interval_flushes))
        if (self._forward_client is not None or ckpt_due
                or self.cfg.collective_attach):
            flush_arrays, table, raw = agg.compute_flush(
                state, table, self.cfg.percentiles, want_raw=True,
                history=self.history)
        else:
            flush_arrays, table = agg.compute_flush(
                state, table, self.cfg.percentiles, history=self.history)
        if self.tables is not None:
            try:
                # idle census over the detached (immutable) table: exact
                # evicted_total + the shrink demand signal
                self.tables.census_flush(table, swapped_at)
            except Exception:
                log.exception("table census failed; eviction accounting "
                              "skipped this interval")
        self._t_flush_phase.observe(time.perf_counter_ns() - dev_t0,
                                    phase="device_update")
        if trace:
            sp.set_tag("h2d_bytes", str(h2d_delta))
        sp.client_finish(self.trace_client)
        # streaming watch tier: hand the DETACHED interval to the watch
        # engine's own thread. compute_flush does not donate its state
        # input, so the reference stays valid for that thread's fused
        # evaluation; offer() is non-blocking (bounded queue,
        # drop-oldest with exact accounting), so watches can never
        # stretch the flush deadline. At overload CRITICAL the
        # evaluation is shed outright — counted, never silent.
        if self.watch_engine is not None:
            watch_shed = False
            if self._overload is not None:
                from veneur_tpu.reliability.overload import CRITICAL
                watch_shed = self._overload.state >= CRITICAL
            if watch_shed:
                self.watch_engine.skip_interval("overload CRITICAL")
            else:
                # pin THIS interval's ring window seq now — a later
                # flush advances the ring before the engine thread runs
                hist_seq = (self.history.seq - 1
                            if self.history is not None
                            and self.history.armed else None)
                self.watch_engine.offer(
                    state, table, int(stats.get("set_shift", 0)), ts,
                    hist_seq)
        # exactly-once forwarding: export + stage this interval's unit
        # under a fresh (epoch, seq) BEFORE the checkpoint build, so the
        # snapshot's spill chunk carries the payload with its envelope
        # (_stage_forward_unit explains the crash-replay invariant)
        #
        # co-located collective tier: hand this interval's forwardable
        # rows to the in-process tier as device staging (zero
        # serialization). A successful absorb IS the forward — the wire
        # path (stage + gRPC/HTTP) is skipped for the interval; any
        # failure falls through to it untouched.
        absorbed = False
        if self.cfg.collective_attach and raw is not None:
            # the co-located absorb IS this interval's forward, so it
            # gets the same flush.forward stage span the wire path
            # would; the tier parents its absorb span onto it and the
            # span tree stays connected across tiers without a wire hop
            asp = stage("forward")
            asp.set_tag("transport", "colocated")
            try:
                absorbed = self._absorb_colocated(raw, table, span=asp)
            finally:
                asp.client_finish(self.trace_client)
        if (self._fwd_source_id is not None and raw is not None
                and not absorbed):
            self._stage_forward_unit(raw, table)
        if self._ckpt_writer is not None:
            if ckpt_due:
                # capture the spill BEFORE the forward drains it: a crash
                # between here and a successful send replays those
                # payloads. The replay is NOT uniformly idempotent at the
                # receiving tier — HLL register folds and LWW gauges
                # absorb duplicates, but counter accumulators and
                # t-digest centroid weights are ADDITIVE and double-count
                # — so with forward_dedup_window > 0 the staged unit
                # replays under its original (source_id, epoch, seq) and
                # the receiver's dedup window suppresses the re-fold;
                # without a window the replay is at-least-once for the
                # additive kinds (forward/envelope.py).
                self._checkpoint_interval(agg, flush_arrays, table, raw, ts)
                self._flushes_since_ckpt = 0
            else:
                self._flushes_since_ckpt += 1
        if self._forward_client is not None and not absorbed:
            # fire-and-forget, concurrent with sink flushes
            # (flusher.go:84-95); _forward logs and counts its own errors,
            # and the flush thread must never block on a slow global tier
            fsp = stage("forward")
            if self._fwd_source_id is not None:
                # ack-gated mode: the interval was staged above; the pump
                # replays every pending unit under its original envelope
                self._spawn_aux(self._pump_traced, fsp)
            else:
                self._spawn_aux(self._forward_traced, fsp, raw, table)

        if self.cfg.count_unique_timeseries:
            from veneur_tpu.server.flusher import unique_timeseries
            self._unique_ts = unique_timeseries(table, self.cfg.is_local)

        # span sinks flush concurrently (flusher.go:56 go flushTraces)
        self._spawn_aux(self.span_pipeline.flush)

        with self._event_lock:
            samples, self.event_samples = self.event_samples, []
        for sink in self.metric_sinks:
            try:
                sink.flush_other_samples(samples)
            except Exception as e:
                log.warning("sink %s FlushOtherSamples: %s", sink.name, e)

        # columnar fast path: when every sink takes frames and no plugin
        # needs object lists, skip per-metric InterMetric construction
        # entirely (~20s of host time per interval at the 10M-key north
        # star; see flusher.MetricFrame)
        if (self.metric_sinks
                and all(getattr(s, "accepts_frames", False)
                        for s in self.metric_sinks)
                and all(getattr(p, "accepts_frames", False)
                        for p in self.plugins)):
            from veneur_tpu.server.flusher import generate_frame
            generate = generate_frame
        else:
            generate = generate_intermetrics
        # degraded-aggregation correction: the detached interval staged
        # set members subsampled at 2^-shift (Aggregator._set_admit), so
        # multiply the FLUSH estimate back by 2^shift. Forward and
        # checkpoint carry raw HLL registers and are untouched; a new
        # dict + new array because the checkpoint snapshot may still
        # reference the originals.
        flush_degraded = False
        set_shift = int(stats.get("set_shift", 0))
        if set_shift > 0 and flush_arrays.get("set_estimate") is not None:
            flush_arrays = dict(flush_arrays)
            flush_arrays["set_estimate"] = (
                flush_arrays["set_estimate"] * (1 << set_shift))
            flush_degraded = True
        fb_t0 = time.perf_counter_ns()
        fbsp = stage("frame_build") if trace else None
        final = generate(
            flush_arrays, table,
            percentiles=self.cfg.percentiles,
            aggregates=self.cfg.aggregates,
            is_local=self.cfg.is_local,
            timestamp=ts, hostname=self.hostname)
        self._t_flush_phase.observe(time.perf_counter_ns() - fb_t0,
                                    phase="frame_build")
        if fbsp is not None:
            fbsp.set_tag("rows", str(len(final)))
            fbsp.client_finish(self.trace_client)
        # flush protection: at CRITICAL, withhold low-priority rows from
        # sink fan-out (and plugins) — the device update, forward, and
        # checkpoint above already ran unconditionally, so no aggregated
        # data is lost, only its low-priority publication this interval
        if self._overload is not None and final:
            from veneur_tpu.reliability.overload import CRITICAL
            if self._overload.state >= CRITICAL:
                final, n_shed = self._flush_protect(final)
                if n_shed:
                    self._overload.count_flush_shed(n_shed)
                    flush_degraded = True
        if flush_degraded and self._overload is not None:
            self._overload.note_degraded_flush()
        if final:
            # parallel sink flushes + barrier with a per-interval join
            # budget (flusher.go:105-115). Slow-sink containment:
            # - a sink whose PREVIOUS flush is still running gets this
            #   interval skipped (counted) instead of a second thread —
            #   a wedged sink must not accrete a thread + metrics list
            #   per interval
            # - a thread that outlives the join budget is handed to the
            #   aux set so shutdown still joins it (abandoning a thread
            #   inside gRPC/JAX at teardown aborts the process); daemon
            #   so a truly wedged one cannot block interpreter exit
            fan_t0 = time.perf_counter_ns()
            sinks_span = stage("sinks")
            sinks_span.set_tag("metrics", str(len(final)))
            threads = []
            for s in self.metric_sinks:
                # keyed by instance, not .name — names are class-level
                # constants and two same-named sinks must not share a
                # containment slot (instances live as long as the server,
                # so id() is stable)
                prev = self._sink_threads.get(id(s))
                if prev is not None and prev.is_alive():
                    self._c_sink_skips.inc()
                    log.warning("sink %s: previous flush still running; "
                                "skipping this interval", s.name)
                    continue
                t = threading.Thread(target=self._flush_sink,
                                     args=(s, final, sinks_span),
                                     daemon=True)
                self._sink_threads[id(s)] = t
                threads.append(t)
            for t in threads:
                t.start()
            # ONE shared interval budget for the whole barrier (a
            # per-thread timeout would give N slow sinks N intervals and
            # stale the watchdog's last_flush_done for merely-slow sinks)
            barrier_deadline = time.monotonic() + self.interval
            for t in threads:
                t.join(timeout=max(0.0,
                                   barrier_deadline - time.monotonic()))
                if t.is_alive():
                    with self._aux_lock:
                        self._aux_threads = [
                            x for x in self._aux_threads if x.is_alive()]
                        self._aux_threads.append(t)
            self._t_flush_phase.observe(time.perf_counter_ns() - fan_t0,
                                        phase="sink_fanout")
            sinks_span.client_finish(self.trace_client)
            # plugins run post-flush (flusher.go:117-131)
            psp = stage("plugins") if self.plugins else None
            from veneur_tpu.server.flusher import MetricFrame
            is_frame = isinstance(final, MetricFrame)
            for p in self.plugins:
                try:
                    if is_frame:
                        p.flush_frame(final)
                    else:
                        p.flush(final)
                except Exception as e:
                    psp.error = True
                    log.warning("plugin %s flush failed: %s", p.name, e)
            if psp is not None:
                psp.client_finish(self.trace_client)
        # Self-telemetry is reported even for an empty interval — the
        # reference always tallies flush totals (flusher.go:300-336), and an
        # idle server must still bootstrap veneur.flush.* / packet counters
        # into its own pipeline.
        # per-interval native-ring poll (emit latency delta average)
        self._poll_ring_telemetry()
        self._report_self_metrics(len(final), time.perf_counter() - flush_t0,
                                  stats, final=final)
        # total = downstream work + the pipeline-thread swap it rode in on
        self._t_flush_phase.observe(
            (time.perf_counter() - flush_t0) * 1e9 + swap_ns, phase="total")
        if trace:
            root.set_tag("rows", str(len(final)))
            root.set_tag("h2d_bytes", str(h2d_delta))
        root.client_finish(self.trace_client)

    def _flush_protect(self, final):
        """Filter low-priority rows out of a flush result (MetricFrame or
        InterMetric list). Keeps self-metrics and any row carrying a
        `shed_priority_tags` match; returns (filtered, n_dropped)."""
        high = tuple(self.cfg.shed_priority_tags)

        def keep(name, tags):
            if name.startswith("veneur."):
                return True
            for h in high:
                for t in tags:
                    if h in t:
                        return True
            return False

        from veneur_tpu.server.flusher import FrameSegment, MetricFrame
        if isinstance(final, MetricFrame):
            segs, dropped = [], 0
            for seg in final.segments:
                keep_idx = [i for i, m in enumerate(seg.metas)
                            if keep(seg.names[i], m.tags)]
                dropped += len(seg.names) - len(keep_idx)
                if not keep_idx:
                    continue
                if len(keep_idx) == len(seg.names):
                    segs.append(seg)
                    continue
                segs.append(FrameSegment(
                    [seg.names[i] for i in keep_idx],
                    seg.values[keep_idx], seg.mtype,
                    [seg.metas[i] for i in keep_idx], seg.is_status))
            return MetricFrame(final.timestamp, final.hostname,
                               segs), dropped
        kept = [m for m in final if keep(m.name, m.tags)]
        return kept, len(final) - len(kept)

    def _forward_traced(self, span, raw, table):
        try:
            self._forward(raw, table, span=span)
        finally:
            span.client_finish(self.trace_client)

    # -- exactly-once forwarding (forward/envelope.py; README
    # §Exactly-once forwarding) --------------------------------------------
    def _next_envelope(self) -> Envelope:
        with self._fwd_meta_lock:
            seq = self._fwd_next_seq
            self._fwd_next_seq += 1
            return Envelope(self._fwd_source_id, self._fwd_epoch, seq)

    def _stage_forward_unit(self, raw, table) -> None:
        """Export this interval's forwardable sketches and stage them as
        an immutable ack-gated unit under a fresh (epoch, seq), on the
        flush worker thread BEFORE the checkpoint build and the send.

        That ordering is the crash-exactly-once invariant: every
        checkpoint's forward-eligible rows are inside its spill chunk
        WITH their envelope, so a crash-restore replays the same bytes
        under the same seq (which the receiver's dedup window can
        suppress) while fold_snapshot(skip_forwarded=True) keeps those
        rows from re-exporting under a fresh seq it couldn't.

        Legacy (unenveloped) spill entries — restored from a pre-upgrade
        checkpoint — fold into this unit so they too travel enveloped."""
        from veneur_tpu.forward.convert import export_metrics
        try:
            fresh = export_metrics(
                raw, table, compression=self.aggregator.spec.compression,
                hll_precision=self.aggregator.spec.hll_precision)
            legacy = [m for _, m in self.forward_spill.take_legacy()]
            if legacy:
                log.info("forward: folding %d legacy spilled payloads "
                         "into this interval's unit", len(legacy))
                fresh = legacy + fresh
            if fresh:
                env = self._next_envelope()
                self.forward_spill.add_unit(fresh, env.epoch, env.seq)
        except Exception:
            # containment: a failed export degrades forwarding for this
            # interval, never the flush (errors surface at the pump)
            self._c_forward_errors.inc()
            log.exception("forward export/staging failed; interval not "
                          "staged")

    def _absorb_colocated(self, raw, table, span=None) -> bool:
        """Hand this interval's forwardable rows to the co-located
        collective tier (collective/tier.py) as device staging. True
        means the tier took the interval and the wire path must not run
        (staging it too would double-count the additive kinds); False
        means no tier / failed absorb, and the caller falls back to the
        ordinary forward path untouched. `span` is the local flush's
        forward stage span — the tier's absorb span parents onto it."""
        from veneur_tpu.collective import tier as collective_tier
        t = collective_tier.lookup(self.cfg.collective_attach)
        if t is None:
            # no co-located tier in this process (yet) — DCN fallback
            return False
        # inject the registry-backed phase timer (idempotent; last
        # writer wins and every local attaches the same server's timer)
        t.set_phase_timer(self._t_coll_phase)
        try:
            if self._collective_participant is None:
                self._collective_participant = t.assign_participant()
            n = t.absorb_raw(raw, table,
                             participant=self._collective_participant,
                             parent_span=span,
                             trace_client=self.trace_client)
        except Exception:
            self._c_coll_errors.inc()
            log.exception("co-located collective absorb failed; interval "
                          "falls back to the wire forward path")
            return False
        self._c_coll_rows.inc(n)
        return True

    def _pump_traced(self, span):
        try:
            self._pump_forward_units(span=span)
        finally:
            span.client_finish(self.trace_client)

    def _pump_forward_units(self, span=None) -> None:
        """Send every staged unit oldest-first; a successful send IS the
        receiver's ack for that seq (the RPC/202 returns only after the
        import was admitted — or recognized as a duplicate, which is
        acked too), so the unit is evicted. A failed or AMBIGUOUS send
        leaves the unit in place untouched: the next interval's pump
        re-sends the SAME bytes under the SAME seq.

        Single-flight (non-blocking lock): a slow failing pump may
        overlap the next interval's; a second concurrent pump would
        re-send units already in flight — harmless to the receiver
        (dedup) but a bandwidth and breaker-accounting mess."""
        if not self._fwd_send_lock.acquire(blocking=False):
            return
        t0 = time.perf_counter_ns()
        n_metrics = 0
        try:
            if (self._forward_breaker is not None
                    and not self._forward_breaker.allow()):
                raise CircuitOpenError("forward: circuit open")
            for unit in self.forward_spill.pending_units():
                # trace context rides the envelope so the receiving
                # tier's absorb span parents onto THIS flush's forward
                # span; untraced (span=None) stays wire-identical to a
                # legacy sender
                env = Envelope(self._fwd_source_id, unit.epoch, unit.seq,
                               trace_id=(span.trace_id
                                         if span is not None else None),
                               parent_span_id=(span.id
                                              if span is not None
                                              else None))
                n_metrics += len(unit.metrics)
                self._send_forward(unit.metrics, span, envelope=env)
                self.forward_spill.ack(unit.epoch, unit.seq)
                with self._fwd_meta_lock:
                    if (unit.epoch == self._fwd_epoch
                            and unit.seq > self._fwd_acked_seq):
                        self._fwd_acked_seq = unit.seq
                if self._forward_breaker is not None:
                    self._forward_breaker.record_success()
                self._c_forward_sends.inc()
        except Exception as e:
            if (self._forward_breaker is not None
                    and not isinstance(e, CircuitOpenError)):
                self._forward_breaker.record_failure()
            # NO spill mutation here: the unsent units (including the
            # one that just failed) are still staged under their seqs —
            # re-sending the same envelope is the whole point
            self._c_forward_errors.inc()
            if span is not None:
                span.error = True
            log.warning("forward failed: %s", e)
        finally:
            dur_ns = time.perf_counter_ns() - t0
            self._t_flush_phase.observe(dur_ns, phase="forward")
            if span is not None and self._flush_trace:
                span.set_tag("rows", str(n_metrics))
            with self._sink_stats_lock:
                self._forward_stats.append((dur_ns, n_metrics))
            self._fwd_send_lock.release()

    def _report_self_metrics(self, n_flushed: int, flush_seconds: float,
                             stats: dict, final=None):
        """Every stage emits self-metrics through the pipeline itself
        (SURVEY §5: worker counts worker.go:513, flush totals
        flusher.go:300-336), as deltas per interval. `stats` is the counter
        snapshot taken on the pipeline thread at swap time."""
        from veneur_tpu.samplers import ssf_samples
        from veneur_tpu.trace.client import report_batch

        cur = {"veneur.packets_received_total": stats["packets_received"],
               "veneur.packets_dropped_total":
                   stats.get("packets_dropped", 0),
               "veneur.packet.error_toolong_total":
                   stats.get("packets_toolong", 0),
               "veneur.parse_errors_total": stats["parse_errors"],
               "veneur.worker.metrics_processed_total": stats["processed"],
               "veneur.worker.metrics_dropped_total": stats["dropped"],
               "veneur.import.errors_total": stats["import_errors"],
               "veneur.pipeline.internal_errors_total":
                   stats.get("internal_errors", 0),
               "veneur.import.metrics_total": stats.get("imported_total", 0),
               # the reference emits BOTH: import.metrics_total from the
               # import server (importsrv/server.go:129) and the worker-
               # level alias operators alert on (worker.go:514)
               "veneur.worker.metrics_imported_total":
                   stats.get("imported_total", 0),
               # the reference tags forward.error_total with a cause
               # (deadline_exceeded/post, flusher.go:512-524); the delta
               # counter here is untagged — the log line carries the why
               "veneur.forward.error_total":
                   stats.get("forward_errors", 0),
               "veneur.flush.intervals_deferred_total":
                   stats["intervals_deferred"],
               "veneur.flush.sink_flushes_skipped_total":
                   stats.get("sink_flushes_skipped", 0),
               # the short alias the fault-tolerance docs use; same
               # counter (slow-sink containment + breaker refusals)
               "veneur.flush.skipped_total":
                   stats.get("sink_flushes_skipped", 0),
               "veneur.spans_received_total": stats["spans_received"],
               "veneur.worker.span.hit_chan_cap":
                   stats.get("span_chan_cap_hits", 0)}
        # per-flush runtime gauges (flusher.go:36-43: span-chan depth,
        # GC count, heap bytes, flush timestamp)
        from veneur_tpu.utils.statsd_emit import runtime_gauges
        rss, ngc = runtime_gauges()
        samples = [ssf_samples.timing("veneur.flush.total_duration_ns",
                                      flush_seconds),
                   ssf_samples.gauge("veneur.flush.metrics_total",
                                     n_flushed),
                   ssf_samples.gauge(
                       "veneur.worker.span_chan.total_elements",
                       float(self.span_pipeline.chan.qsize())),
                   ssf_samples.gauge(
                       "veneur.worker.span_chan.total_capacity",
                       float(self.span_pipeline.chan.maxsize)),
                   ssf_samples.gauge("veneur.gc.number", ngc),
                   ssf_samples.gauge("veneur.mem.heap_alloc_bytes", rss),
                   ssf_samples.gauge("veneur.flush.flush_timestamp_ns",
                                     float(time.time() * 1e9)),
                   # 0 = pure-Python parse fallback (the .so failed to
                   # build): ~40x slower per thread than the C++ engine.
                   # A silent log-line was the only signal before; now
                   # operators can alert on the gauge.
                   ssf_samples.gauge("veneur.parse.native_engine",
                                     1.0 if self._native else 0.0)]
        if self._unique_ts is not None:
            samples.append(ssf_samples.count(
                "veneur.flush.unique_timeseries_total", self._unique_ts,
                {"global_veneur": str(not self.cfg.is_local).lower()}))
            self._unique_ts = None
        # README §Monitoring names operators alert on:
        # worker.metrics_flushed_total by metric_type (unique name-tag-
        # type combos this interval), forward.duration_ns +
        # forward.post_metrics_total per POST, flush.error_total for
        # sink POST errors
        if final is not None and len(final):
            from collections import Counter

            from veneur_tpu.server.flusher import MetricFrame
            if isinstance(final, MetricFrame):
                by_type = Counter()
                for seg in final.segments:
                    by_type[seg.mtype] += len(seg.names)
            else:
                by_type = Counter(m.type for m in final)
            for mtype, n in sorted(by_type.items()):
                samples.append(ssf_samples.count(
                    "veneur.worker.metrics_flushed_total", n,
                    {"metric_type": mtype}))
        # per-(service, ssf_format) span intake (flusher.go:463-466):
        # ssf.spans.received_total + the root-span variant, which carries
        # veneurglobalonly so infrastructure-wide root counts aggregate
        # on the global tier exactly like the reference's
        for (service, fmt), (n, n_root) in sorted(
                self.span_pipeline.drain_service_counts().items()):
            tags = {"service": service, "ssf_format": fmt}
            samples.append(ssf_samples.count(
                "veneur.ssf.spans.received_total", n, tags))
            if n_root:
                samples.append(ssf_samples.count(
                    "veneur.ssf.spans.root.received_total", n_root,
                    dict(tags, veneurglobalonly="true")))
        with self._sink_stats_lock:
            fstats, self._forward_stats = self._forward_stats, []
        for dur_ns, n_metrics in fstats:
            samples.append(ssf_samples.timing(
                "veneur.forward.duration_ns", dur_ns / 1e9))
            samples.append(ssf_samples.count(
                "veneur.forward.post_metrics_total", n_metrics))
        # per-metric-sink conventions, measured centrally by the fan-out
        # (sinks/sinks.go:11-24; the previous interval's threads that
        # outlived the barrier settle into the NEXT interval's report)
        with self._sink_stats_lock:
            sink_stats, self._sink_flush_stats = self._sink_flush_stats, {}
            # swap-and-reset like _sink_flush_stats: stragglers from an
            # abandoned sink thread land in the next interval's dict
            sink_errs, self._sink_flush_errors = (
                self._sink_flush_errors, {})
        for sname, n in sink_errs.items():
            samples.append(ssf_samples.count(
                "veneur.flush.error_total", n, {"sink": sname}))
        for name, (rows, total_ns) in sink_stats.items():
            tags = {"sink": name}
            if rows:
                samples.append(ssf_samples.count(
                    "veneur.sink.metrics_flushed_total", rows, tags))
            samples.append(ssf_samples.timing(
                "veneur.sink.metric_flush_total_duration_ns", total_ns / 1e9,
                tags))
        # resilience telemetry, read from the SAME registry collectors a
        # /metrics scrape uses (one source of truth): retry counts as
        # deltas vs _last_stats so an idle configuration emits nothing,
        # breaker state + spill occupancy as point-in-time gauges
        for lv, total in self.metrics.get(
                "veneur.sink.retries_total").samples():
            name = lv[0] if lv else ""
            key = f"veneur.sink.retries_total|{name}"
            delta = total - self._last_stats.get(key, 0)
            self._last_stats[key] = total
            if delta:
                samples.append(ssf_samples.count(
                    "veneur.sink.retries_total", delta, {"sink": name}))
        for lv, v in self.metrics.get("veneur.circuit.state").samples():
            samples.append(ssf_samples.gauge(
                "veneur.circuit.state", float(v),
                {"sink": lv[0] if lv else ""}))
        for _lv, v in self.metrics.get(
                "veneur.forward.spill_bytes").samples():
            samples.append(ssf_samples.gauge(
                "veneur.forward.spill_bytes", float(v)))
        for mname in ("veneur.forward.spill.spilled_total",
                      "veneur.forward.spill.dropped_total"):
            for _lv, total in self.metrics.get(mname).samples():
                cur[mname] = total
        for name, total in cur.items():
            delta = total - self._last_stats.get(name, 0)
            self._last_stats[name] = total
            if delta:
                samples.append(ssf_samples.count(name, delta))
        self._normalize_self_samples(samples)
        report_batch(self.trace_client, samples)
        self._emit_stats_address(samples)

    def _report_span_worker_samples(self, samples) -> None:
        """Span-worker per-sink telemetry (worker.go:706-713), reported
        through the same normalize → pipeline → stats-mirror path as the
        flush self-metrics. Called from the flush worker's span-flush
        thread; everything downstream is thread-safe (channel client,
        UDP sendto)."""
        from veneur_tpu.trace.client import report_batch
        self._normalize_self_samples(samples)
        report_batch(self.trace_client, samples)
        self._emit_stats_address(samples)

    def _emit_stats_address(self, samples) -> None:
        """Mirror self-metrics to an external statsd daemon when
        stats_address is configured (reference server.go:297 statsd.New +
        scopedstatsd — operators often point this at a plain DogStatsD
        agent, separate from the in-pipeline loop-back)."""
        if self._stats_sock is None:   # unconfigured, bad address, or
            return                     # already closed by shutdown
        from veneur_tpu.proto import ssf_pb2
        from veneur_tpu.utils.statsd_emit import format_line, send_lines
        type_ch = {ssf_pb2.SSFSample.COUNTER: "c",
                   ssf_pb2.SSFSample.GAUGE: "g",
                   ssf_pb2.SSFSample.HISTOGRAM: "h"}
        try:
            lines = []
            for s in samples:
                ch = type_ch.get(s.metric)
                if ch is None:
                    continue
                tags = ",".join(f"{k}:{v}" if v else k
                                for k, v in sorted(s.tags.items()))
                lines.append(format_line(s.name, s.value, ch, tags))
            send_lines(self._stats_sock, self._stats_dest, lines)
        except (OSError, ValueError) as e:
            log.warning("stats_address emit failed: %s", e)

    def _normalize_self_samples(self, samples):
        """veneur_metrics_scopes / veneur_metrics_additional_tags applied
        to self-telemetry (reference scopedstatsd/client.go:33-58 +
        normalizeSpans server.go:179-238)."""
        from veneur_tpu.proto import ssf_pb2
        scopes = self.cfg.veneur_metrics_scopes or {}
        scope_by_type = {
            ssf_pb2.SSFSample.COUNTER: scopes.get("counter"),
            ssf_pb2.SSFSample.GAUGE: scopes.get("gauge"),
            ssf_pb2.SSFSample.HISTOGRAM: scopes.get("histogram"),
            ssf_pb2.SSFSample.SET: scopes.get("set"),
            ssf_pb2.SSFSample.STATUS: scopes.get("status"),
        }
        extra = [t.split(":", 1) if ":" in t else (t, "")
                 for t in self.cfg.veneur_metrics_additional_tags]
        for s in samples:
            want = scope_by_type.get(s.metric)
            if want == "local":
                s.scope = ssf_pb2.SSFSample.LOCAL
            elif want == "global":
                s.scope = ssf_pb2.SSFSample.GLOBAL
            for k, v in extra:
                s.tags[k] = v

    def _forward(self, raw, table, span=None):
        """Serialize and ship forwardable sketch state
        (flusher.go:474 forwardGRPC). Errors are counted, never fatal
        (flusher.go:512-524). `span` is the flush.forward stage span,
        propagated to the peer over HTTP so its /import spans join this
        flush's trace."""
        from veneur_tpu.forward.convert import export_metrics
        t0 = time.perf_counter_ns()
        n_metrics = 0
        fresh = []
        spilled = []
        try:
            fresh = export_metrics(
                raw, table, compression=self.aggregator.spec.compression,
                hll_precision=self.aggregator.spec.hll_precision)
            n_metrics = len(fresh)
            if fresh or (self.forward_spill is not None
                         and len(self.forward_spill)):
                # breaker gate BEFORE the spill drain: while the circuit
                # is open, buffered payloads stay put (no per-interval
                # drain/re-spill churn) and only this interval's fresh
                # batch joins them in the except arm below
                if (self._forward_breaker is not None
                        and not self._forward_breaker.allow()):
                    raise CircuitOpenError("forward: circuit open")
                if self.forward_spill is not None:
                    # (spilled_at, metric) pairs from failed intervals
                    # ride ahead of this interval's batch; the global
                    # tier merges by key, so the combined import equals
                    # what a never-failed run built
                    spilled = self.forward_spill.drain()
                    if spilled:
                        log.info("forward: merging %d spilled payloads "
                                 "into this batch", len(spilled))
                metrics = [m for _, m in spilled] + fresh
                n_metrics = len(metrics)
                if metrics:
                    self._send_forward(metrics, span)
                    if self._forward_breaker is not None:
                        self._forward_breaker.record_success()
                    self._c_forward_sends.inc()
        except Exception as e:
            if (self._forward_breaker is not None
                    and not isinstance(e, CircuitOpenError)):
                self._forward_breaker.record_failure()
            if self.forward_spill is not None:
                # keep the sketches for the next attempt instead of
                # dropping them; re-failed spilled entries keep their
                # ORIGINAL timestamps (readd first — they are oldest)
                # so max_age_s bounds total staleness
                self.forward_spill.readd(spilled)
                self.forward_spill.add(fresh)
            # concurrent forwards (one aux thread per interval; a slow
            # failure can overlap the next interval's) would make += lossy
            # — the registry counter is atomic under its own lock
            self._c_forward_errors.inc()
            if span is not None:
                span.error = True
            log.warning("forward failed: %s", e)
        finally:
            # README §Monitoring: veneur.forward.duration_ns +
            # forward.post_metrics_total, drained by the next interval's
            # self-telemetry report. Recorded on FAILURE too — the
            # duration alert exists precisely for degraded forwards, and
            # a timed-out POST must show as a latency spike, not as an
            # absent metric.
            dur_ns = time.perf_counter_ns() - t0
            self._t_flush_phase.observe(dur_ns, phase="forward")
            if span is not None and self._flush_trace:
                span.set_tag("rows", str(n_metrics))
            with self._sink_stats_lock:
                self._forward_stats.append((dur_ns, n_metrics))

    def _send_forward(self, metrics, span, envelope=None) -> None:
        """One forward send under the retry policy. The HTTP client
        carries the policy itself (each attempt re-runs the whole
        traced_post pipeline), so only wrap clients without one — a
        double wrap would square the attempt count.

        The envelope kwarg is passed through only when set, so embedder
        fakes with the legacy send_metrics signature keep working.
        Every retry attempt re-sends the SAME envelope — an ambiguous
        failure (DEADLINE_EXCEEDED/CANCELLED, rpc.AmbiguousResultError)
        may have folded at the receiver, and only a same-seq re-send
        lets the dedup window suppress the duplicate."""
        kw = {}
        if envelope is not None:
            kw["envelope"] = envelope

        def once():
            self._forward_client.send_metrics(
                metrics, timeout=self.interval, parent_span=span,
                trace_client=self.trace_client, **kw)

        if (self.retry_policy is None
                or getattr(self._forward_client, "retry_policy", None)
                is not None):
            once()
            return

        def on_retry(attempt, exc, delay):
            self._c_forward_retries.inc()
            log.warning("forward attempt %d failed: %s; retrying in "
                        "%.3fs", attempt + 1, exc, delay)

        self.retry_policy.run(once, on_retry=on_retry)

    def _flush_sink(self, sink, metrics, parent=None):
        """metrics is a List[InterMetric] or a flusher.MetricFrame —
        frames only reach sinks that declared accepts_frames.

        Resilience split: a sink with its OWN configured harness
        (ResilientSink) retries and records breaker outcomes per network
        call internally, so the fan-out must neither gate on the shared
        breaker (it would consume the half-open probe the sink's own
        allow() then misses) nor wrap the flush in a second retry loop
        (attempts would multiply). Plain sinks get whole-flush retry and
        breaker accounting here."""
        # ResilientSink KafkaSpanSink etc. live in span_sinks; only
        # metric sinks reach this fan-out, but check the type anyway
        own = (isinstance(sink, ResilientSink)
               and sink.resilience_configured)
        breaker = self._sink_breakers.get(id(sink))
        if not own and breaker is not None and not breaker.allow():
            self._c_sink_skips.inc()
            log.warning("sink %s: circuit %s; skipping this interval",
                        sink.name, breaker.state_name)
            return
        span = parent.child(f"flush.sink.{sink.name}") if parent else None
        if span is not None and self._flush_trace:
            span.set_tag("rows", str(len(metrics)))
        t0 = time.perf_counter_ns()
        ok = True
        try:
            if own or self.retry_policy is None:
                dispatch_flush(sink, metrics)
            else:
                def on_retry(attempt, exc, delay):
                    with self._sink_stats_lock:
                        self._fanout_retries[sink.name] = (
                            self._fanout_retries.get(sink.name, 0) + 1)
                    log.warning("sink %s flush attempt %d failed: %s; "
                                "retrying in %.3fs", sink.name,
                                attempt + 1, exc, delay)

                self.retry_policy.run(
                    lambda: dispatch_flush(sink, metrics),
                    on_retry=on_retry)
            if not own and breaker is not None:
                breaker.record_success()
        except Exception as e:
            ok = False
            if span is not None:
                span.error = True
            with self._sink_stats_lock:
                self._sink_flush_errors[sink.name] = (
                    self._sink_flush_errors.get(sink.name, 0) + 1)
            if not own and breaker is not None:
                breaker.record_failure()
            log.warning("sink %s flush failed: %s", sink.name, e)
        finally:
            # the centrally-measured sink.* conventions
            # (sinks/sinks.go:11-24: metrics_flushed_total +
            # metric_flush_total_duration_ns, tagged sink:<name>) — the
            # fan-out wraps every sink, so no sink can forget to emit
            ns = time.perf_counter_ns() - t0
            self._t_sink_flush.observe(ns, sink=sink.name)
            with self._sink_stats_lock:
                rows, total_ns = self._sink_flush_stats.get(
                    sink.name, (0, 0))
                self._sink_flush_stats[sink.name] = (
                    rows + (len(metrics) if ok else 0), total_ns + ns)
            if span is not None:
                span.client_finish(self.trace_client)

    def _spawn_aux(self, target, *args) -> threading.Thread:
        """Fire-and-forget helpers (forward, span-sink flush) are tracked
        so shutdown can join them — an orphaned thread still inside JAX or
        gRPC at interpreter teardown aborts the process (SIGABRT)."""
        t = threading.Thread(target=target, args=args, daemon=True)
        t.start()
        with self._aux_lock:
            self._aux_threads = [x for x in self._aux_threads
                                 if x.is_alive()]
            self._aux_threads.append(t)
        return t

    def _watchdog(self):
        """reference server.go:900 FlushWatchdog: crash-only restart if
        flushes stall for N intervals. Two stall modes now that flush runs
        on its own thread: the pipeline stops swapping (last_flush stale)
        or the flush worker wedges inside a sink/plugin (last_flush_done
        stale while swaps continue)."""
        missed = self.cfg.flush_watchdog_missed_flushes
        while not self._shutdown.wait(self.interval / 2):
            stale = min(self.last_flush, self.last_flush_done)
            if time.time() - stale > missed * self.interval:
                log.critical(
                    "flush watchdog: no completed flush for %d intervals, "
                    "aborting", missed)
                os._exit(3)

    def shutdown(self, device_timeout: float = 180.0):
        """reference server.go:1418 Shutdown (graceful).

        The joins on device-owning threads (pipeline, flush worker) use a
        generous budget: on a real TPU the first compile of the swap/flush
        program can take tens of seconds, and abandoning a thread inside a
        JAX dispatch at interpreter teardown aborts the process
        (`FATAL: exception not rethrown`, rc 134 — the round-2 bench
        failure). Shutdown must leave NO thread inside the JAX runtime."""
        self._shutdown.set()
        # stop entering pump() on the pipeline thread's next pass; the
        # C++ reader threads themselves are joined AFTER the pipeline
        # thread exits (vr_stop frees the group a mid-flight vr_pump call
        # would still be reading). Fold the group's counters into the
        # Python ones FIRST: a FlushRequest already queued behind us will
        # snapshot packets_received, and losing the reader counts there
        # would emit a huge negative self-telemetry delta.
        with self._reader_fold_lock:
            stop_native_readers = self._native_readers_active
            if stop_native_readers:
                rc = self.aggregator.reader_counters()
                self._packets_received += rc["datagrams"]
                self._packets_dropped_py += rc["ring_dropped"]
                self._packets_toolong_py += rc["toolong"]
                # final admission drain for the same reason: shed/admit
                # decisions since the last poll tick must land in the
                # registry before the counters become unreachable (the
                # drain's "tenants" sub-dict rides along, so per-tenant
                # accounting survives a rolling restart exactly)
                if self._overload is not None:
                    try:
                        self._overload.fold_native_counts(
                            self.aggregator.admission_drain())
                    except Exception:
                        log.exception("native admission drain failed")
                elif self.tenancy is not None:
                    try:
                        drained = self.aggregator.admission_drain()
                        if drained.get("tenants"):
                            self.tenancy.fold_native(drained["tenants"])
                    except Exception:
                        log.exception("tenant drain failed")
                # the quarantine mirror must be current before the
                # shutdown checkpoint snapshots it below
                if self.tenancy is not None:
                    try:
                        self.tenancy.update_table(
                            self.aggregator.tenant_table())
                    except Exception:
                        log.exception("tenant table snapshot failed")
            self._native_readers_active = False
        for s in self._sockets:
            try:
                s.close()
            except OSError:
                pass
        self._release_unix_locks()
        prof = getattr(self, "_profiler", None)
        if prof is not None:
            prof.disable()
            path = "/tmp/veneur_tpu_profile.pstats"
            prof.dump_stats(path)
            log.info("CPU profile written to %s", path)
        # stop the feeders of packet_queue before _STOP so nothing enqueues
        # behind the sentinel: span pipeline (extraction loop-back), HTTP
        # /import, gRPC import
        self.trace_client.close()
        self.span_pipeline.stop()
        if self._overload is not None:
            self._overload.stop()
        if self._stats_sock is not None:
            self._stats_sock.close()   # eagerly created in __init__
            self._stats_sock = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()  # release the listening fd
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=1.0)
        if self.query_engine is not None:
            # before _STOP: the batcher thread enqueues snapshot/launch
            # requests on packet_queue; one racing in behind _STOP
            # would never run
            self.query_engine.close()
        if self.watch_engine is not None:
            # the engine launches on the device from its own thread; it
            # must be out of the JAX runtime before teardown (it never
            # touches packet_queue, so ordering vs _STOP is free)
            self.watch_engine.close()
        self.packet_queue.put(_STOP)
        # drain order matters: the pipeline thread may still enqueue a final
        # flush job; only after it exits is it safe to stop the flush worker
        # (a _STOP racing ahead of that job would strand the last interval)
        if self._pipeline_thread is not None:
            self._pipeline_thread.join(timeout=device_timeout)
            if self._pipeline_thread.is_alive():
                log.error("pipeline thread did not exit within %.0fs",
                          device_timeout)
        # pipeline is out of pump(); now it is safe to join + free the
        # C++ reader group (skip if the pipeline thread is wedged — a
        # freed group under a live vr_pump would be use-after-free)
        if stop_native_readers and not (
                self._pipeline_thread is not None
                and self._pipeline_thread.is_alive()):
            try:
                self.aggregator.readers_stop()
            except Exception:
                log.exception("native reader shutdown failed")
        # bounded put: with a full queue AND a wedged worker, a blocking
        # put would hang shutdown forever (the watchdog is already
        # disarmed); drop one stale job to make room instead
        while True:
            try:
                self._flush_jobs.put_nowait(_STOP)
                break
            except queue.Full:  # vtlint: disable=accounting-flow -- unaccounted branches displace the _STOP sentinel or race an emptied queue; no interval data is lost on them
                try:
                    stale = self._flush_jobs.get_nowait()
                    if stale is not _STOP:
                        # the displaced interval is counted like any
                        # other interval that never reached the sinks
                        self._c_intervals_deferred.inc()
                        stale[-1].finish(False, "dropped at shutdown")
                except queue.Empty:
                    pass
        if self._flush_thread is not None:
            self._flush_thread.join(timeout=device_timeout)
            if self._flush_thread.is_alive():
                log.error("flush worker did not exit within %.0fs",
                          device_timeout)
        # graceful-exit durability: checkpoint the sub-interval tail that
        # never reached a flush. Written SYNCHRONOUSLY (shutdown is the
        # one caller that must not race interpreter teardown) and always
        # newest, so a graceful restart restores ONLY the tail — flushed
        # intervals already left through the sinks. Restoring them too
        # would NOT wash out downstream: HLL registers and LWW gauges do
        # merge a duplicate fold idempotently, but counter accumulators
        # and t-digest centroid weights are ADDITIVE — a re-forwarded
        # interval double-counts them at the global tier. With
        # forward_dedup_window > 0 the tail's export is staged below as
        # an ack-gated unit, so the restart replays it under its
        # original (source_id, epoch, seq) exactly once and the dedup
        # layer (forward/envelope.py) suppresses any crash-driven
        # replay; without a window a crash falls back to the last
        # periodic checkpoint, i.e. at-least-once for the additive kinds
        # of that interval.
        if self._ckpt_writer is not None:
            if self.cfg.checkpoint_on_shutdown:
                try:
                    from veneur_tpu.persistence import build_snapshot
                    state, table = self.aggregator.swap()
                    flush_arrays, table, raw = self.aggregator.compute_flush(
                        state, table, self.cfg.percentiles, want_raw=True,
                        history=self.history)
                    # stage the tail's forward payload BEFORE serializing
                    # the spill: the tail snapshot then carries the unit
                    # with its envelope, the restart replays it once, and
                    # fold_snapshot(skip_forwarded) keeps its rows from
                    # re-exporting under a second seq
                    absorbed = False
                    if self.cfg.collective_attach:
                        absorbed = self._absorb_colocated(raw, table)
                    if self._fwd_source_id is not None and not absorbed:
                        self._stage_forward_unit(raw, table)
                    spill_bytes, spill_n = None, 0
                    if self.forward_spill is not None:
                        spill_bytes = self.forward_spill.to_bytes()
                        spill_n = len(self.forward_spill)
                    n_shards = getattr(self.aggregator, "n_shards", 1)
                    self._ckpt_writer.write_sync(build_snapshot(
                        self.aggregator.spec, table, flush_arrays, raw,
                        agg_kind="sharded" if n_shards > 1 else "single",
                        n_shards=n_shards, interval_ts=int(time.time()),
                        hostname=self.hostname, spill=spill_bytes,
                        spill_entries=spill_n,
                        forward_meta=self._forward_meta_snapshot(),
                        watches=self._watch_snapshot(),
                        history=self._history_snapshot(),
                        tenants=self._tenant_snapshot(),
                        keytables=self._tables_snapshot()))
                except Exception:
                    log.exception("final checkpoint failed; last periodic "
                                  "checkpoint remains newest")
            self._ckpt_writer.close()
        with self._aux_lock:
            aux = list(self._aux_threads)
        for t in aux:
            t.join(timeout=30.0)
        # forward client closes only after the aux forward threads using it
        # have drained
        if self._forward_client is not None:
            self._forward_client.close()
        for t in self._threads:
            t.join(timeout=2.0)
        # quiesce the device runtime: any computation the joined threads
        # dispatched asynchronously must complete before teardown
        try:
            import jax
            # vtlint: disable=jax-hot-path -- shutdown quiesce: the full-device drain is the point here
            jax.block_until_ready(self.aggregator.state)
        except Exception as e:
            # best-effort quiesce: a torn-down backend raising here is
            # expected during interpreter exit, but say so
            log.debug("final device quiesce skipped: %s", e)
        if self._collective_registered:
            from veneur_tpu.collective import tier as collective_tier
            collective_tier.unregister(self._collective_registered,
                                       self.aggregator)
