"""Span pipeline: SpanChan + SpanWorker fan-out (reference worker.go:575-719
SpanWorker, server.go:991-1065 span intake).

Each span fans out to every span sink; a span that is invalid as a trace
AND carries no metrics is dropped (worker.go:627-640). Sink ingest runs
with a per-sink timeout budget enforced at flush, not per span (Python
threads can't be interrupted mid-call; the reference's 9s per-sink ingest
timeout maps to the flush deadline here)."""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, List, Optional

from veneur_tpu.protocol.wire import valid_trace

log = logging.getLogger("veneur_tpu.server.spans")


class SpanPipeline:
    def __init__(self, span_sinks: List, capacity: int = 100,
                 num_workers: int = 1, common_tags=None,
                 report_samples: Optional[Callable] = None):
        """report_samples: callable taking a list of SSFSamples — the span
        worker reports its own per-sink telemetry at flush, exactly as the
        reference's SpanWorker.Flush does through its statsd client
        (worker.go:698-713)."""
        self.span_sinks = list(span_sinks)
        self.chan: "queue.Queue" = queue.Queue(maxsize=capacity)
        self.num_workers = max(1, num_workers)
        self.common_tags = dict(common_tags or {})
        self.report_samples = report_samples
        self.spans_received = 0
        self.spans_dropped = 0
        self.chan_cap_hits = 0
        self.sink_errors = 0
        self._threads: List[threading.Thread] = []
        self._stop = object()
        # per-sink ingest accounting since the last flush, accumulated by
        # worker threads under a lock (the reference uses per-sink atomics,
        # worker.go:617-690 cumulativeTimes)
        self._stats_lock = threading.Lock()
        self._ingest_ns: dict = {}
        self._ingested: dict = {}
        # per-(service, ssf_format) intake counters since the last drain
        # (server.go:154-157 ssfServiceSpanMetrics in a sync.Map;
        # flusher.go:463-466 swaps them out per flush): value is
        # [received, root_received]. Own lock: listener threads must not
        # contend with the span workers' per-batch stats lock.
        self._svc_lock = threading.Lock()
        self._svc_counts: dict = {}

    # -- intake (server.go:1022 handleSSF) ----------------------------------
    def handle_span(self, span, ssf_format: str = None) -> bool:
        """Enqueue; returns False when the channel is full (the reference
        blocks; we drop + count to protect the UDP readers). ssf_format
        ("packet"/"framed") is set by the WIRE listeners only: the
        reference's channel client feeds SpanChan directly
        (server.go:310), bypassing the per-service intake counters, so
        self-telemetry spans (format None) skip them too."""
        # += on an attribute is read-modify-write; concurrent listener
        # threads can interleave at bytecode boundaries and lose counts
        # (the reference uses atomics) — one short lock covers both
        # counters
        with self._svc_lock:
            self.spans_received += 1
            if ssf_format is not None:
                key = (span.service, ssf_format)
                c = self._svc_counts.get(key)
                if c is None:
                    c = self._svc_counts[key] = [0, 0]
                c[0] += 1
                if span.id == span.trace_id:
                    c[1] += 1
        try:
            self.chan.put_nowait(span)
            return True
        except queue.Full:
            with self._svc_lock:
                self.spans_dropped += 1
                self.chan_cap_hits += 1   # worker.go:717 hit_chan_cap
            return False

    def drain_service_counts(self) -> dict:
        """Swap out the per-(service, format) intake counters (the
        flusher.go:463 atomic-swap idiom)."""
        with self._svc_lock:
            counts, self._svc_counts = self._svc_counts, {}
        return counts

    # -- workers (worker.go:611 SpanWorker.Work) ----------------------------
    def start(self):
        for i in range(self.num_workers):
            t = threading.Thread(target=self._work, daemon=True,
                                 name=f"span-worker-{i}")
            t.start()
            self._threads.append(t)

    def _work(self):
        """Batch-drains the channel (one blocking get, then up to 255
        opportunistic gets): per-span queue hops were ~2/3 of the span
        firehose's host cost. Sinks exposing ingest_many get the whole
        batch in one call; others keep the per-span path. Each _stop
        sentinel still terminates exactly one worker."""
        stopping = False
        while not stopping:
            first = self.chan.get()
            if first is self._stop:
                return
            batch = [first]
            while len(batch) < 256:
                try:
                    nxt = self.chan.get_nowait()
                except queue.Empty:
                    break
                if nxt is self._stop:
                    stopping = True
                    break
                batch.append(nxt)
            spans = []
            for span in batch:
                # tag with commonTags without clobbering span tags
                # (worker.go:619-626)
                for k, v in self.common_tags.items():
                    if k not in span.tags:
                        span.tags[k] = v
                # drop spans that are invalid traces and carry no metrics
                if not valid_trace(span) and not span.metrics:
                    with self._svc_lock:
                        self.spans_dropped += 1
                    continue
                spans.append(span)
            if not spans:
                continue
            for sink in self.span_sinks:
                t0 = time.perf_counter_ns()
                many = getattr(sink, "ingest_many", None)
                delivered = False
                if many is not None:
                    try:
                        many(spans)
                        delivered = True
                    except Exception as e:
                        # fall through to per-span delivery so one bad
                        # span can't take the other 255 with it;
                        # ingest_many implementations must be atomic
                        # (no partial state on raise) for this retry to
                        # stay exactly-once
                        log.warning("span sink %s ingest_many failed, "
                                    "retrying per-span: %s", sink.name, e)
                if delivered:
                    ok_spans = len(spans)
                else:
                    ok_spans = 0
                    for span in spans:
                        try:
                            sink.ingest(span)
                            ok_spans += 1
                        except Exception as e:
                            with self._stats_lock:
                                self.sink_errors += 1
                            log.warning("span sink %s ingest failed: %s",
                                        sink.name, e)
                with self._stats_lock:
                    self._ingest_ns[sink.name] = (
                        self._ingest_ns.get(sink.name, 0)
                        + time.perf_counter_ns() - t0)
                    # only successfully-ingested spans count toward
                    # veneur.sink.spans_flushed_total — a dead sink must
                    # not look healthy on dashboards
                    self._ingested[sink.name] = (
                        self._ingested.get(sink.name, 0) + ok_spans)

    def flush(self):
        """worker.go:698 SpanWorker.Flush: flush every span sink, timing
        each, then report the per-sink conventions the reference's span
        worker emits (worker.go:706-713), veneur.-prefixed like the
        reference's central ssf.NamePrefix:
        veneur.worker.span.flush_duration_ns,
        veneur.sink.span_ingest_total_duration_ns (cumulative since last
        flush), and veneur.sink.spans_flushed_total (measured centrally
        as spans
        delivered to the sink — a sampling sink may send fewer downstream,
        which its own telemetry covers)."""
        with self._stats_lock:
            ing_ns, self._ingest_ns = self._ingest_ns, {}
            ing_n, self._ingested = self._ingested, {}
        samples = []
        for sink in self.span_sinks:
            t0 = time.perf_counter_ns()
            try:
                sink.flush()
            except Exception as e:
                log.warning("span sink %s flush failed: %s", sink.name, e)
            if self.report_samples is None:
                continue
            from veneur_tpu.samplers import ssf_samples
            tags = {"sink": sink.name}
            samples.append(ssf_samples.timing(
                "veneur.worker.span.flush_duration_ns",
                (time.perf_counter_ns() - t0) / 1e9, tags))
            samples.append(ssf_samples.timing(
                "veneur.sink.span_ingest_total_duration_ns",
                ing_ns.get(sink.name, 0) / 1e9, tags))
            n = ing_n.get(sink.name, 0)
            if n:
                samples.append(ssf_samples.count(
                    "veneur.sink.spans_flushed_total", n, tags))
        if samples and self.report_samples is not None:
            try:
                self.report_samples(samples)
            except Exception as e:
                log.warning("span worker self-report failed: %s", e)

    def stop(self):
        for _ in self._threads:
            self.chan.put(self._stop)
        for t in self._threads:
            t.join(timeout=2.0)
