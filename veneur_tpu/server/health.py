"""Liveness and readiness evaluation for GET /healthz and /readyz.

Two different questions, two different consumers:

- /healthz (liveness): "is the process worth keeping?" — consumed by a
  supervisor that will RESTART on failure. True iff the pipeline and
  flush-worker threads are alive and the last completed flush is inside
  the watchdog budget (the same `min(last_flush, last_flush_done)`
  staleness the crash-only watchdog enforces, so the two can never
  disagree about what "stuck" means). Overload state is deliberately
  NOT consulted: a SHEDDING server is doing its job; restarting it
  would turn graceful degradation into an outage.

- /readyz (readiness): "should peers send this server NEW traffic?" —
  consumed by load balancers and the proxy ring. True iff the overload
  state is at most PRESSURED, checkpoint restore has completed (a
  restoring server would flush partial aggregates), and the forward
  breaker is not open (a local that cannot reach its global tier only
  accumulates what it will shed).

Both return (ok, detail) where detail is a JSON-ready dict, so the HTTP
handlers and tests share one evaluation.
"""

from __future__ import annotations

from typing import Dict, Tuple

from veneur_tpu.reliability.overload import PRESSURED, STATE_NAMES
from veneur_tpu.reliability.policy import OPEN


def _flush_staleness_budget(server) -> float:
    """Seconds of flush silence tolerated before liveness fails. With
    the watchdog armed this is the watchdog's own budget; without it, a
    generous multiple of the interval (manual-flush rigs — tests,
    benchmarks — idle between flushes by design)."""
    missed = getattr(server.cfg, "flush_watchdog_missed_flushes", 0)
    if missed and missed > 0:
        return missed * server.interval
    return 10.0 * server.interval + 60.0


def check_live(server) -> Tuple[bool, Dict]:
    import time

    pipeline = getattr(server, "_pipeline_thread", None)
    flusher = getattr(server, "_flush_thread", None)
    pipeline_ok = pipeline is not None and pipeline.is_alive()
    flusher_ok = flusher is not None and flusher.is_alive()
    stale_s = time.time() - min(server.last_flush, server.last_flush_done)
    budget = _flush_staleness_budget(server)
    flush_ok = stale_s <= budget
    ok = pipeline_ok and flusher_ok and flush_ok
    return ok, {
        "live": ok,
        "pipeline_thread_alive": pipeline_ok,
        "flush_worker_alive": flusher_ok,
        "flush_staleness_s": round(stale_s, 3),
        "flush_staleness_budget_s": round(budget, 3),
    }


def ready_phase(server) -> str:
    """Machine-readable lifecycle phase for /readyz consumers that need
    to distinguish "joining"/"moving" from "broken": a restoring or
    resharding server is doing planned work (dashboards should not page)
    while a draining one is leaving the ring on purpose. Exactly one of
    `restoring | resharding | draining | ready`, in that precedence —
    restore wins because a restoring server is not yet serving at all,
    and drain wins over reshard because shutdown abandons any move."""
    if not bool(getattr(server, "_restore_complete", True)):
        return "restoring"
    shutdown = getattr(server, "_shutdown", None)
    if shutdown is not None and shutdown.is_set():
        return "draining"
    ov = getattr(server, "_overload", None)
    if bool(getattr(server, "_resharding", False)) or (
            ov is not None and getattr(ov, "resharding", False)):
        return "resharding"
    return "ready"


def check_ready(server) -> Tuple[bool, Dict]:
    ov = getattr(server, "_overload", None)
    state = ov.state if ov is not None else 0
    state_ok = state <= PRESSURED
    restored = bool(getattr(server, "_restore_complete", True))
    fb = getattr(server, "_forward_breaker", None)
    forward_ok = fb is None or fb.state != OPEN
    ok = state_ok and restored and forward_ok
    return ok, {
        "ready": ok,
        # resharding is ready-but-announcing: ok stays True (peers keep
        # sending — the move is live), only the phase flips
        "phase": ready_phase(server),
        "overload_state": STATE_NAMES.get(state, str(state)),
        "overload_pressure": round(ov.pressure, 4) if ov is not None
        else 0.0,
        "restore_complete": restored,
        "forward_breaker_open": not forward_ok,
    }
