"""Server construction from config — sink/plugin wiring.

Mirrors reference server.go:261 NewFromConfig's gating: each sink exists iff
its config keys are set (server.go:472-678), plugins registered from
flush_file / aws_* (server.go:683-731).
"""

from __future__ import annotations

from veneur_tpu.config import Config
from veneur_tpu.server.server import Server


def new_from_config(cfg: Config, extra_metric_sinks=(), extra_span_sinks=(),
                    extra_plugins=()) -> Server:
    metric_sinks = list(extra_metric_sinks)
    span_sinks = list(extra_span_sinks)
    plugins = list(extra_plugins)

    if cfg.debug_flushed_metrics:
        from veneur_tpu.sinks.debug import DebugMetricSink
        metric_sinks.append(DebugMetricSink())
    if cfg.debug_ingested_spans:
        from veneur_tpu.sinks.debug import DebugSpanSink
        span_sinks.append(DebugSpanSink())
    if cfg.datadog_api_key and cfg.datadog_api_hostname:
        from veneur_tpu.sinks.datadog import DatadogMetricSink
        metric_sinks.append(DatadogMetricSink(
            api_key=cfg.datadog_api_key,
            hostname=cfg.hostname,
            api_url=cfg.datadog_api_hostname,
            interval_s=cfg.parse_interval(),
            flush_max_per_body=cfg.datadog_flush_max_per_body,
            tags=cfg.tags,
            metric_name_prefix_drops=cfg.datadog_metric_name_prefix_drops,
            exclude_tags_prefix_by_prefix_metric=(
                cfg.datadog_exclude_tags_prefix_by_prefix_metric)))
    if cfg.flush_file:
        from veneur_tpu.sinks.localfile import LocalFilePlugin
        plugins.append(LocalFilePlugin(
            cfg.flush_file, cfg.hostname,
            interval_s=int(cfg.parse_interval())))
    if cfg.aws_s3_bucket and cfg.aws_region:
        from veneur_tpu.plugins.s3 import S3Plugin
        plugins.append(S3Plugin(
            bucket=cfg.aws_s3_bucket, region=cfg.aws_region,
            access_key_id=cfg.aws_access_key_id,
            secret_access_key=cfg.aws_secret_access_key,
            hostname=cfg.hostname,
            interval_s=int(cfg.parse_interval())))

    return Server(cfg, metric_sinks=metric_sinks, span_sinks=span_sinks,
                  plugins=plugins)
