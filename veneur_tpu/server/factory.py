"""Server construction from config — sink/plugin wiring.

Mirrors reference server.go:261 NewFromConfig's gating: each sink exists iff
its config keys are set (server.go:472-678), plugins registered from
flush_file / aws_* (server.go:683-731).
"""

from __future__ import annotations

import logging

from veneur_tpu.config import Config
from veneur_tpu.server.server import Server

log = logging.getLogger("veneur_tpu.server.factory")


def new_from_config(cfg: Config, extra_metric_sinks=(), extra_span_sinks=(),
                    extra_plugins=()) -> Server:
    metric_sinks = list(extra_metric_sinks)
    span_sinks = list(extra_span_sinks)
    plugins = list(extra_plugins)

    if cfg.debug_flushed_metrics:
        from veneur_tpu.sinks.debug import DebugMetricSink
        metric_sinks.append(DebugMetricSink())
    if cfg.debug_ingested_spans:
        from veneur_tpu.sinks.debug import DebugSpanSink
        span_sinks.append(DebugSpanSink())
    if cfg.datadog_api_key and cfg.datadog_api_hostname:
        from veneur_tpu.sinks.datadog import DatadogMetricSink
        metric_sinks.append(DatadogMetricSink(
            api_key=cfg.datadog_api_key,
            hostname=cfg.hostname,
            api_url=cfg.datadog_api_hostname,
            interval_s=cfg.parse_interval(),
            flush_max_per_body=cfg.datadog_flush_max_per_body,
            tags=cfg.tags,
            metric_name_prefix_drops=cfg.datadog_metric_name_prefix_drops,
            exclude_tags_prefix_by_prefix_metric=(
                cfg.datadog_exclude_tags_prefix_by_prefix_metric)))
    if cfg.signalfx_api_key:
        # gate on the api key alone, like reference server.go:472; the
        # endpoint has the public default
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        per_tag = {}
        for e in cfg.signalfx_per_tag_api_keys:
            if "name" not in e or "api_key" not in e:
                raise ValueError(
                    f"signalfx_per_tag_api_keys entry needs name and "
                    f"api_key: {sorted(e)}")
            per_tag[e["name"]] = e["api_key"]
        from veneur_tpu.config import parse_duration
        # reference server.go:482-486: empty period defaults to 10m
        refresh = parse_duration(
            cfg.signalfx_dynamic_per_tag_api_keys_refresh_period or "10m")
        metric_sinks.append(SignalFxMetricSink(
            api_key=cfg.signalfx_api_key,
            endpoint=cfg.signalfx_endpoint_base
            or "https://ingest.signalfx.com",
            hostname=cfg.hostname,
            hostname_tag=cfg.signalfx_hostname_tag or "host",
            vary_key_by=cfg.signalfx_vary_key_by,
            per_tag_api_keys=per_tag,
            flush_max_per_body=cfg.signalfx_flush_max_per_body or 5000,
            metric_name_prefix_drops=cfg.signalfx_metric_name_prefix_drops,
            metric_tag_prefix_drops=cfg.signalfx_metric_tag_prefix_drops,
            tags=cfg.tags,
            dynamic_per_tag_tokens_enable=(
                cfg.signalfx_dynamic_per_tag_api_keys_enable),
            dynamic_per_tag_tokens_refresh_s=refresh,
            api_endpoint=cfg.signalfx_endpoint_api
            or "https://api.signalfx.com"))
    if bool(cfg.splunk_hec_address) != bool(cfg.splunk_hec_token):
        # reference server.go:574-576: half a splunk config is an error
        raise ValueError(
            "both splunk_hec_address and splunk_hec_token must be set")

    # tracing sinks only exist when spans can arrive
    # (reference server.go:516 gates on ssf_listen_addresses)
    spans_enabled = bool(cfg.ssf_listen_addresses)
    if spans_enabled and cfg.datadog_trace_api_address:
        from veneur_tpu.sinks.datadog_spans import DatadogSpanSink
        span_sinks.append(DatadogSpanSink(
            cfg.datadog_trace_api_address,
            buffer_size=cfg.datadog_span_buffer_size or 16384))
    if spans_enabled and cfg.splunk_hec_address:
        from veneur_tpu.config import parse_duration
        from veneur_tpu.sinks.splunk import SplunkSpanSink
        span_sinks.append(SplunkSpanSink(
            hec_address=cfg.splunk_hec_address,
            tls_validate_hostname=cfg.splunk_hec_tls_validate_hostname,
            token=cfg.splunk_hec_token,
            hostname=cfg.hostname,
            batch_size=cfg.splunk_hec_batch_size,
            sample_rate=cfg.splunk_span_sample_rate or 1,
            send_timeout=parse_duration(cfg.splunk_hec_send_timeout)
            if cfg.splunk_hec_send_timeout else 10.0,
            # reference example.yaml:500: workers default to 1
            workers=cfg.splunk_hec_submission_workers or 1,
            ingest_timeout=parse_duration(cfg.splunk_hec_ingest_timeout)
            if cfg.splunk_hec_ingest_timeout else 0.0,
            max_conn_lifetime=parse_duration(
                cfg.splunk_hec_max_connection_lifetime)
            if cfg.splunk_hec_max_connection_lifetime else 10.0,
            conn_lifetime_jitter=parse_duration(
                cfg.splunk_hec_connection_lifetime_jitter)
            if cfg.splunk_hec_connection_lifetime_jitter else 0.0))
    if spans_enabled and cfg.xray_address:
        if cfg.xray_sample_percentage <= 0:
            # reference server.go:535: 0% means no sink, loudly
            log.warning("xray_address set but xray_sample_percentage is 0; "
                        "not sending any segments")
        else:
            from veneur_tpu.sinks.xray import XRaySpanSink
            span_sinks.append(XRaySpanSink(
                daemon_address=cfg.xray_address,
                sample_percentage=cfg.xray_sample_percentage,
                # annotation allowlist matches tag KEYS
                # (server.go:540-542 strips at ':')
                annotation_tags=[t.split(":")[0]
                                 for t in cfg.xray_annotation_tags]))
    if spans_enabled and cfg.falconer_address:
        from veneur_tpu.sinks.grpsink import FalconerSpanSink
        span_sinks.append(FalconerSpanSink(cfg.falconer_address))
    if spans_enabled and cfg.grpsink_address:
        from veneur_tpu.sinks.grpsink import GRPCSpanSink
        span_sinks.append(GRPCSpanSink(cfg.grpsink_address))
    if cfg.kafka_broker:
        from veneur_tpu.sinks.kafka import KafkaMetricSink, KafkaSpanSink
        if cfg.kafka_metric_topic or cfg.kafka_check_topic:
            metric_sinks.append(KafkaMetricSink(
                cfg.kafka_broker,
                metric_topic=cfg.kafka_metric_topic,
                check_topic=cfg.kafka_check_topic))
        if spans_enabled and cfg.kafka_span_topic:
            span_sinks.append(KafkaSpanSink(
                cfg.kafka_broker, span_topic=cfg.kafka_span_topic,
                serialization=cfg.kafka_span_serialization_format
                or "protobuf",
                sample_rate_percent=cfg.kafka_span_sample_rate_percent,
                sample_tag=cfg.kafka_span_sample_tag))
    if spans_enabled and cfg.lightstep_access_token:
        from veneur_tpu.sinks.lightstep import LightStepSpanSink
        span_sinks.append(LightStepSpanSink(
            access_token=cfg.lightstep_access_token,
            collector_host=cfg.lightstep_collector_host,
            num_clients=cfg.lightstep_num_clients or 1))
    if cfg.flush_file:
        from veneur_tpu.sinks.localfile import LocalFilePlugin
        plugins.append(LocalFilePlugin(
            cfg.flush_file, cfg.hostname,
            interval_s=int(cfg.parse_interval())))
    if cfg.aws_s3_bucket and cfg.aws_region:
        from veneur_tpu.plugins.s3 import S3Plugin
        plugins.append(S3Plugin(
            bucket=cfg.aws_s3_bucket, region=cfg.aws_region,
            access_key_id=cfg.aws_access_key_id,
            secret_access_key=cfg.aws_secret_access_key,
            hostname=cfg.hostname,
            interval_s=int(cfg.parse_interval()),
            staging_dir=cfg.aws_s3_staging_dir))

    return Server(cfg, metric_sinks=metric_sinks, span_sinks=span_sinks,
                  plugins=plugins)
