"""SSF wire protocol: framing and packet parsing.

reference protocol/wire.go: frame = [1B version=0][4B big-endian length]
[protobuf SSFSpan], 16MB cap (:44); framing errors are fatal per connection
(IsFramingError); ParseSSF (:137) normalizes the legacy name tag and zero
sample rates.
"""

from __future__ import annotations

import struct
from typing import Optional

from veneur_tpu.proto import ssf_pb2

MAX_SSF_PACKET_LENGTH = 16 * 1024 * 1024
VERSION_0 = 0


class FramingError(Exception):
    """The stream is unrecoverably broken (reference IsFramingError)."""


def parse_ssf(packet: bytes) -> ssf_pb2.SSFSpan:
    """Parse + normalize one SSF protobuf packet (wire.go:137 ParseSSF)."""
    span = ssf_pb2.SSFSpan()
    span.ParseFromString(packet)
    if not span.name:
        # legacy name-tag promotion (wire.go:155-163)
        if "name" in span.tags:
            span.name = span.tags["name"]
        span.tags.pop("name", None)
    for sample in span.metrics:
        if sample.sample_rate == 0:
            sample.sample_rate = 1.0
    return span


def valid_trace(span: ssf_pb2.SSFSpan) -> bool:
    """wire.go:81 ValidTrace."""
    return (span.id != 0 and span.trace_id != 0
            and span.start_timestamp != 0 and span.end_timestamp != 0
            and bool(span.name))


def read_ssf(stream) -> Optional[ssf_pb2.SSFSpan]:
    """Read one framed span from a file-like stream (wire.go:108 ReadSSF).
    Returns None on clean EOF at a message boundary; raises FramingError on
    mid-frame EOF, bad version, or oversized length."""
    head = stream.read(1)
    if head == b"":
        return None
    version = head[0]
    if version != VERSION_0:
        raise FramingError(f"unknown SSF frame version {version}")
    raw_len = stream.read(4)
    if len(raw_len) < 4:
        raise FramingError("truncated SSF frame length")
    (length,) = struct.unpack(">I", raw_len)
    if length > MAX_SSF_PACKET_LENGTH:
        raise FramingError(f"SSF frame of {length} bytes exceeds cap")
    body = b""
    while len(body) < length:
        chunk = stream.read(length - len(body))
        if not chunk:
            raise FramingError("truncated SSF frame body")
        body += chunk
    return parse_ssf(body)


def write_ssf(stream, span: ssf_pb2.SSFSpan) -> int:
    """Write one framed span (wire.go:182 WriteSSF)."""
    body = span.SerializeToString()
    stream.write(struct.pack(">BI", VERSION_0, len(body)))
    stream.write(body)
    return len(body)
