from veneur_tpu.protocol.wire import (  # noqa: F401
    FramingError,
    MAX_SSF_PACKET_LENGTH,
    parse_ssf,
    read_ssf,
    valid_trace,
    write_ssf,
)
