"""S3 archive plugin (reference plugins/s3/s3.go): posts each flush's
InterMetrics as `<hostname>/<timestamp>.tsv.gz` (s3.go:90 S3Path).

boto3 is not part of this image, so the S3 client is injectable: pass any
object with `put_object(Bucket=, Key=, Body=)` (boto3's S3 client
signature). Without one, construction requires boto3 and raises cleanly —
the factory only wires this plugin when aws_* keys are configured.
"""

from __future__ import annotations

import logging
import time

from veneur_tpu.sinks.localfile import encode_intermetrics_csv

log = logging.getLogger("veneur_tpu.plugins.s3")


class S3Plugin:
    name = "s3"

    def __init__(self, bucket: str, region: str, hostname: str,
                 access_key_id: str = "", secret_access_key: str = "",
                 interval_s: int = 10, client=None, staging_dir: str = ""):
        self.bucket = bucket
        self.hostname = hostname
        self.interval_s = interval_s
        # optional durable staging: each flush's object is written
        # locally (atomic temp + rename) BEFORE the network put and
        # unlinked only after S3 acknowledges — a crash or failed upload
        # leaves a complete .tsv.gz an operator can re-upload, never a
        # torn one (README §Durability)
        self.staging_dir = staging_dir
        if client is None:
            try:
                import boto3  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "S3 plugin requires boto3 or an injected client") from e
            client = boto3.client(
                "s3", region_name=region,
                aws_access_key_id=access_key_id or None,
                aws_secret_access_key=secret_access_key or None)
        self.client = client

    def s3_path(self, ts: int, ext: str = "tsv.gz") -> str:
        """reference plugins/s3/s3.go:90: <hostname>/<unix_ts>.<ext>."""
        return f"{self.hostname}/{ts}.{ext}"

    def flush(self, metrics):
        import os
        ts = int(time.time())
        body = encode_intermetrics_csv(metrics, self.hostname,
                                       self.interval_s, compress=True)
        staged = None
        if self.staging_dir:
            from veneur_tpu.utils.atomicio import atomic_write_bytes
            os.makedirs(self.staging_dir, exist_ok=True)
            staged = os.path.join(self.staging_dir, f"{ts}.tsv.gz")
            atomic_write_bytes(staged, body)
        self.client.put_object(Bucket=self.bucket,
                               Key=self.s3_path(ts), Body=body)
        if staged is not None:
            # acknowledged upload: the staged copy has served its purpose
            try:
                os.unlink(staged)
            except OSError:
                pass

    # see LocalFilePlugin: materialize, but don't veto the frame path
    accepts_frames = True

    def flush_frame(self, frame):
        self.flush(frame.intermetrics())
