"""Post-flush plugins (reference plugins/plugins.go:16-19): hooks that
receive the final InterMetric batch after sink flushes. A plugin is any
object with `.name` and `.flush(metrics)`."""
