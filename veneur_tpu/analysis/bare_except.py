"""vtlint pass: no silent error swallowing in the egress paths.

Port of scripts/check_no_bare_except.py. Fails on two patterns inside
the egress modules:

  except:                      # bare except — catches KeyboardInterrupt
  except Exception: pass       # swallow with NO logging/accounting

Both hide exactly the failures the reliability layer exists to count: a
dropped flush that is neither retried, spilled, nor reported is an
invisible data loss. `except BaseException:` with a bare re-raise
passes (the resource-cleanup idiom); a body that does real work passes.
"""

from __future__ import annotations

import ast
from typing import List

from veneur_tpu.analysis.core import Finding, Project

NAME = "bare-except"
DOC = ("egress paths never swallow errors silently "
       "(no bare except, no `except Exception: pass`)")

# the egress surface: everything that ships data out of the process
EGRESS = [
    "veneur_tpu/sinks",
    "veneur_tpu/forward",
    "veneur_tpu/reliability",
    "veneur_tpu/server/server.py",
]


def _is_swallow(handler: ast.ExceptHandler) -> bool:
    """True for a body that does nothing at all."""
    return all(isinstance(stmt, ast.Pass)
               or (isinstance(stmt, ast.Expr)
                   and isinstance(stmt.value, ast.Constant)
                   and stmt.value.value is Ellipsis)
               for stmt in handler.body)


def _is_reraise_only(handler: ast.ExceptHandler) -> bool:
    return (len(handler.body) == 1
            and isinstance(handler.body[0], ast.Raise)
            and handler.body[0].exc is None)


def run(project: Project, egress: List[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for ctx in project.files(*(egress or EGRESS)):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None and not _is_reraise_only(node):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    "bare `except:` in egress path"))
            elif (isinstance(node.type, ast.Name)
                  and node.type.id in ("Exception", "BaseException")
                  and _is_swallow(node)):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`except {node.type.id}:` swallows silently "
                    "(log it or count it)"))
    return findings
