"""vtlint pass: key-table capacity mutation only behind the grow helper.

Per-kind key-table capacities can only change at a flush swap boundary:
the C++ engine's sentinel lanes, the Python packed-buffer layouts, the
flush program's compile key, and the snapshot sidecar all derive from
the live TableSpec, so a capacity that changes anywhere else tears the
interval. `veneur_tpu/tables/growth.py` is the ONE module that owns the
sequencing (stage on the engine via `capacity_set`, apply inside the
swap's reset while the tables are empty, rebuild the backend around the
same engine). This pass makes that grow site un-bypassable:

  1. calls to a capacity mutator — `capacity_set`, `vt_capacity_set`,
     `vrm_capacity_set` — anywhere in the tree outside growth.py and
     the ctypes binding layer (veneur_tpu/native/__init__.py) are
     flagged;
  2. assignments to a `spec` or `pspec` attribute outside `__init__`
     (construction fixes the TableSpec; a live capacity change must be
     a whole-backend rebuild through tables/growth.py grow_swap()) are
     flagged.

Tests and the analysis package itself are out of scope — the contract
binds production code; tests exercise mutators on purpose.
"""

from __future__ import annotations

import ast
from typing import List

from veneur_tpu.analysis.core import Finding, Project

NAME = "table-grow-quiesce"
DOC = ("key-table capacity / TableSpec mutation happens only behind the "
       "swap-boundary grow helper (tables/growth.py)")

# the scanned tree (production code only; tests exercise mutators)
ROOTS = ["veneur_tpu"]

_MUTATORS = {"capacity_set", "vt_capacity_set", "vrm_capacity_set"}

_CALL_ALLOWED = {
    "veneur_tpu/tables/growth.py",     # THE documented grow site
    "veneur_tpu/native/__init__.py",   # ctypes binding internals
}

_SPEC_ATTRS = {"spec", "pspec"}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _attr_targets(stmt: ast.stmt):
    """Attribute names assigned by a statement (plain or augmented)."""
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    else:
        return
    for t in targets:
        if isinstance(t, ast.Attribute):
            yield t.attr


def _scan_file(ctx) -> List[Finding]:
    findings: List[Finding] = []
    # map every node to its enclosing function name, so rule 2 can give
    # construction (__init__) its pass
    enclosing = {}

    def mark(fn_name, node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mark(child.name, child)
            else:
                enclosing[child] = fn_name
                mark(fn_name, child)

    mark("", ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in _MUTATORS and ctx.rel not in _CALL_ALLOWED:
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"{name}() outside the grow helper — per-kind "
                    "capacities may only change inside "
                    "tables/growth.py grow_swap(), where the staged "
                    "capacities apply at the swap's reset while the "
                    "tables are empty"))
        for attr in _attr_targets(node) if isinstance(node, ast.stmt) \
                else ():
            if attr in _SPEC_ATTRS and enclosing.get(node) != "__init__":
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"assignment to .{attr} outside __init__ — the "
                    "TableSpec is fixed at construction; a live "
                    "capacity change is a whole-backend rebuild "
                    "through tables/growth.py grow_swap()"))
    return findings


def run(project: Project, roots: List[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    scanned = False
    for ctx in project.files(*(roots or ROOTS)):
        scanned = True
        if ctx.rel.startswith("veneur_tpu/analysis/"):
            continue   # the lint layer names mutators in strings/docs
        findings.extend(_scan_file(ctx))
    if not scanned:
        findings.append(Finding(
            NAME, (roots or ROOTS)[0], 0, "scan root missing or empty"))
    return findings
