"""vtlint pass: drop/send-failure handlers account on EVERY path.

Supersedes the any-account-in-body halves of drop-accounting and
ambiguous-paths with a dataflow walk: a handler that increments a
counter on one branch but early-returns on another still loses data
silently on the unaccounted branch, and the old lint couldn't see it.

The walk simulates the handler body with an accounted/unaccounted state
set: an accounting statement (raise, `+= `, `.inc(...)`, `.append` onto
a rejection collection, or a call to a same-module helper that itself
accounts on every path — one level deep) flips the state; `return`
while possibly unaccounted, or control falling off the end of the
handler while possibly unaccounted, is a finding at that line.

Surface: the drop-exception handlers (`Full`/`ParseError`/
`FramingError`) across the ingest+egress tree, plus every handler in
the exactly-once send/retry functions.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from veneur_tpu.analysis.core import FileContext, Finding, Project
from veneur_tpu.analysis import ambiguous_paths, drop_accounting

NAME = "accounting-flow"
DOC = ("every branch of a drop/send-failure handler accounts before "
       "it exits (dataflow, follows early returns + helper calls); "
       "per-ring counter drains fold across ALL rings")

_REJECT_NAMES = ("invalid", "drop", "reject", "shed", "error")

# surface 3: cross-ring counter folds. The multi-ring engine's per-ring
# drains are DESTRUCTIVE (admission deltas) or partial (one ring's
# counters); a caller that reads one ring outside a fold loop silently
# loses the other rings' counts — exactly the bug class the
# datagrams == toolong + admitted + shed invariant exists to catch.
# Calls to these names must sit inside a for/while fold over the rings;
# `*_one` accessors are exempt BY NAME (the suffix is the documented
# "caller must fold" contract this surface enforces on their callers).
RING_DRAINS = frozenset({
    "vrm_admission_counters", "vrm_counters", "vrm_ring_stats",
    "ring_admission_drain_one", "ring_counters_one", "ring_stats_one",
    # tenant shed/demote deltas ride the same destructive per-ring drain
    # contract: one ring read outside a fold loses the others' counts
    "vrm_tenant_counters", "ring_tenant_drain_one"})
RING_TARGETS = (
    "veneur_tpu/native/__init__.py",
    "veneur_tpu/server/server.py",
    "veneur_tpu/server/native_aggregator.py",
)


def _helper_name(call: ast.Call) -> Optional[str]:
    """Leaf name of a `self.helper(...)`/`helper(...)` call."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self":
        return f.attr
    return None


class _Flow:
    """Accounted-on-every-path analysis over one module."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        # leaf function name -> def node (methods + module functions)
        self.functions: Dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)
        self._helper_cache: Dict[str, bool] = {}

    # -- what counts as accounting ------------------------------------------
    def _accounts_stmt(self, stmt: ast.stmt, depth: int) -> bool:
        """Does executing this one statement guarantee accounting?"""
        if isinstance(stmt, ast.Raise):
            return True
        if isinstance(stmt, ast.AugAssign) \
                and isinstance(stmt.op, ast.Add):
            return True
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute):
                if call.func.attr == "inc" or "bump" in call.func.attr:
                    return True
                if call.func.attr == "append":
                    target = call.func.value
                    name = (target.id if isinstance(target, ast.Name)
                            else target.attr
                            if isinstance(target, ast.Attribute) else "")
                    if any(r in name.lower() for r in _REJECT_NAMES):
                        return True
            if depth < 1:
                helper = _helper_name(call)
                fn = self.functions.get(helper) if helper else None
                if fn is not None and self._helper_accounts(helper, fn):
                    return True
        # a with-statement accounts if its body does on every path
        if isinstance(stmt, ast.With) and stmt.body:
            _, states = self._flow(stmt.body, {False}, [], depth)
            return states == {True}
        return False

    def _helper_accounts(self, name: str, fn) -> bool:
        if name not in self._helper_cache:
            self._helper_cache[name] = False   # break recursion cycles
            viols: List[int] = []
            _, states = self._flow(fn.body, {False}, viols, depth=1)
            self._helper_cache[name] = not viols and states <= {True}
        return self._helper_cache[name]

    # -- the state walk ------------------------------------------------------
    def _flow(self, stmts, states: Set[bool], viols: List[int],
              depth: int) -> tuple:
        """Advance the accounted-state set through a statement list.
        Returns (terminated, out_states); records violation lines for
        exits reachable while unaccounted."""
        for stmt in stmts:
            if not states:
                return True, states     # all paths already exited
            if states == {True}:
                return False, states    # accounted: rest is fine
            if self._accounts_stmt(stmt, depth):
                states = {True}
                continue
            if isinstance(stmt, ast.Return):
                if False in states:
                    viols.append(stmt.lineno)
                return True, set()
            if isinstance(stmt, (ast.Break, ast.Continue)):
                # loop-internal control flow inside the handler: the
                # handler itself continues; treat as a fallthrough
                return True, states
            if isinstance(stmt, ast.If):
                _, s1 = self._flow(stmt.body, set(states), viols, depth)
                _, s2 = self._flow(stmt.orelse, set(states), viols,
                                   depth)
                states = s1 | s2
            elif isinstance(stmt, (ast.For, ast.While)):
                _, s1 = self._flow(stmt.body, set(states), viols, depth)
                states = states | s1    # zero iterations possible
                _, s2 = self._flow(stmt.orelse, set(states), viols,
                                   depth)
                states = states | s2
            elif isinstance(stmt, ast.With):
                _, states = self._flow(stmt.body, states, viols, depth)
            elif isinstance(stmt, ast.Try):
                _, s1 = self._flow(stmt.body, set(states), viols, depth)
                out = set(s1)
                for h in stmt.handlers:
                    _, sh = self._flow(h.body, set(states), viols,
                                       depth)
                    out |= sh
                _, out = self._flow(stmt.orelse, out, viols, depth)
                _, out = self._flow(stmt.finalbody, out, viols, depth)
                states = out
            # plain statements (Assign, Expr, Pass, ...) don't change
            # the accounted state
        return False, states

    def check_handler(self, handler: ast.ExceptHandler,
                      what: str) -> List[Finding]:
        viols: List[int] = []
        _, states = self._flow(handler.body, {False}, viols, depth=0)
        findings = [
            Finding(NAME, self.ctx.rel, line,
                    f"{what} exits here on a branch that never "
                    "accounted the discarded data")
            for line in viols]
        if False in states:
            findings.append(Finding(
                NAME, self.ctx.rel, handler.lineno,
                f"{what} can fall through without accounting on at "
                "least one branch"))
        return findings


def _call_leaf(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _walk_shallow(node, root):
    """ast.walk that does NOT descend into nested function defs (each
    def is analyzed as its own fold scope)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if n is not root and isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _ring_fold_findings(ctx: FileContext, drains) -> List[Finding]:
    """Per-ring drain calls outside a for/while fold loop, per function.
    A lone drain reads (or destructively resets) ONE ring where the
    accounting invariant needs the sum over all of them."""
    findings: List[Finding] = []
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name.endswith("_one"):
            continue   # per-ring accessor shim: contract rides the name
        looped = set()
        for node in _walk_shallow(fn, fn):
            if isinstance(node, (ast.For, ast.While)):
                for sub in _walk_shallow(node, fn):
                    if sub is node:
                        continue
                    if isinstance(sub, ast.Call) \
                            and _call_leaf(sub) in drains:
                        looped.add(id(sub))
        for node in _walk_shallow(fn, fn):
            if isinstance(node, ast.Call) and _call_leaf(node) in drains \
                    and id(node) not in looped:
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"per-ring drain `{_call_leaf(node)}` in "
                    f"{fn.name}() outside a fold loop — counters from "
                    "the other rings are lost (sum across all rings)"))
    return findings


def run(project: Project, targets: List[str] = None,
        send_targets: Dict[str, Set[str]] = None,
        ring_targets: List[str] = None,
        ring_drains: Set[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    # surface 1: drop-exception handlers across the ingest/egress tree
    for ctx in project.files(*(targets if targets is not None
                               else drop_accounting.TARGETS)):
        flow = _Flow(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            dropped = [n for n in drop_accounting.exc_names(node)
                       if n in drop_accounting.DROP_EXCS]
            if dropped:
                findings.extend(flow.check_handler(
                    node, f"`except {'/'.join(dropped)}` handler"))
    # surface 2: every handler in the exactly-once send/retry functions
    for rel, funcs in (send_targets if send_targets is not None
                       else ambiguous_paths.TARGETS).items():
        ctx = project.file(rel)
        if ctx is None:
            continue   # ambiguous-paths already reports the miss
        flow = _Flow(ctx)
        for fname, handler in ambiguous_paths._function_handlers(
                ctx.tree, funcs):
            findings.extend(flow.check_handler(
                handler, f"except in {fname}()"))
    # surface 3: per-ring counter drains must fold across all rings
    drains = ring_drains if ring_drains is not None else RING_DRAINS
    for ctx in project.files(*(ring_targets if ring_targets is not None
                               else RING_TARGETS)):
        findings.extend(_ring_fold_findings(ctx, drains))
    return findings
