"""vtlint pass: derived lock guards — no read-modify-write of a
lock-guarded attribute outside its lock.

The PR 2 race, generalized: `self.imported_total += 1` from two threads
loses increments because `+=` on an attribute is a read-modify-write.
Rather than asking every class to declare its locking contract, this
pass DERIVES it from the code the way a reviewer would:

  1. a lock attribute is any `self.X = threading.Lock()/RLock()/
     Condition()` assignment (alias-aware);
  2. an attribute is *guarded by* lock X when any method touches it
     inside `with self.X:` — the class itself claims X protects it;
  3. methods named `*_locked` inherit the locks held at their lexical
     `self._foo_locked()` call sites (the caller-holds-the-lock
     convention used by ForwardSpillBuffer._evict_locked and
     DedupWindow._verdict_locked);
  4. a read-modify-write of a guarded attribute (`self.a += n`,
     `self.a = self.a + n`, `self.a[k] += n`) while holding NONE of its
     guard locks is a lost-update race — flagged.

Nested function definitions reset the held-lock set: a closure defined
under a lock runs later on whatever thread calls it (exactly how
ProxyServer.start's gRPC on_reject callback raced envelope_rejected).

Attributes never touched under any lock derive no guard and are not
flagged — single-writer designs (OverloadController.state, the
aggregator's pipeline-thread counters) stay lint-silent by
construction, no annotations needed.
"""

from __future__ import annotations

import ast
from collections import defaultdict
from typing import Dict, FrozenSet, List, Set

from veneur_tpu.analysis.core import FileContext, Finding, Project

NAME = "lock-discipline"
DOC = ("read-modify-writes of derived lock-guarded attributes happen "
       "under their lock")

# the concurrent surface: every module whose classes share state across
# threads (registry, spill/dedup/overload, proxy, spans, server, the
# aggregators, resilient sinks)
MODULES = [
    "veneur_tpu/observability/registry.py",
    "veneur_tpu/reliability/spill.py",
    "veneur_tpu/reliability/overload.py",
    "veneur_tpu/forward/envelope.py",
    "veneur_tpu/forward/proxysrv.py",
    "veneur_tpu/forward/rpc.py",
    "veneur_tpu/server/spans.py",
    "veneur_tpu/server/server.py",
    "veneur_tpu/server/aggregator.py",
    "veneur_tpu/server/sharded_aggregator.py",
    "veneur_tpu/server/native_aggregator.py",
    "veneur_tpu/sinks/base.py",
]

_LOCK_TYPES = ("threading.Lock", "threading.RLock", "threading.Condition")


def _self_attr(node: ast.AST):
    """'x' for a `self.x` attribute node, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _lock_attrs(cls: ast.ClassDef, ctx: FileContext) -> Set[str]:
    locks = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        resolved = ctx.resolve(value.func)
        if resolved in _LOCK_TYPES or (
                resolved in ("Lock", "RLock", "Condition")):
            for t in node.targets:
                attr = _self_attr(t)
                if attr:
                    locks.add(attr)
    return locks


class _Analyzer:
    """One class's lock analysis: held-set-aware walks over each method,
    with one level of caller-holds propagation into *_locked methods."""

    def __init__(self, ctx: FileContext, cls: ast.ClassDef):
        self.ctx = ctx
        self.cls = cls
        self.locks = _lock_attrs(cls, ctx)
        self.methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # attr -> set of locks some method holds while touching it
        self.guarded: Dict[str, Set[str]] = defaultdict(set)
        # method name -> union of lock sets held at its call sites
        self.locked_callers: Dict[str, Set[str]] = defaultdict(set)
        # (method, lineno, attr, held) read-modify-write sites
        self.rmw_sites: List[tuple] = []

    # -- phase 1: walk every method, recording accesses + RMWs --------------
    def _with_locks(self, stmt: ast.With) -> Set[str]:
        held = set()
        for item in stmt.items:
            attr = _self_attr(item.context_expr)
            if attr in self.locks:
                held.add(attr)
        return held

    def _record_access(self, node: ast.AST, held: FrozenSet[str]) -> None:
        attr = _self_attr(node)
        if attr and attr not in self.locks and held:
            self.guarded[attr] |= held

    def _rmw_attr(self, stmt: ast.stmt):
        """The self-attribute a statement read-modify-writes, or None."""
        if isinstance(stmt, ast.AugAssign):
            t = stmt.target
            if isinstance(t, ast.Subscript):
                t = t.value
            return _self_attr(t)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            attr = _self_attr(stmt.targets[0])
            if attr and any(_self_attr(n) == attr
                            for n in ast.walk(stmt.value)):
                return attr
        return None

    def _scan_expr(self, method: str, node: ast.AST,
                   held: FrozenSet[str]) -> None:
        if isinstance(node, ast.Lambda):
            # a lambda runs later, on an unknown thread, with no lock
            self._scan_expr(method, node.body, frozenset())
            return
        self._record_access(node, held)
        if isinstance(node, ast.Call):
            callee = _self_attr(node.func)
            if callee and callee.endswith("_locked") and held:
                self.locked_callers[callee] |= held
        for child in ast.iter_child_nodes(node):
            self._scan_expr(method, child, held)

    def _walk_body(self, stmts, method: str,
                   held: FrozenSet[str]) -> None:
        # statements with bodies need held-set threading; expressions
        # are scanned flat
        for stmt in stmts:
            rmw = self._rmw_attr(stmt)
            if rmw and rmw not in self.locks:
                self.rmw_sites.append((method, stmt.lineno, rmw, held))
            if isinstance(stmt, ast.With):
                inner = frozenset(held | self._with_locks(stmt))
                for item in stmt.items:
                    self._scan_expr(method, item.context_expr, held)
                self._walk_body(stmt.body, method, inner)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self._walk_body(stmt.body, method, frozenset())
            elif isinstance(stmt, (ast.If, ast.While)):
                self._scan_expr(method, stmt.test, held)
                self._walk_body(stmt.body, method, held)
                self._walk_body(stmt.orelse, method, held)
            elif isinstance(stmt, ast.For):
                self._scan_expr(method, stmt.target, held)
                self._scan_expr(method, stmt.iter, held)
                self._walk_body(stmt.body, method, held)
                self._walk_body(stmt.orelse, method, held)
            elif isinstance(stmt, ast.Try):
                self._walk_body(stmt.body, method, held)
                for h in stmt.handlers:
                    self._walk_body(h.body, method, held)
                self._walk_body(stmt.orelse, method, held)
                self._walk_body(stmt.finalbody, method, held)
            else:
                self._scan_expr(method, stmt, held)

    def analyze(self) -> List[Finding]:
        if not self.locks:
            return []
        for name, fn in self.methods.items():
            self._walk_body(fn.body, name, frozenset())
        # phase 2: *_locked methods re-walk under their callers' locks
        # (one level: enough for the _evict_locked/_verdict_locked
        # convention without whole-program call-graph analysis)
        for name, held in self.locked_callers.items():
            fn = self.methods.get(name)
            if fn is not None:
                self._walk_body(fn.body, name, frozenset(held))

        findings = []
        for method, lineno, attr, held in self.rmw_sites:
            if method in ("__init__", "__del__"):
                continue   # construction/teardown: no concurrency yet
            guards = self.guarded.get(attr)
            if not guards:
                continue   # never touched under a lock: no derived claim
            if held & guards:
                continue
            if method.endswith("_locked") \
                    and self.locked_callers.get(method, set()) & guards:
                continue   # caller holds the guard by convention
            lock_names = ", ".join(sorted(guards))
            findings.append(Finding(
                NAME, self.ctx.rel, lineno,
                f"{self.cls.name}.{method}() read-modify-writes "
                f"self.{attr} without a lock, but other code guards it "
                f"with self.{lock_names} — lost-update race (take the "
                "lock, or route the counter through TelemetryRegistry)"))
        return findings


def run(project: Project, modules: List[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for rel in (modules or MODULES):
        ctx = project.file(rel)
        if ctx is None:
            findings.append(Finding(
                NAME, rel, 0, "file missing — update MODULES"))
            continue
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_Analyzer(ctx, node).analyze())
    return findings
