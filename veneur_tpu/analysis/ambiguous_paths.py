"""vtlint pass: forward send/retry failure paths preserve exactly-once.

Port of scripts/check_ambiguous_paths.py. The exactly-once contract
(forward/envelope.py) hangs on one discipline in the send/retry code: a
failed or AMBIGUOUS send must leave the unit staged under its ORIGINAL
(source_id, epoch, seq) so the retry re-sends the same envelope and the
receiver's dedup window can suppress it.

1. Every except handler in the named send/retry functions must account
   its failure (raise / `.inc()` / `+=`). The accounting-flow pass
   additionally holds these handlers to the every-path standard.
2. No except handler may fake an ack or evict staged state
   (`.ack/.drain/.popleft/.clear` and `return True` are forbidden).
3. forward/rpc.py's _AMBIGUOUS_CODES must keep DEADLINE_EXCEEDED and
   CANCELLED, and AmbiguousResultError must still be raised there.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from veneur_tpu.analysis.core import Finding, Project
from veneur_tpu.analysis.drop_accounting import accounts_anywhere

NAME = "ambiguous-paths"
DOC = ("send/retry except arms never fake an ack or evict staged "
       "state; ambiguous-result classification stays put")

# (file, function names lexically containing send/retry except arms)
TARGETS: Dict[str, Set[str]] = {
    "veneur_tpu/forward/rpc.py": {
        "send_metrics", "send_serialized", "send_json", "_post"},
    "veneur_tpu/server/server.py": {
        "_forward", "_forward_traced", "_send_forward",
        "_stage_forward_unit", "_pump_forward_units", "_pump_traced"},
    "veneur_tpu/forward/proxysrv.py": {
        "handle", "_deliver_enveloped", "proxy_json_metrics",
        "_post_import"},
}

RPC_FILE = "veneur_tpu/forward/rpc.py"

# calls that evict/ack staged send state; illegal in a failure arm
_EVICT_CALLS = ("ack", "drain", "popleft", "clear")


def _evicts_or_acks(handler: ast.ExceptHandler):
    """Offending nodes: spill/window eviction calls or `return True`
    (a fabricated ack) anywhere in the handler body."""
    bad = []
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _EVICT_CALLS):
            bad.append((node.lineno, f".{node.func.attr}(...)"))
        if (isinstance(node, ast.Return)
                and isinstance(node.value, ast.Constant)
                and node.value.value is True):
            bad.append((node.lineno, "return True"))
    return bad


def _function_handlers(tree: ast.AST, wanted: Set[str]):
    """Yield (funcname, ExceptHandler) for handlers lexically inside the
    wanted function defs (nested defs inherit the enclosing name)."""
    for node in ast.walk(tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in wanted):
            for sub in ast.walk(node):
                if isinstance(sub, ast.ExceptHandler):
                    yield node.name, sub


def _present_functions(tree: ast.AST, wanted: Set[str]) -> Set[str]:
    present = set()
    for node in ast.walk(tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in wanted):
            present.add(node.name)
    return present


def _check_classification(project: Project, rpc_rel: str) -> List[Finding]:
    """Rule 3: rpc.py still classifies DEADLINE_EXCEEDED/CANCELLED as
    ambiguous and raises AmbiguousResultError somewhere."""
    ctx = project.file(rpc_rel)
    if ctx is None:
        return [Finding(NAME, rpc_rel, 0, "file missing — update TARGETS")]
    findings = []
    codes = set()
    raises_ambiguous = False
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            if "_AMBIGUOUS_CODES" in targets and isinstance(
                    node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Attribute):
                        codes.add(elt.attr)
        if isinstance(node, ast.Raise) and node.exc is not None:
            call = node.exc
            name = (call.func if isinstance(call, ast.Call) else call)
            if (isinstance(name, ast.Name)
                    and name.id == "AmbiguousResultError"):
                raises_ambiguous = True
    for want in ("DEADLINE_EXCEEDED", "CANCELLED"):
        if want not in codes:
            findings.append(Finding(
                NAME, rpc_rel, 0,
                f"_AMBIGUOUS_CODES no longer includes {want} — "
                "ambiguous timeouts would re-send under a fresh seq "
                "and double-fold at the global tier"))
    if not raises_ambiguous:
        findings.append(Finding(
            NAME, rpc_rel, 0,
            "AmbiguousResultError is never raised — the ambiguous "
            "classification regressed"))
    return findings


def run(project: Project, targets: Dict[str, Set[str]] = None,
        rpc_rel: str = None) -> List[Finding]:
    findings: List[Finding] = []
    for rel, funcs in (targets or TARGETS).items():
        ctx = project.file(rel)
        if ctx is None:
            findings.append(Finding(
                NAME, rel, 0, "file missing — update TARGETS"))
            continue
        seen = set()
        for fname, handler in _function_handlers(ctx.tree, funcs):
            seen.add(fname)
            if not accounts_anywhere(handler):
                findings.append(Finding(
                    NAME, rel, handler.lineno,
                    f"except in {fname}() swallows a send failure "
                    "without raise/.inc()/+="))
            for lineno, what in _evicts_or_acks(handler):
                findings.append(Finding(
                    NAME, rel, lineno,
                    f"except in {fname}() contains {what} — a failure "
                    "arm must not ack or evict the staged unit (retry "
                    "must re-send the same seq)"))
        # functions with no handler are fine (all errors propagate =
        # re-send same seq) but must still EXIST so a rename doesn't
        # silently shrink the lint surface
        missing = funcs - _present_functions(ctx.tree, funcs)
        for fname in sorted(missing):
            findings.append(Finding(
                NAME, rel, 0,
                f"expected function {fname}() not found — update "
                "veneur_tpu/analysis/ambiguous_paths.py TARGETS if it "
                "moved"))
    if rpc_rel is None and targets is None:
        rpc_rel = RPC_FILE
    if rpc_rel is not None:
        findings.extend(_check_classification(project, rpc_rel))
    return findings
