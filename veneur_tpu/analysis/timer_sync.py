"""vtlint pass: wall-time deltas around device dispatch must sync.

JAX dispatch is async: `time.perf_counter_ns()` deltas taken around a
bare step call measure the host-side ENQUEUE cost (microseconds), not
the device work — the exact bug class behind the old step_ns
accounting, where "device time" collapsed to dispatch time and the
real cost surfaced later as a mystery stall in whoever synced first.

The rule: inside the warm dispatch files, a `t = perf_counter_ns()` /
`... perf_counter_ns() - t` pair with a device-tainted call between
the two timestamps must also have a sync (`block_until_ready` or
`jaxruntime.sync_and_time`) between them — OR store the delta under a
name containing `dispatch`, which declares the enqueue-only meaning
explicitly (the `dispatch_dt` convention the aggregators use).

The taint walk is jax_hot_path's (`state` roots + jax.* results +
assignment growth); a measurement this pass cannot see through (e.g. a
callee that host-materializes, which IS an implicit sync) carries a
one-line reasoned suppression.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from veneur_tpu.analysis.core import FileContext, Finding, Project
from veneur_tpu.analysis.jax_hot_path import _is_tainted

NAME = "timer-sync"
DOC = ("perf_counter_ns deltas spanning device dispatch either sync "
       "(block_until_ready / sync_and_time) or are named dispatch_*")

# the warm dispatch files: everywhere a perf_counter pair can wrap a
# jitted step call. server.py's flush phases are out of scope — its
# compute_flush callees host-materialize (an implicit sync) and the
# phases deliberately measure mixed host+device wall time.
FILES = [
    "veneur_tpu/server/native_aggregator.py",
    "veneur_tpu/server/aggregator.py",
    "veneur_tpu/server/sharded_aggregator.py",
    "veneur_tpu/collective/tier.py",
    "veneur_tpu/query/engine.py",
    "veneur_tpu/watch/engine.py",
    "veneur_tpu/history/writer.py",
]

_SYNC_LEAVES = ("block_until_ready", "sync_and_time")


def _is_pcns(node: ast.AST, ctx: FileContext) -> bool:
    """Is this expression a bare time.perf_counter_ns() call?"""
    if not isinstance(node, ast.Call):
        return False
    resolved = ctx.resolve(node.func) or ""
    return resolved.rsplit(".", 1)[-1] == "perf_counter_ns"


def _target_name(node: ast.AST,
                 parents: Dict[ast.AST, ast.AST],
                 ctx: FileContext) -> Optional[str]:
    """The name the enclosing Assign/AugAssign stores into, or None
    when the delta feeds straight into a call (observe(...))."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.AugAssign):
            t = cur.target
            return ctx.dotted(t) if isinstance(t, ast.Attribute) \
                else getattr(t, "id", None)
        if isinstance(cur, ast.Assign):
            for t in cur.targets:
                if isinstance(t, ast.Name):
                    return t.id
                if isinstance(t, ast.Attribute):
                    return ctx.dotted(t)
            return None
        cur = parents.get(cur)
    return None


def _check_fn(ctx: FileContext, fn) -> List[Finding]:
    tainted: Set[str] = set()
    for arg in fn.args.args:
        if arg.arg == "state":
            tainted.add("state")
    parents: Dict[ast.AST, ast.AST] = {}
    for p in ast.walk(fn):
        for c in ast.iter_child_nodes(p):
            parents[c] = p

    t0s: Dict[str, int] = {}
    device_calls: List[int] = []
    syncs: List[int] = []
    deltas: List[Tuple[int, str, Optional[str]]] = []

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            if _is_pcns(node.value, ctx):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        t0s[t.id] = node.lineno
            elif _is_tainted(node.value, ctx, tainted):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
        elif isinstance(node, ast.Call):
            fname = node.func
            resolved = ctx.resolve(fname) or ""
            leaf = resolved.rsplit(".", 1)[-1]
            if leaf in _SYNC_LEAVES or (
                    isinstance(fname, ast.Attribute)
                    and fname.attr in _SYNC_LEAVES):
                syncs.append(node.lineno)
            elif not _is_pcns(node, ctx) \
                    and _is_tainted(node, ctx, tainted):
                device_calls.append(node.lineno)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                and _is_pcns(node.left, ctx) \
                and isinstance(node.right, ast.Name):
            deltas.append((node.lineno, node.right.id,
                           _target_name(node, parents, ctx)))

    findings: List[Finding] = []
    for lineno, t0_name, target in deltas:
        start = t0s.get(t0_name)
        if start is None or lineno <= start:
            continue
        if target is not None and "dispatch" in target:
            continue  # declared enqueue-only measurement
        spanned = [l for l in device_calls if start < l < lineno]
        if not spanned:
            continue
        if any(start < l < lineno for l in syncs):
            continue
        findings.append(Finding(
            NAME, ctx.rel, lineno,
            f"perf_counter_ns delta in {fn.name}() spans a device "
            f"dispatch (line {spanned[0]}) with no block_until_ready/"
            "sync_and_time before the second timestamp — this measures "
            "async enqueue cost, not device work; sync inside the "
            "range, or store it as dispatch_* if enqueue time is "
            "the point"))
    return findings


def run(project: Project, files: List[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for rel in (files if files is not None else FILES):
        ctx = project.file(rel)
        if ctx is None:
            findings.append(Finding(
                NAME, rel, 0, "file missing — update FILES in "
                "veneur_tpu/analysis/timer_sync.py"))
            continue
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for f in _check_fn(ctx, node):
                    key = (f.file, f.line)
                    if key not in seen:  # nested defs are walked twice
                        seen.add(key)
                        findings.append(f)
    return findings
