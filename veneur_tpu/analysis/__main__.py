"""CLI: python -m veneur_tpu.analysis [--all | PASS ...] [--json]
[--list] [--root DIR]."""

from __future__ import annotations

import argparse
import sys

from veneur_tpu.analysis import PASSES, run_cli


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m veneur_tpu.analysis",
        description="vtlint: unified static analysis for veneur-tpu")
    ap.add_argument("passes", nargs="*", metavar="PASS",
                    help="pass names to run (see --list)")
    ap.add_argument("--all", action="store_true",
                    help="run every registered pass")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("--root", default=None,
                    help="project root (default: this repo)")
    args = ap.parse_args(argv)

    if args.list:
        for name, mod in PASSES.items():
            print(f"{name:16s} {mod.DOC}")
        return 0
    if args.all:
        names = list(PASSES)
    else:
        names = args.passes
    if not names:
        ap.error("give pass names, or --all / --list")
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        ap.error(f"unknown pass(es): {', '.join(unknown)} "
                 "(see --list)")
    return run_cli(names, root=args.root, as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
