"""vtlint pass: no hidden host syncs or jit-boundary hazards in the
warm per-batch/per-flush functions.

The ingest arc's perf contract: once warm, a batch crosses the host ->
device boundary exactly once (the packed h2d feed) and nothing on the
pipeline thread ever waits on the device. Three regression classes this
pass catches mechanically:

1. **Implicit host syncs on device values** — `float()` / `int()` /
   `bool()` / `np.asarray()` / `np.array()` / `.item()` / `.tolist()`
   applied to a traced or device-derived value blocks the caller until
   every queued device computation lands (the exact bug fixed in
   sharded _apply_hll_imports: `np.array(self.state.hll)` stalled
   swap() — and therefore ingest — behind the full step backlog).
   Host-side numpy values are fine; a cheap taint walk tells them
   apart: device roots are `self.state` / a `state` parameter, any
   `jax.*`/`jax.numpy.*` call result, and locals assigned from either.
2. **Python branching on traced values** — an `if`/`while` whose test
   touches a device value is a host sync in disguise.
3. **Jit-boundary hazards** — `jax.block_until_ready` in production
   code (bench/deliberate drain points carry a reasoned suppression);
   the donating jit wrappers losing their `donate_argnums`/
   `donate_argnames` (the donation contract the double-buffered packed
   feed depends on — without it every step copies DeviceState); and
   call sites passing list/dict/set literals for the static `spec`/
   `sizes` args of the jitted family (unhashable statics throw at
   trace time; a fresh tuple per call recompiles).
4. **Host code inside Pallas kernels** — the body of any function
   handed to `pl.pallas_call` is device code: every parameter is a Ref
   (or a value loaded from one), so a Python `if`/`while` on one, or a
   `float()`/`np.asarray()`/`.item()` host conversion, either fails at
   trace time on TPU or — worse — silently "works" in interpret mode
   and then diverges on hardware. Structured control flow belongs in
   `@pl.when` / `lax.cond` / `lax.fori_loop`. Kernels are resolved
   from the call site (a bare name or `functools.partial(name, ...)`)
   so nested closure kernels are scanned too; keyword-only kernel
   params are treated as host statics (the `functools.partial`
   convention) and stay untainted.
5. **Host code inside shard_map bodies** — a function handed to
   `shard_map` traces per-device-tile exactly like a kernel: every
   positional parameter is a device shard, so the same host-sync and
   traced-branching rules apply. Additionally, every collective in a
   body must name its mesh axis: `lax.psum(x, ...)`-family calls with
   the axis argument MISSING rely on implicit axis context that does
   not exist under shard_map (trace-time error at best), and a bare
   NUMERIC axis silently means a positional array axis on several of
   these APIs — the reduce happens inside one shard instead of across
   the mesh. A string literal or a named constant (REPLICA_AXIS /
   SHARD_AXIS) passes.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from veneur_tpu.analysis.core import FileContext, Finding, Project

NAME = "jax-hot-path"
DOC = ("warm per-batch/per-flush functions contain no implicit host "
       "syncs, traced-value branching, or jit-boundary hazards")

# the hot-path-alloc set, extended with the per-flush warm paths that
# run on (and block) the pipeline thread
HOT_FUNCS: Dict[str, List[str]] = {
    "veneur_tpu/server/native_aggregator.py": [
        "_emit_native", "feed", "pump", "_split_shards"],
    "veneur_tpu/aggregation/step.py": ["pack_batch"],
    "veneur_tpu/server/aggregator.py": [
        "_on_batch", "_flush_hll_imports", "swap", "query_snapshot"],
    "veneur_tpu/server/sharded_aggregator.py": [
        "_dispatch_row", "_on_shard_batch", "_emit_all",
        "_apply_hll_imports", "swap", "query_snapshot"],
    "veneur_tpu/collective/tier.py": [
        "_dispatch_row", "_dispatch_routed", "_on_stage_batch",
        "absorb_raw", "swap", "query_snapshot"],
    "veneur_tpu/query/engine.py": [
        "_launch", "_launch_on_pipeline", "_launch_combined"],
    # history ring maintenance runs inside the flush's dispatch window
    # on the pipeline/flush thread: a hidden sync here stalls swap()
    "veneur_tpu/history/writer.py": [
        "begin_flush", "commit_flush", "_roll", "record_frame"],
}

# named jit wrappers that MUST donate their state argument: dropping
# donate_argnums/donate_argnames silently doubles per-step HBM traffic
DONATING_JITS: Dict[str, List[str]] = {
    "veneur_tpu/aggregation/step.py": [
        "ingest_step", "ingest_step_packed", "compact"],
    # the ring mutators update HistoryState in place; losing donation
    # doubles the history tier's HBM footprint per flush
    "veneur_tpu/history/device.py": [
        "write_window", "wipe_rows", "roll_tiers"],
}

# static parameters of the jitted family: a list/dict/set literal here
# is unhashable (TypeError at trace time)
STATIC_ARG_NAMES = ("spec", "sizes", "hspec")
JITTED_CALLEES = ("ingest_step", "packed_step", "compact",
                  "flush_compute", "quantile_compute",
                  "write_window", "wipe_rows", "roll_tiers",
                  "range_in_packed", "query_combined")

# files scanned for stray block_until_ready (bench code lives under
# benchmarks/ and is out of scope by construction); the Pallas-kernel
# scan follows the same list unless overridden
SYNC_SCAN = ["veneur_tpu"]

_HOST_CONVERTERS = ("float", "int", "bool")
_NP_CONVERTERS = ("numpy.asarray", "numpy.array")
_SYNC_METHODS = ("item", "tolist")


def _is_tainted(node: ast.AST, ctx: FileContext,
                tainted: Set[str]) -> bool:
    """Does this expression derive from a device value?"""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        # self.state (and anything hanging off it) is the device root
        if ctx.dotted(node) in ("self.state", "state"):
            return True
        return _is_tainted(node.value, ctx, tainted)
    if isinstance(node, ast.Subscript):
        return _is_tainted(node.value, ctx, tainted)
    if isinstance(node, ast.Call):
        fn = node.func
        resolved = ctx.resolve(fn)
        if resolved and (resolved.startswith("jax.numpy.")
                         or resolved.startswith("jax.")):
            return True
        # method call on a tainted object stays tainted
        # (state.hll.at[...].max(rows), self._ingest(self.state, ...))
        if isinstance(fn, ast.Attribute) \
                and _is_tainted(fn.value, ctx, tainted):
            return True
        return any(_is_tainted(a, ctx, tainted) for a in node.args)
    if isinstance(node, ast.BinOp):
        return (_is_tainted(node.left, ctx, tainted)
                or _is_tainted(node.right, ctx, tainted))
    if isinstance(node, (ast.Compare,)):
        return (_is_tainted(node.left, ctx, tainted)
                or any(_is_tainted(c, ctx, tainted)
                       for c in node.comparators))
    if isinstance(node, ast.UnaryOp):
        return _is_tainted(node.operand, ctx, tainted)
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_is_tainted(e, ctx, tainted) for e in node.elts)
    return False


def _check_hot_fn(ctx: FileContext, fn) -> List[Finding]:
    findings: List[Finding] = []
    tainted: Set[str] = set()
    # a parameter literally named `state` is device state by convention
    for arg in fn.args.args:
        if arg.arg == "state":
            tainted.add("state")

    for node in ast.walk(fn):
        # grow the taint set: locals assigned from device expressions
        if isinstance(node, ast.Assign) \
                and _is_tainted(node.value, ctx, tainted):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    tainted.add(t.id)
        elif isinstance(node, (ast.If, ast.While)) \
                and _is_tainted(node.test, ctx, tainted):
            findings.append(Finding(
                NAME, ctx.rel, node.lineno,
                f"Python branch on a traced/device value in hot "
                f"function {fn.name}() — forces a blocking "
                "device->host sync per batch; compute the predicate "
                "on host state or inside the jitted step"))
        elif isinstance(node, ast.Call):
            fname = node.func
            resolved = ctx.resolve(fname)
            if resolved in _HOST_CONVERTERS and len(node.args) >= 1 \
                    and _is_tainted(node.args[0], ctx, tainted):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`{resolved}()` on a device value in hot function "
                    f"{fn.name}() — implicit blocking transfer"))
            elif resolved in _NP_CONVERTERS and node.args \
                    and _is_tainted(node.args[0], ctx, tainted):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`{resolved.replace('numpy', 'np')}` on a device "
                    f"value in hot function {fn.name}() — full "
                    "device->host materialization blocks on every "
                    "queued step; keep the merge on device"))
            elif isinstance(fname, ast.Attribute) \
                    and fname.attr in _SYNC_METHODS \
                    and _is_tainted(fname.value, ctx, tainted):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`.{fname.attr}()` on a device value in hot "
                    f"function {fn.name}() — implicit blocking "
                    "transfer"))
    return findings


def _check_jit_decls(project: Project,
                     donating: Dict[str, List[str]]) -> List[Finding]:
    findings: List[Finding] = []
    for rel, names in donating.items():
        ctx = project.file(rel)
        if ctx is None:
            findings.append(Finding(
                NAME, rel, 0, "file missing — update DONATING_JITS"))
            continue
        seen = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in names:
                        seen[t.id] = node
        for name in names:
            node = seen.get(name)
            if node is None:
                findings.append(Finding(
                    NAME, rel, 0,
                    f"donating jit wrapper {name} not found — renamed? "
                    "update DONATING_JITS in veneur_tpu/analysis/"
                    "jax_hot_path.py"))
                continue
            donates = any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for call in ast.walk(node.value)
                if isinstance(call, ast.Call)
                for kw in call.keywords)
            if not donates:
                findings.append(Finding(
                    NAME, rel, node.lineno,
                    f"{name} lost its donate_argnums/donate_argnames — "
                    "the packed feed's in-place DeviceState update "
                    "becomes a full copy per step"))
    return findings


def _check_static_args(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    unhashable = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                  ast.DictComp, ast.SetComp)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve(node.func)
        leaf = (resolved or "").rsplit(".", 1)[-1]
        if leaf not in JITTED_CALLEES:
            continue
        for kw in node.keywords:
            if kw.arg in STATIC_ARG_NAMES \
                    and isinstance(kw.value, unhashable):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"{leaf}({kw.arg}=...) passes an unhashable "
                    f"{type(kw.value).__name__.lower()} literal for a "
                    "static jit arg — TypeError at trace time; pass a "
                    "hashable (tuple/NamedTuple) spec"))
    return findings


def _check_block_until_ready(ctx: FileContext) -> List[Finding]:
    findings = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "block_until_ready":
            findings.append(Finding(
                NAME, ctx.rel, node.lineno,
                "block_until_ready outside bench code — a deliberate "
                "full-device drain; if intended, suppress with a "
                "reason"))
    return findings


def _kernel_def(ctx: FileContext, call: ast.Call):
    """Resolve `pl.pallas_call(<kernel>, ...)`'s first positional arg
    to a FunctionDef in this file. Handles a bare name and the
    `functools.partial(name, ...)` static-binding idiom; anything else
    (lambda, attribute on another module) is skipped — kernels in this
    codebase are always file-local by construction."""
    if not call.args:
        return None
    target = call.args[0]
    if isinstance(target, ast.Call):
        resolved = ctx.resolve(target.func)
        if (resolved or "").rsplit(".", 1)[-1] == "partial" \
                and target.args:
            target = target.args[0]
    if not isinstance(target, ast.Name):
        return None
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef) and node.name == target.id:
            return node
    return None


def _check_kernel_body(ctx: FileContext, fn) -> List[Finding]:
    """Treat a pallas_call body as device code: every positional param
    is a Ref, so the _is_tainted walk starts fully tainted. Keyword-only
    params are host statics bound via functools.partial (Python `for`
    over them unrolls at trace time and is fine; only `if`/`while` on
    Ref-derived values are syncs-in-disguise)."""
    findings: List[Finding] = []
    tainted: Set[str] = set()
    for arg in list(fn.args.posonlyargs) + list(fn.args.args):
        tainted.add(arg.arg)
    if fn.args.vararg is not None:
        tainted.add(fn.args.vararg.arg)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) \
                and _is_tainted(node.value, ctx, tainted):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    tainted.add(t.id)
        elif isinstance(node, (ast.If, ast.While)) \
                and _is_tainted(node.test, ctx, tainted):
            kind = "if" if isinstance(node, ast.If) else "while"
            findings.append(Finding(
                NAME, ctx.rel, node.lineno,
                f"Python `{kind}` on a Ref-derived value inside Pallas "
                f"kernel {fn.name}() — kernels trace once; use "
                "@pl.when / lax.cond / lax.fori_loop for data-dependent "
                "control flow"))
        elif isinstance(node, ast.Call):
            fname = node.func
            resolved = ctx.resolve(fname)
            if resolved in _HOST_CONVERTERS and len(node.args) >= 1 \
                    and _is_tainted(node.args[0], ctx, tainted):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`{resolved}()` on a Ref-derived value inside "
                    f"Pallas kernel {fn.name}() — host conversion in "
                    "device code fails on TPU (and silently diverges "
                    "in interpret mode)"))
            elif resolved in _NP_CONVERTERS and node.args \
                    and _is_tainted(node.args[0], ctx, tainted):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`{resolved.replace('numpy', 'np')}` on a "
                    f"Ref-derived value inside Pallas kernel "
                    f"{fn.name}() — host materialization in device "
                    "code; keep the computation in jnp"))
            elif isinstance(fname, ast.Attribute) \
                    and fname.attr in _SYNC_METHODS \
                    and _is_tainted(fname.value, ctx, tainted):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`.{fname.attr}()` on a Ref-derived value inside "
                    f"Pallas kernel {fn.name}() — host sync in device "
                    "code"))
    return findings


def _check_pallas_kernels(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    checked = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve(node.func)
        if (resolved or "").rsplit(".", 1)[-1] != "pallas_call":
            continue
        kernel = _kernel_def(ctx, node)
        if kernel is None or id(kernel) in checked:
            continue
        checked.add(id(kernel))
        findings.extend(_check_kernel_body(ctx, kernel))
    return findings


# lax collectives and the positional index of their axis-name argument;
# axis_index takes it first, the reducers/permuters take it second
_COLLECTIVE_AXIS_ARG = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "all_gather": 1,
    "all_to_all": 1, "psum_scatter": 1, "ppermute": 1, "axis_index": 0,
}


def _axis_arg_ok(axis: ast.AST) -> bool:
    """A collective axis must be NAMED: a string literal, a variable /
    attribute holding one (REPLICA_AXIS), or a tuple of those. A
    numeric literal is a positional-array-axis footgun."""
    if isinstance(axis, ast.Constant):
        return isinstance(axis.value, str)
    if isinstance(axis, (ast.Name, ast.Attribute)):
        return True
    if isinstance(axis, (ast.Tuple, ast.List)):
        return bool(axis.elts) and all(_axis_arg_ok(e) for e in axis.elts)
    return False


def _check_collective_axes(ctx: FileContext, fn) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve(node.func)
        leaf = (resolved or "").rsplit(".", 1)[-1]
        idx = _COLLECTIVE_AXIS_ARG.get(leaf)
        if idx is None:
            continue
        axis = None
        if len(node.args) > idx:
            axis = node.args[idx]
        else:
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis"):
                    axis = kw.value
                    break
        if axis is None:
            findings.append(Finding(
                NAME, ctx.rel, node.lineno,
                f"`{leaf}` inside shard_map body {fn.name}() names no "
                "mesh axis — shard_map bodies have no implicit axis "
                "context; pass the axis name (REPLICA_AXIS/SHARD_AXIS)"))
        elif not _axis_arg_ok(axis):
            findings.append(Finding(
                NAME, ctx.rel, node.lineno,
                f"`{leaf}` inside shard_map body {fn.name}() passes a "
                "non-name axis argument — a numeric axis means a "
                "positional array axis, reducing WITHIN one shard "
                "instead of across the mesh; use the mesh axis name"))
    return findings


def _check_shard_map_body(ctx: FileContext, fn) -> List[Finding]:
    """A shard_map body is device code: every positional param is a
    per-tile shard, so the kernel taint walk applies verbatim — plus
    the named-collective-axis rule."""
    findings: List[Finding] = []
    tainted: Set[str] = set()
    for arg in list(fn.args.posonlyargs) + list(fn.args.args):
        tainted.add(arg.arg)
    if fn.args.vararg is not None:
        tainted.add(fn.args.vararg.arg)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) \
                and _is_tainted(node.value, ctx, tainted):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    tainted.add(t.id)
        elif isinstance(node, (ast.If, ast.While)) \
                and _is_tainted(node.test, ctx, tainted):
            kind = "if" if isinstance(node, ast.If) else "while"
            findings.append(Finding(
                NAME, ctx.rel, node.lineno,
                f"Python `{kind}` on a device value inside shard_map "
                f"body {fn.name}() — the body traces once per tile; "
                "use lax.cond / lax.fori_loop for data-dependent "
                "control flow"))
        elif isinstance(node, ast.Call):
            fname = node.func
            resolved = ctx.resolve(fname)
            if resolved in _HOST_CONVERTERS and len(node.args) >= 1 \
                    and _is_tainted(node.args[0], ctx, tainted):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`{resolved}()` on a device value inside shard_map "
                    f"body {fn.name}() — host conversion in device "
                    "code fails at trace time"))
            elif resolved in _NP_CONVERTERS and node.args \
                    and _is_tainted(node.args[0], ctx, tainted):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`{resolved.replace('numpy', 'np')}` on a device "
                    f"value inside shard_map body {fn.name}() — host "
                    "materialization in device code; keep the merge "
                    "in jnp"))
            elif isinstance(fname, ast.Attribute) \
                    and fname.attr in _SYNC_METHODS \
                    and _is_tainted(fname.value, ctx, tainted):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`.{fname.attr}()` on a device value inside "
                    f"shard_map body {fn.name}() — host sync in device "
                    "code"))
    findings.extend(_check_collective_axes(ctx, fn))
    return findings


def _check_shard_maps(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    checked = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve(node.func)
        if (resolved or "").rsplit(".", 1)[-1] != "shard_map":
            continue
        # same call-site resolution as kernels: a bare name or
        # functools.partial(name, ...) defined anywhere in this file
        body = _kernel_def(ctx, node)
        if body is None or id(body) in checked:
            continue
        checked.add(id(body))
        findings.extend(_check_shard_map_body(ctx, body))
    return findings


def run(project: Project, hot_funcs: Dict[str, List[str]] = None,
        donating_jits: Dict[str, List[str]] = None,
        sync_scan: List[str] = None,
        pallas_scan: List[str] = None,
        shard_map_scan: List[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for rel, funcs in (hot_funcs if hot_funcs is not None
                       else HOT_FUNCS).items():
        ctx = project.file(rel)
        if ctx is None:
            findings.append(Finding(
                NAME, rel, 0, "file missing — update HOT_FUNCS"))
            continue
        seen = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name in funcs):
                seen.add(node.name)
                findings.extend(_check_hot_fn(ctx, node))
        for name in funcs:
            if name not in seen:
                findings.append(Finding(
                    NAME, rel, 0,
                    f"hot function {name}() not found — renamed? "
                    "update HOT_FUNCS in veneur_tpu/analysis/"
                    "jax_hot_path.py"))
        findings.extend(_check_static_args(ctx))
    findings.extend(_check_jit_decls(
        project, donating_jits if donating_jits is not None
        else DONATING_JITS))
    scan = sync_scan if sync_scan is not None else SYNC_SCAN
    for ctx in project.files(*scan):
        findings.extend(_check_block_until_ready(ctx))
    for ctx in project.files(*(pallas_scan if pallas_scan is not None
                               else scan)):
        findings.extend(_check_pallas_kernels(ctx))
    for ctx in project.files(*(shard_map_scan if shard_map_scan is not None
                               else scan)):
        findings.extend(_check_shard_maps(ctx))
    return findings
