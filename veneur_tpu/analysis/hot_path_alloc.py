"""vtlint pass: the per-batch pump/emit hot path stays allocation-free.

Port of scripts/check_hot_path_alloc.py. The zero-copy ingest contract:
once the pipeline is warm, moving a batch from the wire to the device
performs NO per-batch Python-side allocation — staged lanes land in
pre-allocated double-buffered flat host buffers and every array the
dispatch touches is a view or a reused buffer. `np.zeros` is allowed
(the packed-layout contract requires zero-initialized buffers at
allocation time, and none of the hot functions allocate at all).

Now alias-aware: `import numpy as xp; xp.empty(...)` is caught too.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from veneur_tpu.analysis.core import Finding, Project

NAME = "hot-path-alloc"
DOC = ("per-batch hot functions stay allocation-free "
       "(no .copy()/np.empty/np.concatenate/np.stack)")

# {file: functions that run once per batch (or per datagram) when warm}
HOT_FUNCS: Dict[str, List[str]] = {
    "veneur_tpu/server/native_aggregator.py": [
        "_emit_native", "feed", "pump", "_split_shards"],
    "veneur_tpu/aggregation/step.py": ["pack_batch"],
    "veneur_tpu/server/aggregator.py": ["_on_batch"],
    "veneur_tpu/server/sharded_aggregator.py": ["_dispatch_row"],
}

# numpy constructors that allocate a fresh array per call
_NP_ALLOCS = ("empty", "concatenate", "stack")


def _violations_in(ctx, fn: ast.AST) -> List[Finding]:
    problems = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr == "copy":
            problems.append(Finding(
                NAME, ctx.rel, node.lineno,
                f"`.copy()` in hot-path function {fn.name}() — use the "
                "pre-allocated packed buffer"))
        elif attr in _NP_ALLOCS:
            if ctx.resolve(node.func) == f"numpy.{attr}":
                problems.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`np.{attr}` in hot-path function {fn.name}() — "
                    "per-batch allocation; move it to an _alloc_* init "
                    "helper"))
    return problems


def run(project: Project, hot_funcs: Dict[str, List[str]] = None
        ) -> List[Finding]:
    findings: List[Finding] = []
    for rel, funcs in (hot_funcs or HOT_FUNCS).items():
        ctx = project.file(rel)
        if ctx is None:
            findings.append(Finding(
                NAME, rel, 0, "file missing — update HOT_FUNCS"))
            continue
        seen = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name in funcs):
                seen.add(node.name)
                findings.extend(_violations_in(ctx, node))
        for name in funcs:
            if name not in seen:
                findings.append(Finding(
                    NAME, rel, 0,
                    f"hot-path function {name}() not found — renamed? "
                    "update HOT_FUNCS in veneur_tpu/analysis/"
                    "hot_path_alloc.py"))
    return findings
