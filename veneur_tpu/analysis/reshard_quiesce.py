"""vtlint pass: shard-map mutation only behind the swap-boundary helper.

A shard map can only change at a buffer-swap boundary: the native
engine's staged rows are keyed under the OLD map, the reader rings hold
key-replica caches of old-map slots, and a packed batch must never
straddle two maps. `veneur_tpu/reshard/quiesce.py` is the ONE module
that owns that sequencing (stage on the engine, apply inside the swap's
reset while the rings are quiesced). This pass makes the boundary
un-bypassable by review accident:

  1. calls to a shard-map mutator — `shard_map_set`,
     `vt_shard_map_set`, `vrm_shard_map_set` — anywhere in the tree
     outside quiesce.py and the ctypes binding layer
     (veneur_tpu/native/__init__.py) are flagged;
  2. assignments to an `n_shards` attribute outside `__init__` (object
     construction fixes the map; everything after must go through the
     helper) are flagged;
  3. assignments to a proxy `_ring` attribute outside
     forward/proxysrv.py (whose refresh() is ring membership's own
     documented swap site) are flagged.

Tests and the analysis package itself are out of scope — the contract
binds production code; tests exercise mutators on purpose.
"""

from __future__ import annotations

import ast
from typing import List

from veneur_tpu.analysis.core import Finding, Project

NAME = "reshard-quiesce"
DOC = ("shard-map / ring-membership mutation happens only behind the "
       "documented swap-boundary helper (reshard/quiesce.py)")

# the scanned tree (production code only; tests exercise mutators)
ROOTS = ["veneur_tpu"]

_MUTATORS = {"shard_map_set", "vt_shard_map_set", "vrm_shard_map_set"}

# (file, reason) exemptions per rule
_CALL_ALLOWED = {
    "veneur_tpu/reshard/quiesce.py",   # THE documented helper
    "veneur_tpu/native/__init__.py",   # ctypes binding internals
}
_RING_ALLOWED = {
    "veneur_tpu/forward/proxysrv.py",  # refresh() is ring membership's
    #                                    own documented swap site
}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _attr_targets(stmt: ast.stmt):
    """Attribute names assigned by a statement (plain or augmented)."""
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    else:
        return
    for t in targets:
        if isinstance(t, ast.Attribute):
            yield t.attr


def _scan_file(ctx) -> List[Finding]:
    findings: List[Finding] = []
    # map every node to its enclosing function name, so rule 2 can give
    # construction (__init__) its pass
    enclosing = {}

    def mark(fn_name, node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mark(child.name, child)
            else:
                enclosing[child] = fn_name
                mark(fn_name, child)

    mark("", ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in _MUTATORS and ctx.rel not in _CALL_ALLOWED:
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"{name}() outside the swap-boundary helper — a "
                    "shard map may only change inside "
                    "reshard/quiesce.py shard_map_swap(), where the "
                    "staged map applies at the swap's reset under the "
                    "ring quiesce"))
        for attr in _attr_targets(node) if isinstance(node, ast.stmt) \
                else ():
            if attr == "n_shards" and enclosing.get(node) != "__init__":
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    "assignment to .n_shards outside __init__ — the "
                    "shard map is fixed at construction; live changes "
                    "go through reshard/quiesce.py shard_map_swap()"))
            elif attr == "_ring" and ctx.rel not in _RING_ALLOWED \
                    and enclosing.get(node) != "__init__":
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    "assignment to ._ring outside forward/proxysrv.py "
                    "— ring membership changes only in the proxy's "
                    "refresh() (its documented swap site)"))
    return findings


def run(project: Project, roots: List[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    scanned = False
    for ctx in project.files(*(roots or ROOTS)):
        scanned = True
        if ctx.rel.startswith("veneur_tpu/analysis/"):
            continue   # the lint layer names mutators in string/docs
        findings.extend(_scan_file(ctx))
    if not scanned:
        findings.append(Finding(
            NAME, (roots or ROOTS)[0], 0, "scan root missing or empty"))
    return findings
