"""vtlint pass: every data-discarding code path increments a counter.

Port of scripts/check_drop_accounting.py. The overload contract is
"nothing is shed silently": an operator must be able to reconstruct
sent == processed + sum(drop counters) from telemetry alone.

1. Every `except queue.Full` / ParseError / FramingError handler must
   do accounting in its body — a counter `.inc(...)`, an `x += 1`
   increment, a re-raise, or an `.append(...)` onto a rejection
   collection. (The accounting-flow pass holds the same handlers to the
   stronger every-path standard; this pass keeps the legacy any-path
   rule so the delegating shim enforces exactly what it used to.)

2. The canonical drop-counter families must each still be REGISTERED
   somewhere in the tree as a string literal.
"""

from __future__ import annotations

import ast
from typing import List

from veneur_tpu.analysis.core import Finding, Project

NAME = "drop-accounting"
DOC = ("drop-exception handlers account, and every required drop "
       "counter stays registered")

# the ingest + egress surface: everywhere a sample can be discarded
TARGETS = [
    "veneur_tpu/server",
    "veneur_tpu/samplers",
    "veneur_tpu/protocol",
    "veneur_tpu/forward",
    "veneur_tpu/reliability",
    "veneur_tpu/watch",
]

# counter families that discard sites rely on; each must appear as a
# registration literal somewhere under veneur_tpu/
REQUIRED_COUNTERS = [
    "veneur.packets_dropped_total",
    "veneur.parse_errors_total",
    "veneur.worker.metrics_dropped_total",
    "veneur.overload.shed_total",
    "veneur.forward.spill.dropped_total",
    "veneur.tcp.rejected_total",
    "veneur.tcp.idle_closed_total",
    "veneur.watch.notify_dropped_total",
]

# exception names whose handlers ARE discard sites
DROP_EXCS = ("Full", "ParseError", "FramingError")

_REJECT_NAMES = ("invalid", "drop", "reject", "shed", "error")


def exc_names(node: ast.ExceptHandler) -> List[str]:
    """Leaf names of the handled exception type(s): `queue.Full` ->
    Full, `(Full, OSError)` -> both."""
    t = node.type
    if t is None:
        return []
    parts = t.elts if isinstance(t, ast.Tuple) else [t]
    names = []
    for p in parts:
        if isinstance(p, ast.Attribute):
            names.append(p.attr)
        elif isinstance(p, ast.Name):
            names.append(p.id)
    return names


def accounts_anywhere(handler: ast.ExceptHandler) -> bool:
    """True when the handler body increments something: an `.inc(...)`
    method call, an augmented `+=` assignment (the plain-int counter
    idiom), a re-raise (the caller accounts), or an `.append(...)` onto
    a rejection collection (the hand-off idiom where the CALLER counts
    the returned rejects)."""
    for stmt in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(stmt, ast.Raise):
            return True
        if isinstance(stmt, ast.AugAssign) and isinstance(stmt.op, ast.Add):
            return True
        if (isinstance(stmt, ast.Call)
                and isinstance(stmt.func, ast.Attribute)):
            # .inc() on a registry counter, or a *bump* counter helper
            # (the locked-increment idiom: self._bump("errors", n))
            if stmt.func.attr == "inc" or "bump" in stmt.func.attr:
                return True
            if stmt.func.attr == "append":
                target = stmt.func.value
                name = (target.id if isinstance(target, ast.Name)
                        else target.attr
                        if isinstance(target, ast.Attribute) else "")
                if any(r in name.lower() for r in _REJECT_NAMES):
                    return True
    return False


def run(project: Project, targets: List[str] = None,
        required_counters: List[str] = None,
        literal_roots: List[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for ctx in project.files(*(targets or TARGETS)):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            dropped = [n for n in exc_names(node) if n in DROP_EXCS]
            if dropped and not accounts_anywhere(node):
                findings.append(Finding(
                    NAME, ctx.rel, node.lineno,
                    f"`except {'/'.join(dropped)}` discards data "
                    "without incrementing a drop counter"))

    literals = set()
    for ctx in project.files(*(literal_roots or ["veneur_tpu"])):
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value.startswith("veneur.")):
                literals.add(node.value)
    for name in (required_counters if required_counters is not None
                 else REQUIRED_COUNTERS):
        if name not in literals:
            findings.append(Finding(
                NAME, "", 0,
                f"required drop counter {name!r} is no longer "
                "registered anywhere under veneur_tpu/"))
    return findings
