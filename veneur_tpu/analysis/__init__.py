"""vtlint: the unified static-analysis framework.

One `Project` (one AST parse per file) feeds a config-driven registry of
passes. Run from the command line::

    python -m veneur_tpu.analysis --all            # every pass
    python -m veneur_tpu.analysis lock-discipline  # one pass
    python -m veneur_tpu.analysis --all --json     # machine-readable
    python -m veneur_tpu.analysis --list           # pass inventory

Suppress a finding in place with a mandatory reason::

    x = np.asarray(dev)  # vtlint: disable=jax-hot-path -- flush boundary

The old scripts/check_*.py entry points delegate here (see run_cli).
"""

from __future__ import annotations

import json
import time
from typing import Dict, List

from veneur_tpu.analysis import (ambiguous_paths, accounting_flow,
                                 bare_except, drop_accounting,
                                 hot_path_alloc, jax_hot_path,
                                 lock_discipline, metric_names,
                                 reshard_quiesce, snapshot_schema,
                                 table_grow_quiesce, timer_sync)
from veneur_tpu.analysis.core import (REPO, Finding, Project,
                                      filter_suppressed,
                                      reasonless_suppressions)

JSON_SCHEMA_VERSION = 1

# ordered registry: name -> module (must expose NAME, DOC, run(project))
PASSES = {
    m.NAME: m for m in (
        hot_path_alloc,
        drop_accounting,
        ambiguous_paths,
        bare_except,
        metric_names,
        snapshot_schema,
        jax_hot_path,
        lock_discipline,
        accounting_flow,
        timer_sync,
        reshard_quiesce,
        table_grow_quiesce,
    )
}


def run_passes(project: Project, names: List[str]) -> Dict:
    """Run the named passes over one shared Project; returns the full
    result dict (the --json schema, minus nothing)."""
    t_all = time.monotonic()
    pass_rows = []
    findings: List[Finding] = []
    for name in names:
        mod = PASSES[name]
        t0 = time.monotonic()
        found = filter_suppressed(project, mod.run(project))
        pass_rows.append({
            "name": name,
            "doc": mod.DOC,
            "findings": len(found),
            "runtime_s": round(time.monotonic() - t0, 4),
        })
        findings.extend(found)
    findings.extend(reasonless_suppressions(project))
    return {
        "version": JSON_SCHEMA_VERSION,
        "root": str(project.root),
        "passes": pass_rows,
        "findings": [
            {"pass": f.pass_name, "file": f.file, "line": f.line,
             "message": f.message}
            for f in findings],
        "files_parsed": project.parse_count,
        "parse_count": project.parse_count,
        "runtime_s": round(time.monotonic() - t_all, 4),
        "ok": not findings,
    }


def run_cli(pass_names: List[str], root=None, as_json: bool = False) -> int:
    """Shared entry point for __main__ and the scripts/check_* shims:
    run the passes, print findings (or the JSON result), return the
    process exit code."""
    project = Project(root or REPO)
    result = run_passes(project, pass_names)
    if as_json:
        print(json.dumps(result, sort_keys=True))
    else:
        for f in result["findings"]:
            loc = f["file"] or "<project>"
            if f["line"]:
                loc += f":{f['line']}"
            print(f"{loc}: [{f['pass']}] {f['message']}")
        n = len(result["findings"])
        names = ", ".join(pass_names)
        if n:
            print(f"vtlint: {n} finding(s) from {names}")
        else:
            print(f"vtlint: OK ({names}; "
                  f"{result['files_parsed']} files, "
                  f"{result['runtime_s']}s)")
    return 1 if result["findings"] else 0
