"""vtlint engine: one AST parse per file, shared by every pass.

The six scripts/check_*.py one-offs each re-parsed the tree they cared
about; with nine passes that would be nine parses of server.py per lint
run. Here a Project caches one FileContext per file — the parsed tree,
an import-alias map (`import numpy as np` -> np resolves to numpy), and
the `# vtlint: disable=<pass>` suppression table — and passes share it.

Suppression syntax (per line; a comment alone on its line also covers
the next line, so long statements can carry one above them):

    x = np.asarray(dev)  # vtlint: disable=jax-hot-path -- flush boundary

The reason string after `--` is mandatory: a suppression without one is
itself reported (pass name `vtlint`), so silencing a finding always
leaves a reviewable sentence behind.
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

_SUPPRESS_RE = re.compile(
    r"#\s*vtlint:\s*disable=([A-Za-z0-9_,-]+)"
    r"(?:\s+--\s*(.*?))?\s*(?:#|$)")


@dataclass(frozen=True)
class Finding:
    """One problem one pass found. `file` is project-relative ("" for
    project-level findings such as a missing required counter)."""
    pass_name: str
    file: str
    line: int
    message: str

    def format(self) -> str:
        loc = self.file or "<project>"
        if self.line:
            loc += f":{self.line}"
        return f"{loc}: [{self.pass_name}] {self.message}"


@dataclass
class Suppression:
    passes: Tuple[str, ...]
    reason: str
    line: int            # the line the comment is on


class FileContext:
    """One parsed Python file: tree + alias map + suppressions."""

    def __init__(self, root: pathlib.Path, rel: str, source: str):
        self.root = root
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        # local name -> canonical dotted path, from this file's imports
        self.aliases: Dict[str, str] = {}
        # effective line -> suppression active there
        self.suppressions: Dict[int, Suppression] = {}
        self._build_aliases()
        self._build_suppressions()

    # -- alias / symbol resolution ------------------------------------------
    def _build_aliases(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        # the conventional jax.numpy spelling: resolve through the alias
        # map so `import jax.numpy as jnp` lands on the canonical name
        if self.aliases.get("jnp") == "jax.numpy":
            pass  # already canonical

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Raw dotted name of a Name/Attribute chain, None for anything
        else (calls, subscripts)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted path of a Name/Attribute chain with the
        file's import aliases applied: `np.asarray` -> numpy.asarray,
        `z` (from x import y as z) -> x.y."""
        raw = self.dotted(node)
        if raw is None:
            return None
        head, _, rest = raw.partition(".")
        head = self.aliases.get(head, head)
        return f"{head}.{rest}" if rest else head

    # -- suppressions --------------------------------------------------------
    def _build_suppressions(self) -> None:
        for lineno, text in enumerate(self.source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            passes = tuple(p.strip() for p in m.group(1).split(",")
                           if p.strip())
            sup = Suppression(passes, (m.group(2) or "").strip(), lineno)
            self.suppressions[lineno] = sup
            # a comment-only line suppresses the statement below it too
            if text.split("#", 1)[0].strip() == "":
                self.suppressions.setdefault(lineno + 1, sup)

    def suppressed(self, pass_name: str, line: int) -> bool:
        sup = self.suppressions.get(line)
        return bool(sup and (pass_name in sup.passes
                             or "all" in sup.passes))


class Project:
    """Root + parsed-file cache. `parse_count` exists so tests can pin
    the one-parse-per-file contract."""

    def __init__(self, root) -> None:
        self.root = pathlib.Path(root)
        self._files: Dict[str, Optional[FileContext]] = {}
        self.parse_count = 0

    def file(self, rel: str) -> Optional[FileContext]:
        """FileContext for a project-relative path; None when the file
        is missing or unparseable (passes report that themselves)."""
        if rel not in self._files:
            path = self.root / rel
            ctx = None
            if path.is_file():
                try:
                    ctx = FileContext(self.root, rel, path.read_text())
                    self.parse_count += 1
                except SyntaxError:
                    ctx = None
            self._files[rel] = ctx
        return self._files[rel]

    def exists(self, rel: str) -> bool:
        return (self.root / rel).is_file()

    def files(self, *entries: str) -> Iterable[FileContext]:
        """Every parseable .py under the given project-relative files or
        directories, in sorted order, via the cache."""
        rels: List[str] = []
        for entry in entries:
            p = self.root / entry
            if p.is_file():
                rels.append(entry)
            elif p.is_dir():
                rels.extend(
                    str(f.relative_to(self.root))
                    for f in sorted(p.rglob("*.py")))
        for rel in rels:
            ctx = self.file(rel)
            if ctx is not None:
                yield ctx


def filter_suppressed(project: Project, findings: List[Finding]
                      ) -> List[Finding]:
    """Drop findings their file suppresses, and report any suppression
    comment missing a reason string (pass name `vtlint`)."""
    kept = []
    for f in findings:
        ctx = project.file(f.file) if f.file.endswith(".py") else None
        if ctx is not None and f.line \
                and ctx.suppressed(f.pass_name, f.line):
            continue
        kept.append(f)
    return kept


def reasonless_suppressions(project: Project) -> List[Finding]:
    """Framework self-check: every `# vtlint: disable=` comment must
    carry a `-- reason`. Scans only files already parsed this run, so
    it costs no extra parse."""
    out = []
    for rel, ctx in sorted(project._files.items()):
        if ctx is None:
            continue
        seen = set()
        for sup in ctx.suppressions.values():
            if id(sup) in seen:
                continue
            seen.add(id(sup))
            if not sup.reason:
                out.append(Finding(
                    "vtlint", rel, sup.line,
                    "suppression without a reason — write "
                    "`# vtlint: disable=<pass> -- <why>`"))
    return out
