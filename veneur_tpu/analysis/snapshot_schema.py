"""vtlint pass: the checkpoint snapshot schema cannot drift silently.

Port of scripts/check_snapshot_schema.py. Unlike the AST passes this one
runs the live code: the on-disk checkpoint format
(veneur_tpu/persistence/codec.py) pins a hash over the structures its
meaning depends on — DeviceState's field list and TableSpec's field
names — and this pass compares the live hash against the pin for the
current SNAPSHOT_FORMAT_VERSION. A mismatch means old checkpoints would
be misread: bump the version, pin the new hash, and decide whether
read_manifest rejects or migrates the previous version.

Runs only against the installed veneur_tpu package (a --root pointed at
a fixture tree skips it: there is nothing to import there).
"""

from __future__ import annotations

import os
from typing import List

from veneur_tpu.analysis.core import REPO, Finding, Project

NAME = "snapshot-schema"
DOC = ("live schema_hash() matches the pinned hash for "
       "SNAPSHOT_FORMAT_VERSION")

CODEC_REL = "veneur_tpu/persistence/codec.py"


def run(project: Project) -> List[Finding]:
    if project.root != REPO and not project.exists(CODEC_REL):
        return []   # fixture tree: nothing to import
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from veneur_tpu.persistence.codec import (SNAPSHOT_FORMAT_VERSION,
                                              _SCHEMA_MIGRATIONS,
                                              _SCHEMA_PINS, schema_hash)
    findings = []
    live = schema_hash()
    pinned = _SCHEMA_PINS.get(SNAPSHOT_FORMAT_VERSION)
    if pinned is None:
        findings.append(Finding(
            NAME, CODEC_REL, 0,
            f"SNAPSHOT_FORMAT_VERSION={SNAPSHOT_FORMAT_VERSION} has no "
            f"pin in codec._SCHEMA_PINS — add one: "
            f"{SNAPSHOT_FORMAT_VERSION}: \"{live}\""))
    elif live != pinned:
        findings.append(Finding(
            NAME, CODEC_REL, 0,
            f"snapshot schema DRIFTED (pinned {pinned}, live {live}). "
            "DeviceState._fields or TableSpec changed shape; old "
            "checkpoints would be misread. Bump "
            "SNAPSHOT_FORMAT_VERSION, pin the new hash in "
            "_SCHEMA_PINS, and decide what read_manifest does with "
            "the previous version: reject (default) or migrate"))
    # every superseded pin must carry an explicit migration entry: a
    # version bump without one silently ORPHANS the old checkpoints
    # (read_manifest would reject them), and a migration entry without a
    # frozen pin cannot be hash-verified at read time
    for old in _SCHEMA_PINS:
        if old == SNAPSHOT_FORMAT_VERSION:
            continue
        if old not in _SCHEMA_MIGRATIONS:
            findings.append(Finding(
                NAME, CODEC_REL, 0,
                f"superseded format v{old} has a pin but no "
                "_SCHEMA_MIGRATIONS entry — add one describing the "
                "layout change (read_manifest only accepts migratable "
                "versions), or drop the pin if v%d checkpoints are "
                "intentionally orphaned" % old))
    for old in _SCHEMA_MIGRATIONS:
        if old not in _SCHEMA_PINS:
            findings.append(Finding(
                NAME, CODEC_REL, 0,
                f"_SCHEMA_MIGRATIONS lists v{old} but _SCHEMA_PINS has "
                "no frozen hash for it — read_manifest cannot verify "
                f"v{old} snapshots"))
    return findings
