"""vtlint pass: every self-telemetry metric name is registered once and
documented.

Port of scripts/check_metric_names.py. The telemetry registry
(veneur_tpu/observability/registry.py) is the single source of truth
for `veneur.*` series:

  1. a name is REGISTERED (registry.counter/gauge/timer/callback with a
     literal name) at most once across the tree;
  2. every name the code can emit or register appears in the README's
     metric inventory (between the metric-inventory markers);
  3. every inventory row corresponds to a name the code actually uses.

Dynamically-built names can't be string-checked; they are documented as
a pattern in the README prose and intentionally out of scope here.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from typing import List

from veneur_tpu.analysis.core import Finding, Project

NAME = "metric-names"
DOC = ("veneur.* series registered once and kept in lockstep with the "
       "README metric inventory")

SAMPLE_FNS = {"count", "gauge", "timing", "histogram", "set_", "status"}
REGISTER_FNS = {"counter", "gauge", "timer", "callback"}

INV_BEGIN = "<!-- metric-inventory:begin -->"
INV_END = "<!-- metric-inventory:end -->"


def _literal_name(call: ast.Call):
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str) \
            and call.args[0].value.startswith("veneur."):
        return call.args[0].value
    return None


def _scan(ctx, emitted: dict, registered: dict) -> None:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            name = _literal_name(node)
            if name is None:
                continue
            func = node.func
            on_samples = (isinstance(func.value, ast.Name)
                          and func.value.id == "ssf_samples")
            if on_samples and func.attr in SAMPLE_FNS:
                emitted[name].append(f"{ctx.rel}:{node.lineno}")
            elif not on_samples and func.attr in REGISTER_FNS:
                registered[name].append(f"{ctx.rel}:{node.lineno}")
        elif isinstance(node, ast.Dict):
            # the self-telemetry snapshot dict: {"veneur.x": ..., ...}
            keys = [k.value for k in node.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and k.value.startswith("veneur.")]
            if len(keys) >= 3:
                for k in keys:
                    emitted[k].append(f"{ctx.rel}:{node.lineno}")


def inventory_names(text: str):
    try:
        block = text.split(INV_BEGIN, 1)[1].split(INV_END, 1)[0]
    except IndexError:
        return None
    return set(re.findall(r"`(veneur\.[a-zA-Z0-9._]+)`", block))


def run(project: Project, pkg: str = "veneur_tpu",
        readme: str = "README.md") -> List[Finding]:
    emitted: dict = defaultdict(list)
    registered: dict = defaultdict(list)
    for ctx in project.files(pkg):
        _scan(ctx, emitted, registered)

    findings: List[Finding] = []
    for name, sites in sorted(registered.items()):
        if len(sites) > 1:
            rel, _, line = sites[1].rpartition(":")
            findings.append(Finding(
                NAME, rel, int(line),
                f"{name}: registered at {len(sites)} sites "
                f"({', '.join(sites)}); one owner only"))

    known = set(emitted) | set(registered)
    readme_path = project.root / readme
    if not readme_path.is_file():
        findings.append(Finding(NAME, "", 0, f"{readme} missing"))
        inv = set()
    else:
        inv = inventory_names(readme_path.read_text())
        if inv is None:
            findings.append(Finding(
                NAME, readme, 0,
                f"lacks the {INV_BEGIN} .. {INV_END} block"))
            inv = set()
    for name in sorted(known - inv):
        sites = (emitted.get(name) or registered.get(name))[:2]
        rel, _, line = sites[0].rpartition(":")
        findings.append(Finding(
            NAME, rel, int(line),
            f"{name}: used at {', '.join(sites)} but absent from the "
            "README metric inventory"))
    for name in sorted(inv - known):
        findings.append(Finding(
            NAME, readme, 0,
            f"{name}: in the README inventory but no code emits or "
            "registers it"))
    return findings
