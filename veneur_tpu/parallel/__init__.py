from veneur_tpu.parallel.sharded import (  # noqa: F401
    REPLICA_AXIS,
    SHARD_AXIS,
    make_mesh,
    sharded_empty_state,
    make_sharded_ingest,
    make_sharded_ingest_packed,
    make_merged_flush,
    stack_batches,
)
