"""Device-mesh sharding of the key table and the collective global merge.

The reference's two distribution axes (SURVEY §2.4) map onto a 2-D
`jax.sharding.Mesh`:

- **"shard"** — key-space parallelism: `Digest % numWorkers` routing
  (reference server.go:973,984) becomes a leading shard axis on every state
  array, partitioned across devices. Each key lives on exactly one device
  (host.py assigns slot = shard * per_shard + local), so the ingest scatter
  never crosses devices — the per-worker-private-maps property of the
  reference (worker.go:60-84), expressed as sharding.
- **"replica"** — the local→global aggregation tier: each replica group
  accumulates its own sample stream (one "local veneur instance" worth of
  state); the flush-time merge the reference does over gRPC
  (importsrv/server.go:102 → samplers Merge methods) becomes on-device
  collectives over ICI: `psum` for counters/histogram scalars, register-max
  for HLL, all-gather + re-compress for t-digest centroids, and a
  stamp-argmax for last-write-wins gauges.

All state arrays carry leading dims [R, S] (replica, shard) and are laid out
with `NamedSharding(mesh, P("replica", "shard"))`; compute enters via
`jax.shard_map`, inside which each device sees its [r_local, s_local] block
and runs the same per-table ingest core under double vmap.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from veneur_tpu.aggregation.state import DeviceState, TableSpec, empty_state
from veneur_tpu.aggregation.step import Batch, flush_core, ingest_core
# the replica-tier merge collectives live in collective/ops.py (reusable
# over any named axis); this module keeps the mesh/state plumbing and the
# historical names
from veneur_tpu.collective.ops import (
    REPLICA_AXIS, SHARD_AXIS, merge_replica_block,
    shard_map as _shard_map)


def make_mesh(n_replicas: int, n_shards: int, devices=None) -> Mesh:
    """A (replica, shard) mesh over `n_replicas * n_shards` devices, or —
    with fewer physical devices — the largest (nr, ns) mesh where nr divides
    n_replicas and ns divides n_shards. shard_map blocks then hold multiple
    logical tiles per device (leading block dims > 1), which the vmapped
    cores handle transparently."""
    import numpy as np
    if devices is None:
        devices = jax.devices()
    need = n_replicas * n_shards
    if len(devices) >= need:
        return Mesh(np.asarray(devices[:need]).reshape(n_replicas, n_shards),
                    (REPLICA_AXIS, SHARD_AXIS))
    nr, ns = max(
        ((r, s) for r in range(1, n_replicas + 1) if n_replicas % r == 0
         for s in range(1, n_shards + 1) if n_shards % s == 0
         and r * s <= len(devices)),
        key=lambda p: p[0] * p[1])
    return Mesh(np.asarray(devices[:nr * ns]).reshape(nr, ns),
                (REPLICA_AXIS, SHARD_AXIS))


def state_sharding(mesh: Mesh):
    return NamedSharding(mesh, P(REPLICA_AXIS, SHARD_AXIS))


def sharded_empty_state(spec: TableSpec, n_replicas: int, n_shards: int,
                        mesh: Mesh) -> DeviceState:
    """DeviceState whose arrays have leading [R, S] dims, device-placed with
    (replica, shard) sharding. `spec` capacities are PER SHARD."""
    one = empty_state(spec)
    sh = state_sharding(mesh)

    def tile(x):
        tiled = jnp.broadcast_to(x, (n_replicas, n_shards) + x.shape)
        return jax.device_put(tiled, sh)

    return jax.tree.map(tile, one)


def stack_batches(batches, n_replicas: int, n_shards: int) -> Batch:
    """Stack a [R][S] nested list of per-shard Batches into one Batch with
    leading [R, S] dims (host-side numpy; feed to the sharded ingest).
    Optional lanes (None, e.g. histo_stat_* on pure-ingest batches) stay
    None — every tile must agree on which lanes are present."""
    import numpy as np
    cols = list(zip(*[list(zip(*[batches[r][s] for s in range(n_shards)]))
                      for r in range(n_replicas)]))

    def stack(col):
        flat = [x for row in col for x in row]
        if all(x is None for x in flat):
            return None
        if any(x is None for x in flat):
            raise ValueError(
                "stack_batches: every tile must agree on which optional "
                "Batch lanes are present (mixing Batcher batches with "
                "hand-built ones?)")
        return np.stack([np.stack(row) for row in col])

    return Batch(*[stack(col) for col in cols])


def make_sharded_ingest(mesh: Mesh, spec: TableSpec):
    """Jitted (state, batch) -> state over the mesh. Batch arrays must carry
    the same leading [R, S] dims as the state; each (replica, shard) tile's
    scatters stay on its own device — zero communication."""
    core = partial(ingest_core, spec=spec, allow_pallas=False)
    vv = jax.vmap(jax.vmap(core))
    fn = _shard_map(
        vv, mesh=mesh,
        in_specs=(P(REPLICA_AXIS, SHARD_AXIS), P(REPLICA_AXIS, SHARD_AXIS)),
        out_specs=P(REPLICA_AXIS, SHARD_AXIS))
    return jax.jit(fn, donate_argnums=(0,))


def make_sharded_ingest_packed(mesh: Mesh, spec: TableSpec, sizes: tuple):
    """Packed-transfer variant of make_sharded_ingest: (state, flat) ->
    state where flat is i32[R, S, W] — each tile's batch as ONE bit-packed
    buffer (aggregation/step.py pack_batch), with the compact control word
    in-band. Same single-executable / single-transfer rationale as the
    single-device ingest_step_packed, applied per mesh tile.

    The compact cond sits ABOVE the tile vmaps with a scalar predicate
    (every tile of a dispatch carries the same word): a vmapped cond
    would lower to a select that computes BOTH branches, running the
    sort-based recompression every step instead of every
    compact_every-th."""
    from veneur_tpu.aggregation.step import (
        compact_core, ingest_core, unpack_batch)

    def tile_ingest(state, flat):
        # allow_pallas=False: the tile body runs under two vmaps, where
        # the fused kernel's scalar-prefetch grid does not apply
        return ingest_core(state, unpack_batch(flat[1:], sizes),
                           spec=spec, allow_pallas=False)

    vv_ingest = jax.vmap(jax.vmap(tile_ingest))
    vv_compact = jax.vmap(jax.vmap(partial(compact_core, spec=spec)))

    def block(state, flat):
        st = vv_ingest(state, flat)
        do_compact = flat[0, 0, 0] != 0   # scalar: cond stays a branch
        return jax.lax.cond(do_compact, vv_compact, lambda s: s, st)

    fn = _shard_map(
        block, mesh=mesh,
        in_specs=(P(REPLICA_AXIS, SHARD_AXIS), P(REPLICA_AXIS, SHARD_AXIS)),
        out_specs=P(REPLICA_AXIS, SHARD_AXIS))
    return jax.jit(fn, donate_argnums=(0,))


def _merge_replica_block(state: DeviceState, spec: TableSpec):
    """Inside shard_map: merge a [r_local, s_local, ...] block over the full
    replica axis. The per-family sketch merges live in collective/ops.py
    (generalized over the axis name); this wrapper pins the replica axis."""
    return merge_replica_block(state, spec, REPLICA_AXIS)


def make_merged_flush(mesh: Mesh, spec: TableSpec):
    """Jitted (state[R,S,...], qs[Q]) -> flush dict with leading [S] dim:
    replica-merged, per-shard final aggregates. The replica merge is the
    reference's global-tier import (SURVEY §3.4) as one collective program;
    the flush math is flush_core per shard."""

    def block(state: DeviceState, qs):
        # _merge_replica_block already re-compresses digests to canonical
        # cells; no separate compact pass needed before the flush math.
        merged = _merge_replica_block(state, spec)
        out = jax.vmap(lambda st: flush_core(st, qs, spec=spec))(merged)
        return out

    # replica-reduced outputs aren't replicated the way the checker wants;
    # the kwarg that disables the check was renamed check_rep -> check_vma
    try:
        fn = _shard_map(
            block, mesh=mesh,
            in_specs=(P(REPLICA_AXIS, SHARD_AXIS), P()),
            out_specs=P(SHARD_AXIS),
            check_vma=False)
    except TypeError:
        fn = _shard_map(
            block, mesh=mesh,
            in_specs=(P(REPLICA_AXIS, SHARD_AXIS), P()),
            out_specs=P(SHARD_AXIS),
            check_rep=False)
    return jax.jit(fn)
