"""Device-mesh sharding of the key table and the collective global merge.

The reference's two distribution axes (SURVEY §2.4) map onto a 2-D
`jax.sharding.Mesh`:

- **"shard"** — key-space parallelism: `Digest % numWorkers` routing
  (reference server.go:973,984) becomes a leading shard axis on every state
  array, partitioned across devices. Each key lives on exactly one device
  (host.py assigns slot = shard * per_shard + local), so the ingest scatter
  never crosses devices — the per-worker-private-maps property of the
  reference (worker.go:60-84), expressed as sharding.
- **"replica"** — the local→global aggregation tier: each replica group
  accumulates its own sample stream (one "local veneur instance" worth of
  state); the flush-time merge the reference does over gRPC
  (importsrv/server.go:102 → samplers Merge methods) becomes on-device
  collectives over ICI: `psum` for counters/histogram scalars, register-max
  for HLL, all-gather + re-compress for t-digest centroids, and a
  stamp-argmax for last-write-wins gauges.

All state arrays carry leading dims [R, S] (replica, shard) and are laid out
with `NamedSharding(mesh, P("replica", "shard"))`; compute enters via
`jax.shard_map`, inside which each device sees its [r_local, s_local] block
and runs the same per-table ingest core under double vmap.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from veneur_tpu.aggregation.state import DeviceState, TableSpec, empty_state
from veneur_tpu.aggregation.step import Batch, ingest_core, flush_core
from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import tdigest as td

REPLICA_AXIS = "replica"
SHARD_AXIS = "shard"

# jax.shard_map went public after 0.4.x; older installs only have the
# experimental location
try:
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map


def make_mesh(n_replicas: int, n_shards: int, devices=None) -> Mesh:
    """A (replica, shard) mesh over `n_replicas * n_shards` devices, or —
    with fewer physical devices — the largest (nr, ns) mesh where nr divides
    n_replicas and ns divides n_shards. shard_map blocks then hold multiple
    logical tiles per device (leading block dims > 1), which the vmapped
    cores handle transparently."""
    import numpy as np
    if devices is None:
        devices = jax.devices()
    need = n_replicas * n_shards
    if len(devices) >= need:
        return Mesh(np.asarray(devices[:need]).reshape(n_replicas, n_shards),
                    (REPLICA_AXIS, SHARD_AXIS))
    nr, ns = max(
        ((r, s) for r in range(1, n_replicas + 1) if n_replicas % r == 0
         for s in range(1, n_shards + 1) if n_shards % s == 0
         and r * s <= len(devices)),
        key=lambda p: p[0] * p[1])
    return Mesh(np.asarray(devices[:nr * ns]).reshape(nr, ns),
                (REPLICA_AXIS, SHARD_AXIS))


def state_sharding(mesh: Mesh):
    return NamedSharding(mesh, P(REPLICA_AXIS, SHARD_AXIS))


def sharded_empty_state(spec: TableSpec, n_replicas: int, n_shards: int,
                        mesh: Mesh) -> DeviceState:
    """DeviceState whose arrays have leading [R, S] dims, device-placed with
    (replica, shard) sharding. `spec` capacities are PER SHARD."""
    one = empty_state(spec)
    sh = state_sharding(mesh)

    def tile(x):
        tiled = jnp.broadcast_to(x, (n_replicas, n_shards) + x.shape)
        return jax.device_put(tiled, sh)

    return jax.tree.map(tile, one)


def stack_batches(batches, n_replicas: int, n_shards: int) -> Batch:
    """Stack a [R][S] nested list of per-shard Batches into one Batch with
    leading [R, S] dims (host-side numpy; feed to the sharded ingest).
    Optional lanes (None, e.g. histo_stat_* on pure-ingest batches) stay
    None — every tile must agree on which lanes are present."""
    import numpy as np
    cols = list(zip(*[list(zip(*[batches[r][s] for s in range(n_shards)]))
                      for r in range(n_replicas)]))

    def stack(col):
        flat = [x for row in col for x in row]
        if all(x is None for x in flat):
            return None
        if any(x is None for x in flat):
            raise ValueError(
                "stack_batches: every tile must agree on which optional "
                "Batch lanes are present (mixing Batcher batches with "
                "hand-built ones?)")
        return np.stack([np.stack(row) for row in col])

    return Batch(*[stack(col) for col in cols])


def make_sharded_ingest(mesh: Mesh, spec: TableSpec):
    """Jitted (state, batch) -> state over the mesh. Batch arrays must carry
    the same leading [R, S] dims as the state; each (replica, shard) tile's
    scatters stay on its own device — zero communication."""
    core = partial(ingest_core, spec=spec, allow_pallas=False)
    vv = jax.vmap(jax.vmap(core))
    fn = _shard_map(
        vv, mesh=mesh,
        in_specs=(P(REPLICA_AXIS, SHARD_AXIS), P(REPLICA_AXIS, SHARD_AXIS)),
        out_specs=P(REPLICA_AXIS, SHARD_AXIS))
    return jax.jit(fn, donate_argnums=(0,))


def make_sharded_ingest_packed(mesh: Mesh, spec: TableSpec, sizes: tuple):
    """Packed-transfer variant of make_sharded_ingest: (state, flat) ->
    state where flat is i32[R, S, W] — each tile's batch as ONE bit-packed
    buffer (aggregation/step.py pack_batch), with the compact control word
    in-band. Same single-executable / single-transfer rationale as the
    single-device ingest_step_packed, applied per mesh tile.

    The compact cond sits ABOVE the tile vmaps with a scalar predicate
    (every tile of a dispatch carries the same word): a vmapped cond
    would lower to a select that computes BOTH branches, running the
    sort-based recompression every step instead of every
    compact_every-th."""
    from veneur_tpu.aggregation.step import (
        compact_core, ingest_core, unpack_batch)

    def tile_ingest(state, flat):
        # allow_pallas=False: the tile body runs under two vmaps, where
        # the fused kernel's scalar-prefetch grid does not apply
        return ingest_core(state, unpack_batch(flat[1:], sizes),
                           spec=spec, allow_pallas=False)

    vv_ingest = jax.vmap(jax.vmap(tile_ingest))
    vv_compact = jax.vmap(jax.vmap(partial(compact_core, spec=spec)))

    def block(state, flat):
        st = vv_ingest(state, flat)
        do_compact = flat[0, 0, 0] != 0   # scalar: cond stays a branch
        return jax.lax.cond(do_compact, vv_compact, lambda s: s, st)

    fn = _shard_map(
        block, mesh=mesh,
        in_specs=(P(REPLICA_AXIS, SHARD_AXIS), P(REPLICA_AXIS, SHARD_AXIS)),
        out_specs=P(REPLICA_AXIS, SHARD_AXIS))
    return jax.jit(fn, donate_argnums=(0,))


def _merge_replica_block(state: DeviceState, spec: TableSpec):
    """Inside shard_map: merge a [r_local, s_local, ...] block over the full
    replica axis (local reduce + named-axis collective). Returns arrays with
    the replica dims reduced away — one merged table per shard tile."""
    ax = REPLICA_AXIS

    def pair_total(hi, lo, acc):
        """Sum two-float pairs across ALL replicas without collapsing to
        f32 (a plain psum of hi+lo rounds the ~48-bit pairs back to 24
        bits — the same boundary bug combine_flush_scalars fixes on the
        host). Gather every replica's pair and fold sequentially with
        error-free TwoSum merges; the global counter merge then matches
        the reference's exact int64 adds (importsrv -> Counter.Merge)."""
        from veneur_tpu.utils.numerics import twofloat_add, twofloat_merge
        hi, lo = twofloat_add(hi, lo, acc)   # absorb any unfolded acc
        hs = jax.lax.all_gather(hi, ax)      # [Rg, r_local, s, K]
        ls = jax.lax.all_gather(lo, ax)
        hs = hs.reshape((-1,) + hs.shape[2:])
        ls = ls.reshape((-1,) + ls.shape[2:])

        def body(carry, x):
            return twofloat_merge(carry[0], carry[1], x[0], x[1]), None

        (h, l), _ = jax.lax.scan(body, (hs[0], ls[0]), (hs[1:], ls[1:]))
        return h, l

    counters = pair_total(state.counter_hi, state.counter_lo,
                          state.counter_acc)
    h_count = pair_total(state.h_count_hi, state.h_count_lo,
                         state.h_count_acc)
    h_sum = pair_total(state.h_sum_hi, state.h_sum_lo, state.h_sum_acc)
    h_recip = pair_total(state.h_recip_hi, state.h_recip_lo,
                         state.h_recip_acc)

    # HLL: register-wise max (reference Set.Merge = HLL union,
    # samplers/samplers.go:461). The resident layout is 6-bit packed i32
    # words; componentwise max of packed WORDS is not register max (a high
    # register field dominates the word compare regardless of the low
    # fields), so unpack to dense u8 registers, max locally and across the
    # collective, repack. The dense form is transient — it never lands in
    # state or HBM-resident buffers.
    dense = hll_ops.unpack_registers(state.hll, precision=spec.hll_precision)
    dense = jax.lax.pmax(dense.max(axis=0), ax)
    hll = hll_ops.pack_registers(dense, precision=spec.hll_precision)

    # gauges/status: last-write-wins with canonical order = highest global
    # replica index that wrote (reference Gauge.Merge overwrites, :297)
    def lww(val, stamp):
        r_local = val.shape[0]
        ridx = jax.lax.axis_index(ax) * r_local + jnp.arange(r_local)
        prio = jnp.where(stamp > 0, ridx[:, None, None] + 1, 0)
        vals = jax.lax.all_gather(val, ax)          # [Rg, r_local, s, K]
        prios = jax.lax.all_gather(prio, ax)
        vals = vals.reshape((-1,) + vals.shape[2:])
        prios = prios.reshape((-1,) + prios.shape[2:])
        win = jnp.argmax(prios, axis=0)
        merged = jnp.take_along_axis(vals, win[None], axis=0)[0]
        written = prios.max(axis=0) > 0
        return merged, written.astype(jnp.uint8)

    gauge, gauge_stamp = lww(state.gauge, state.gauge_stamp)
    status, status_stamp = lww(state.status, state.status_stamp)

    # t-digest: gather every replica's centroids for the key, concatenate
    # along the centroid axis, re-compress to canonical cells (the
    # fixed-shape analogue of Histo.Merge digest re-add,
    # samplers/samplers.go:726)
    wm = jax.lax.all_gather(state.h_wm, ax)   # [Rg, r_local, s, K, C]
    w = jax.lax.all_gather(state.h_w, ax)
    wm = jnp.moveaxis(wm.reshape((-1,) + wm.shape[2:]), 0, -2)  # [s,K,R,C]
    w = jnp.moveaxis(w.reshape((-1,) + w.shape[2:]), 0, -2)
    s_l, k, r, c = w.shape
    mean = wm / jnp.maximum(w, 1e-30)
    mean = mean.reshape(s_l, k, r * c)
    w = w.reshape(s_l, k, r * c)
    m2, w2 = td.compress_rows(mean, w, compression=spec.compression,
                              cells_per_k=spec.cells_per_k,
                              out_c=spec.centroids,
                              exact_extremes=spec.exact_extremes)
    # back to the state's [C + temp] column layout, temp emptied
    pad = jnp.zeros(w2.shape[:-1] + (spec.temp_cells,), w2.dtype)
    w2 = jnp.concatenate([w2, pad], axis=-1)
    wm2 = jnp.concatenate([m2 * w2[..., :spec.centroids], pad], axis=-1)

    h_min = jax.lax.pmin(state.h_min.min(axis=0), ax)
    h_max = jax.lax.pmax(state.h_max.max(axis=0), ax)

    z = jnp.zeros_like
    merged = DeviceState(
        counter_acc=z(counters[0]), counter_hi=counters[0],
        counter_lo=counters[1],
        gauge=gauge, gauge_stamp=gauge_stamp,
        status=status, status_stamp=status_stamp,
        hll=hll,
        h_wm=wm2, h_w=w2,
        h_temp_n=jnp.zeros(w2.shape[:-1], jnp.int32),
        h_min=h_min, h_max=h_max,
        h_count_acc=z(h_count[0]), h_count_hi=h_count[0],
        h_count_lo=h_count[1],
        h_sum_acc=z(h_sum[0]), h_sum_hi=h_sum[0], h_sum_lo=h_sum[1],
        h_recip_acc=z(h_recip[0]), h_recip_hi=h_recip[0],
        h_recip_lo=h_recip[1],
    )
    return merged


def make_merged_flush(mesh: Mesh, spec: TableSpec):
    """Jitted (state[R,S,...], qs[Q]) -> flush dict with leading [S] dim:
    replica-merged, per-shard final aggregates. The replica merge is the
    reference's global-tier import (SURVEY §3.4) as one collective program;
    the flush math is flush_core per shard."""

    def block(state: DeviceState, qs):
        # _merge_replica_block already re-compresses digests to canonical
        # cells; no separate compact pass needed before the flush math.
        merged = _merge_replica_block(state, spec)
        out = jax.vmap(lambda st: flush_core(st, qs, spec=spec))(merged)
        return out

    # replica-reduced outputs aren't replicated the way the checker wants;
    # the kwarg that disables the check was renamed check_rep -> check_vma
    try:
        fn = _shard_map(
            block, mesh=mesh,
            in_specs=(P(REPLICA_AXIS, SHARD_AXIS), P()),
            out_specs=P(SHARD_AXIS),
            check_vma=False)
    except TypeError:
        fn = _shard_map(
            block, mesh=mesh,
            in_specs=(P(REPLICA_AXIS, SHARD_AXIS), P()),
            out_specs=P(SHARD_AXIS),
            check_rep=False)
    return jax.jit(fn)
