"""Multi-host mesh plumbing: jax.distributed init + SPMD-safe placement.

## Architecture: where ICI ends and DCN begins

The reference scales across hosts with a name-keyed gRPC tier
(flusher.go:474 forwardGRPC -> importsrv; proxied by the consistent-hash
router). This framework keeps that tier as the DCN backend ON PURPOSE:

- **Within a host/slice** (chips joined by ICI): the aggregation state
  shards over a `(replica, shard)` Mesh and the global merge is XLA
  collectives (parallel/sharded.py) — psum / all-gather / register-max
  ride ICI, exactly where the hardware wants them.
- **Between hosts** (DCN): metric keys are dynamic strings; each host's
  key table assigns slots in arrival order, so two hosts' raw state
  arrays are NOT slot-aligned and cannot be psum-merged. The name-keyed
  gRPC forward/import path (forward/rpc.py -> server import) re-keys on
  the receiving tier — the TPU-native analogue of the reference's
  cross-host protocol, and the reason collectives never cross DCN for
  ingest. ("Lay out shardings so collectives ride ICI, not DCN.")

What multi-PROCESS jax (this module) is still for: a pod slice whose
hosts share one SPMD program — e.g. a global tier whose *merge
collectives* span hosts. jax.distributed joins the processes, the mesh
is built over GLOBAL devices, and the helpers below create/place arrays
in the multi-controller world where plain `jax.device_put(host_array,
NamedSharding)` is not allowed. The cross-process collective merge is
validated end-to-end (2 processes, CPU Gloo backend) in
tests/test_multihost.py; slot alignment there is the caller's contract,
as it is for replicas inside one process.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from veneur_tpu.aggregation.state import TableSpec, empty_state
from veneur_tpu.parallel.sharded import state_sharding


def init_multihost(coordinator_address: str = None,
                   num_processes: int = None,
                   process_id: int = None) -> None:
    """Join this server process into a multi-controller jax runtime.
    Arguments default from VENEUR_TPU_COORDINATOR / _NUM_PROCESSES /
    _PROCESS_ID (mirroring the reference's env-driven fleet config);
    no-op when neither arguments nor env are set."""
    coordinator_address = coordinator_address or os.environ.get(
        "VENEUR_TPU_COORDINATOR", "")
    if not coordinator_address:
        return
    # unset stays None: jax.distributed auto-detects num_processes /
    # process_id on managed TPU fleets; explicit sentinels would poison
    # that detection and hang cluster formation
    if num_processes is None:
        env = os.environ.get("VENEUR_TPU_NUM_PROCESSES", "")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("VENEUR_TPU_PROCESS_ID", "")
        process_id = int(env) if env else None
    jax.distributed.initialize(coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def multihost_empty_state(spec: TableSpec, n_replicas: int, n_shards: int,
                          mesh):
    """sharded_empty_state for a mesh that may span processes: arrays are
    created INSIDE jit with out_shardings (SPMD-safe — every process runs
    the identical program; no host array ever needs global placement)."""
    sh = state_sharding(mesh)

    def make():
        one = empty_state(spec)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n_replicas, n_shards) + x.shape),
            one)

    shardings = jax.tree.map(lambda _: sh, jax.eval_shape(make))
    return jax.jit(make, out_shardings=shardings)()


def put_process_local_batch(stacked_local, mesh, n_replicas: int):
    """Global [R, S, ...] Batch from each process's local [r_local, S, ...]
    stacked rows (stack_batches output for the process's replicas)."""
    sh = NamedSharding(mesh, P("replica", "shard"))

    def place(x):
        if x is None:
            return None
        if jax.process_count() == 1:
            return jax.device_put(x, sh)
        global_shape = (n_replicas,) + tuple(x.shape[1:])
        return jax.make_array_from_process_local_data(sh, x, global_shape)

    return jax.tree.map(place, stacked_local, is_leaf=lambda x: x is None)
