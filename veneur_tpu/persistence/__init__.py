"""Durability subsystem: versioned on-disk checkpoints of the full
aggregation snapshot, an async double-buffered writer, and merge-based
warm restart (README §Durability).

  codec     chunked CRC-checksummed format + manifest + schema hash
  snapshot  build the in-memory snapshot from a flush's outputs
  writer    background serialize/fsync/rename/GC, off the flush path
  restore   validate, quarantine-on-corrupt, fold via sketch merges
  assembly  multi-host checkpoints: per-process parts, one manifest
"""

from veneur_tpu.persistence.assembly import (  # noqa: F401
    finalize_assembly, list_assemblies, load_assembly, write_part)
from veneur_tpu.persistence.codec import (  # noqa: F401
    SNAPSHOT_FORMAT_VERSION, CorruptSnapshot, list_checkpoints,
    load_dir, read_manifest, schema_hash, verify_dir)
from veneur_tpu.persistence.snapshot import build_snapshot  # noqa: F401
from veneur_tpu.persistence.writer import CheckpointWriter  # noqa: F401
from veneur_tpu.persistence.restore import (  # noqa: F401
    fold_snapshot, restore_latest, restore_spill)
