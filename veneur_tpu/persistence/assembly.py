"""Multi-host snapshot assembly: one logical checkpoint built from
per-process shard chunks.

A multi-host collective tier has no single process that can see the whole
mesh's live rows: each process gathers (and can address) only its own
devices' shards. Instead of electing a writer and hauling every shard's
sketch bytes over DCN, each process writes its OWN rows as an ordinary
codec checkpoint directory — a *part* — under one assembly directory:

  ckpt-00000042-assembly/
    part-0000/   chunks.bin + MANIFEST.json   (process 0's rows)
    part-0001/   ...                          (process 1's rows)
    ASSEMBLY.json                             written LAST, atomically

ASSEMBLY.json is the unifying manifest: it lands only after every part's
own manifest validated, so its presence certifies the whole set the same
way MANIFEST.json certifies chunks.bin. A crash mid-assembly leaves a
directory restore treats as non-existent.

Restore is re-sharding by construction: load_assembly concatenates the
parts back into one in-memory snapshot and fold_snapshot re-enters every
row through restore_metric, whose routing digest (restore.py _digest ==
collective/keytable.py route_digest) re-derives the owner shard on the
CURRENT mesh — the part layout never constrains the restoring topology.
Hash routing keeps part key sets disjoint (each process persists the keys
its shards own), so concatenation is a union, and additive kinds cannot
double-count.

Per-process identity does NOT assemble: spill bytes and forward envelope
state belong to the process that minted them (source_id semantics), so
parts carry them but load_assembly deliberately drops both.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import List, Tuple

import numpy as np

from veneur_tpu.persistence import codec
from veneur_tpu.utils.atomicio import atomic_write_bytes, fsync_dir

log = logging.getLogger("veneur_tpu.persistence.assembly")

ASSEMBLY_NAME = "ASSEMBLY.json"
ASSEMBLY_FORMAT_VERSION = 1

_ASM_RE = re.compile(r"^ckpt-(\d{8})-assembly$")
_PART_RE = re.compile(r"^part-(\d{4})$")


def assembly_dirname(seq: int) -> str:
    return f"{codec.checkpoint_dirname(seq)}-assembly"


def part_dirname(rank: int) -> str:
    return f"part-{rank:04d}"


def is_assembly(dirpath: str) -> bool:
    return os.path.isfile(os.path.join(dirpath, ASSEMBLY_NAME))


def write_part(root: str, seq: int, rank: int, snap: dict,
               fsync: bool = True) -> str:
    """Persist one process's rows as part `rank` of assembly `seq`.
    `snap` is an ordinary build_snapshot dict holding ONLY this
    process's shards' rows. Safe to call concurrently from different
    processes (distinct ranks). Returns the part path."""
    if rank < 0 or rank > 9999:
        raise ValueError(f"assembly rank {rank} out of range")
    asm = os.path.join(root, assembly_dirname(seq))
    os.makedirs(asm, exist_ok=True)
    part = os.path.join(asm, part_dirname(rank))
    os.makedirs(part, exist_ok=True)
    codec.encode_to_dir(part, snap, fsync=fsync)
    if fsync:
        fsync_dir(asm)
    return part


def finalize_assembly(root: str, seq: int, n_parts: int,
                      fsync: bool = True) -> str:
    """Validate all `n_parts` parts and write ASSEMBLY.json (atomically,
    LAST — the completeness certificate). Run by one designated process
    (rank 0) after a barrier confirms every part landed. Raises
    CorruptSnapshot if any part is missing or invalid."""
    asm = os.path.join(root, assembly_dirname(seq))
    parts = []
    for rank in range(n_parts):
        part = os.path.join(asm, part_dirname(rank))
        manifest = codec.read_manifest(part)  # raises CorruptSnapshot
        parts.append({
            "rank": rank,
            "dir": part_dirname(rank),
            "hostname": manifest.get("hostname", ""),
            "n_shards": int(manifest.get("n_shards", 1)),
            "rows": manifest.get("rows", {}),
        })
    doc = {
        "assembly_format_version": ASSEMBLY_FORMAT_VERSION,
        "format_version": codec.SNAPSHOT_FORMAT_VERSION,
        "seq": int(seq),
        "n_parts": int(n_parts),
        "parts": parts,
        "created_at": time.time(),
    }
    atomic_write_bytes(os.path.join(asm, ASSEMBLY_NAME),
                       json.dumps(doc, indent=1).encode(), fsync=fsync)
    return asm


def _read_assembly_doc(dirpath: str) -> dict:
    path = os.path.join(dirpath, ASSEMBLY_NAME)
    try:
        with open(path, "rb") as f:
            doc = json.loads(f.read())
    except FileNotFoundError:
        raise codec.CorruptSnapshot(f"{dirpath}: no {ASSEMBLY_NAME}")
    except (ValueError, OSError) as e:
        raise codec.CorruptSnapshot(
            f"{dirpath}: unreadable assembly manifest: {e}")
    if (not isinstance(doc, dict) or "parts" not in doc
            or "n_parts" not in doc):
        raise codec.CorruptSnapshot(
            f"{dirpath}: assembly manifest missing parts index")
    if doc.get("assembly_format_version") != ASSEMBLY_FORMAT_VERSION:
        raise codec.CorruptSnapshot(
            f"{dirpath}: assembly format version "
            f"{doc.get('assembly_format_version')!r}, this build reads "
            f"{ASSEMBLY_FORMAT_VERSION}")
    return doc


def load_assembly(dirpath: str) -> dict:
    """Read + validate every part and concatenate them into one
    in-memory snapshot (fold_snapshot's input layout). HLL rows are
    normalized to dense uint8 registers so parts written by different
    format versions concatenate; fold_snapshot unions them through the
    same merge path either way."""
    from veneur_tpu.ops.hll import unpack_registers_np
    doc = _read_assembly_doc(dirpath)
    snaps = []
    for entry in doc["parts"]:
        part = os.path.join(dirpath, str(entry.get("dir", "")))
        if os.path.dirname(os.path.relpath(part, dirpath)):
            raise codec.CorruptSnapshot(
                f"{dirpath}: part dir {entry.get('dir')!r} escapes the "
                "assembly")
        snaps.append(codec.load_dir(part))
    if not snaps:
        raise codec.CorruptSnapshot(f"{dirpath}: assembly with no parts")
    precisions = {int(s["spec"]["hll_precision"]) for s in snaps}
    if len(precisions) > 1:
        raise codec.CorruptSnapshot(
            f"{dirpath}: parts disagree on hll_precision {precisions}")
    precision = precisions.pop()

    tables = {k: [] for k in codec.TABLE_KINDS}
    arrays = {name: [] for name in codec.ARRAY_FIELDS}
    for s in snaps:
        for k in codec.TABLE_KINDS:
            tables[k].extend(s["tables"][k])
        hll = np.asarray(s["arrays"]["hll"])
        if hll.dtype != np.uint8:
            hll = unpack_registers_np(hll.astype(np.int32),
                                      precision=precision)
        for name in codec.ARRAY_FIELDS:
            arr = (np.asarray(hll, np.uint8) if name == "hll"
                   else np.asarray(s["arrays"][name]))
            arrays[name].append(arr)

    def _cat(chunks):
        live = [c for c in chunks if len(c)]
        if not live:
            return chunks[0]
        return np.concatenate(live, axis=0)

    base = snaps[0]
    return {
        "agg_kind": "assembly",
        "n_shards": max(int(s["n_shards"]) for s in snaps),
        "spec": base["spec"],
        "created_at": max(float(s["created_at"]) for s in snaps),
        "interval_ts": max(int(s["interval_ts"]) for s in snaps),
        "hostname": base.get("hostname", ""),
        "tables": tables,
        "arrays": {k: _cat(v) for k, v in arrays.items()},
        # per-process identity: spill payloads and forward envelopes
        # belong to the process that minted them, never to the assembly
        "spill": b"",
        "forward": None,
    }


def list_assemblies(root: str) -> List[Tuple[int, str]]:
    """(seq, path) for every COMPLETE assembly under root, oldest first
    (ASSEMBLY.json present == finalized)."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _ASM_RE.match(name)
        if not m:
            continue
        path = os.path.join(root, name)
        if is_assembly(path):
            out.append((int(m.group(1)), path))
    return sorted(out)
