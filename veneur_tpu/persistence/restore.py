"""Warm restart: load the newest valid checkpoint and FOLD it into the
live aggregator through the same sketch-merge ops the forward/import
path uses.

Restore never overwrites device state. Every snapshot row re-enters
through Aggregator.restore_metric — counter add (two-float split so f64
counts survive the f32 staging lane), HLL register max-merge, t-digest
centroid re-add with the exact min/max/reciprocalSum stats lane, gauge/
status last-write-wins — so a restore composes with concurrent ingest
exactly like an imported interval does: restored state merges, and any
later live sample for the same key wins the LWW lanes because restore
runs before the listeners start.

Corrupt snapshots are rejected and QUARANTINED (moved under
<checkpoint_dir>/quarantine/), and restore falls back to the next-newest
checkpoint, then to a cold start — a bad disk must never crash or wedge
startup.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import numpy as np

from veneur_tpu.persistence import codec
from veneur_tpu.utils.hashing import fnv1a_32

log = logging.getLogger("veneur_tpu.persistence.restore")


def _digest(kind: str, name: str, joined_tags: str) -> int:
    """Deterministic shard-routing digest for restored keys, the
    parser's recipe (samplers/parser.py _key_info). The original ingest
    digest is not persisted; any stable hash works — the KeyTable's
    by_key dict guarantees later live samples land on the same slot
    regardless of which digest allocated it."""
    h = fnv1a_32(name.encode("utf-8", "surrogateescape"))
    h = fnv1a_32(kind.encode(), h)
    return fnv1a_32(joined_tags.encode("utf-8", "surrogateescape"), h)


def restore_latest(root: str, on_corrupt=None
                   ) -> Optional[Tuple[dict, str]]:
    """Newest-first scan: load the first checkpoint that validates,
    quarantining every rejected one along the way. Multi-host assemblies
    (persistence/assembly.py) rank alongside single-process checkpoints
    by sequence number, assemblies first on a tie (an assembly at seq N
    supersedes any stray single part at N). Returns (snapshot, path) or
    None for a cold start."""
    from veneur_tpu.persistence import assembly
    candidates = sorted(
        [(seq, 0, path) for seq, path in codec.list_checkpoints(root)]
        + [(seq, 1, path) for seq, path in assembly.list_assemblies(root)])
    for seq, is_asm, path in reversed(candidates):
        try:
            snap = (assembly.load_assembly(path) if is_asm
                    else codec.load_dir(path))
        except codec.CorruptSnapshot as e:
            log.warning("rejecting checkpoint %s: %s", path, e)
            try:
                codec.quarantine(root, path)
            except OSError as qe:
                log.warning("could not quarantine %s: %s", path, qe)
            if on_corrupt is not None:
                on_corrupt()
            continue
        return snap, path
    return None


def fold_snapshot(aggregator, snap: dict, skip_forwarded: bool = False) -> int:
    """Merge every snapshot row into `aggregator` via restore_metric;
    returns the number of rows folded. Capacity overflow in a smaller
    target table is counted in the aggregator's dropped_capacity, same
    as live ingest.

    With `skip_forwarded` (a local restoring under exactly-once
    forwarding), rows a local's flush would export forward-ONLY — global
    counters/gauges/histos, non-local sets (the exact complement of
    flusher.py's local-flush masks) — are NOT folded back: their
    payloads were staged into the spill (under their original envelopes)
    BEFORE this snapshot was written, so the spill replay delivers them
    and re-folding here would re-export the same data under a fresh seq
    the receiver cannot dedup. Mixed-scope histograms flush both tiers
    and must stay."""
    from veneur_tpu.aggregation.host import SCOPE_GLOBAL, SCOPE_LOCAL
    arrays = snap["arrays"]
    n = 0

    def rows(kind):
        for i, entry in enumerate(snap["tables"][kind]):
            name, tags, scope, hostname, message, imported_only, \
                actual_kind, joined_tags = entry
            if joined_tags is None:
                joined_tags = ",".join(tags)
            scope = int(scope)
            if skip_forwarded:
                if kind in ("counter", "gauge", "histo"):
                    if scope == SCOPE_GLOBAL:
                        continue
                elif kind == "set":
                    if scope != SCOPE_LOCAL:
                        continue
            yield (i, actual_kind, name, tuple(tags), scope,
                   hostname, message, bool(imported_only), joined_tags)

    for i, kind, name, tags, scope, host, _msg, imp, joined in \
            rows("counter"):
        aggregator.restore_metric(
            kind, name, tags, scope, _digest(kind, name, joined),
            {"value": float(arrays["counter"][i])},
            hostname=host, imported_only=imp, joined_tags=joined)
        n += 1
    for i, kind, name, tags, scope, host, _msg, imp, joined in \
            rows("gauge"):
        aggregator.restore_metric(
            kind, name, tags, scope, _digest(kind, name, joined),
            {"value": float(arrays["gauge"][i])},
            hostname=host, imported_only=imp, joined_tags=joined)
        n += 1
    for i, kind, name, tags, scope, host, msg, imp, joined in \
            rows("status"):
        aggregator.restore_metric(
            kind, name, tags, scope, _digest(kind, name, joined),
            {"value": float(arrays["status"][i])},
            hostname=host, message=msg, imported_only=imp,
            joined_tags=joined)
        n += 1
    # v2 snapshots hold 6-bit packed i32 word rows, v1 dense u8 register
    # rows; either way the aggregator's restore interface takes dense u8
    # "registers" (they fold through the normal merge path, so a v1
    # snapshot restores byte-exact into the packed table)
    hll_rows = np.asarray(arrays["hll"])
    if hll_rows.dtype != np.uint8:
        from veneur_tpu.ops.hll import unpack_registers_np
        hll_rows = unpack_registers_np(
            hll_rows.astype(np.int32),
            precision=int(snap["spec"]["hll_precision"]))
    for i, kind, name, tags, scope, host, _msg, imp, joined in \
            rows("set"):
        aggregator.restore_metric(
            kind, name, tags, scope, _digest(kind, name, joined),
            {"registers": np.asarray(hll_rows[i], np.uint8)},
            hostname=host, imported_only=imp, joined_tags=joined)
        n += 1
    for i, kind, name, tags, scope, host, _msg, imp, joined in \
            rows("histo"):
        aggregator.restore_metric(
            kind, name, tags, scope, _digest(kind, name, joined),
            {"means": arrays["h_mean"][i],
             "weights": arrays["h_weight"][i],
             "min": float(arrays["h_min"][i]),
             "max": float(arrays["h_max"][i]),
             "recip": float(arrays["h_recip"][i])},
            hostname=host, imported_only=imp, joined_tags=joined)
        n += 1
    aggregator.restore_flush()
    return n


def restore_spill(spill_buffer, spill_bytes: bytes) -> int:
    """Re-seed a configured ForwardSpillBuffer from snapshot bytes,
    preserving original spill stamps. Entries already past max_age_s
    re-enter and are counted into dropped_age at the next drain — drop
    accounting survives the restart, nothing vanishes silently."""
    if not spill_bytes or spill_buffer is None:
        return 0
    from veneur_tpu.reliability.spill import parse_spill_bytes
    # with_envelope keeps each staged unit's (epoch, seq) attached so the
    # post-restart replay re-sends the ORIGINAL seqs the receiver's dedup
    # window knows how to suppress (exactly-once across a crash)
    entries, _caps = parse_spill_bytes(spill_bytes, with_envelope=True)
    spill_buffer.restore_entries(entries)
    return len(entries)
