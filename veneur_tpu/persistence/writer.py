"""Async checkpoint writer: double-buffered, latest-wins, off the flush
path.

The flush worker hands build_snapshot's host-side dict to submit() and
moves on — serialization, fsync, atomic rename, and retention GC all run
on one background thread. The double-buffer is a single pending slot: at
most one write is in flight, at most one snapshot waits, and a newer
snapshot REPLACES a waiting older one (checkpoints are full state, so
the newest supersedes; writing a stale one would only add latency to the
recovery point).

Write protocol (crash-safe at every instant):
  1. serialize into  <root>/.tmp-ckpt-<seq>/   (chunks, then manifest —
     codec.encode_to_dir fsyncs both)
  2. os.replace -> <root>/ckpt-<seq>           (atomic publish)
  3. fsync <root>, bump last_write_ts, GC to the newest `retain`

A failed write is counted and logged, never raised into the flush path;
the fault point `checkpoint.write` (reliability/faults.py) exercises
exactly that containment.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time

from veneur_tpu.persistence import codec
from veneur_tpu.reliability.faults import CHECKPOINT_WRITE, FAULTS
from veneur_tpu.utils.atomicio import fsync_dir

log = logging.getLogger("veneur_tpu.persistence.writer")


class CheckpointWriter:
    def __init__(self, root: str, retain: int = 3, fsync: bool = True,
                 write_timer=None, bytes_counter=None,
                 writes_counter=None):
        """`write_timer`/`bytes_counter`/`writes_counter` are registry
        instruments (Timer.observe(ns) / Counter.inc(n)) owned by the
        server; None leaves the writer silent (tests, CLI)."""
        self.root = root
        self.retain = max(1, int(retain))
        self.fsync = fsync
        self._write_timer = write_timer
        self._bytes_counter = bytes_counter
        self._writes_counter = writes_counter
        os.makedirs(root, exist_ok=True)
        existing = codec.list_checkpoints(root)
        self._next_seq = (existing[-1][0] + 1) if existing else 0
        self.failures = 0
        self.writes = 0
        self.last_write_ts: float = 0.0
        self.last_path: str = ""
        self._cond = threading.Condition()
        self._pending = None      # the double-buffer's waiting slot
        self._writing = False
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="checkpoint-writer")
        self._thread.start()

    # -- submission ---------------------------------------------------------
    def submit(self, snap: dict) -> None:
        """Queue a snapshot for background write; replaces any snapshot
        still waiting (latest wins)."""
        with self._cond:
            if self._closed:
                return
            if self._pending is not None:
                log.debug("checkpoint writer busy; superseding pending "
                          "snapshot")
            self._pending = snap
            self._cond.notify_all()

    def write_sync(self, snap: dict) -> bool:
        """Write on the CALLER's thread (shutdown's final checkpoint, the
        CLI, tests). Serializes against the background thread via the
        same in-flight gate. Returns success."""
        with self._cond:
            while self._writing:
                self._cond.wait()
            self._writing = True
        try:
            return self._write(snap)
        finally:
            with self._cond:
                self._writing = False
                self._cond.notify_all()

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until no snapshot is pending or in flight (tests)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending is not None or self._writing:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(timeout=left)
        return True

    def close(self) -> None:
        """Finish the in-flight/pending write and stop the thread."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=60.0)
        if self._thread.is_alive():
            log.error("checkpoint writer thread did not exit")

    # -- background thread --------------------------------------------------
    def _run(self):
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait()
                snap = self._pending
                self._pending = None
                if snap is None and self._closed:
                    return
                self._writing = True
            try:
                self._write(snap)
            finally:
                with self._cond:
                    self._writing = False
                    self._cond.notify_all()

    def _write(self, snap: dict) -> bool:
        seq = self._next_seq
        tmp = os.path.join(self.root, f".tmp-{codec.checkpoint_dirname(seq)}")
        t0 = time.perf_counter_ns()
        try:
            FAULTS.inject(CHECKPOINT_WRITE)
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            nbytes = codec.encode_to_dir(tmp, snap, fsync=self.fsync)
            final = os.path.join(self.root, codec.checkpoint_dirname(seq))
            os.replace(tmp, final)
            if self.fsync:
                fsync_dir(self.root)
        except Exception as e:
            # containment: a full disk / injected fault degrades the
            # recovery point, never the flush path
            self.failures += 1
            log.warning("checkpoint write failed (seq %d): %s", seq, e)
            shutil.rmtree(tmp, ignore_errors=True)
            return False
        dur_ns = time.perf_counter_ns() - t0
        self._next_seq = seq + 1
        self.writes += 1
        self.last_write_ts = time.time()
        self.last_path = final
        if self._write_timer is not None:
            self._write_timer.observe(dur_ns)
        if self._bytes_counter is not None:
            self._bytes_counter.inc(nbytes)
        if self._writes_counter is not None:
            self._writes_counter.inc()
        self._gc()
        return True

    def _gc(self):
        ckpts = codec.list_checkpoints(self.root)
        for _seq, path in ckpts[:-self.retain]:
            shutil.rmtree(path, ignore_errors=True)
            log.debug("checkpoint retention: removed %s", path)
