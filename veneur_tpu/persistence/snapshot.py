"""Build the in-memory checkpoint snapshot from a flush's outputs.

The async writer's zero-extra-transfer contract lives here: a checkpoint
is assembled ONLY from what the flush already moved device→host —
`compute_flush`'s compact result arrays and (with want_raw) the live
rows' mergeable sketch state. No additional device reads, no
full-capacity DeviceState copies; the snapshot is O(live keys).

Snapshot layout (the dict codec.encode_to_dir serializes):

  agg_kind     "single" | "sharded"
  n_shards     shard count the tables were laid out for
  spec         TableSpec fields as a plain dict
  interval_ts  the swap timestamp of the captured interval
  created_at   wall clock at build time
  hostname     reporting hostname
  tables       {kind: [[name, tags, scope, hostname, message,
                        imported_only, actual_kind, joined_tags], ...]}
               in ALLOCATION ORDER — entry i pairs with row i of the
               kind's arrays (the compute_flush pairing contract)
  arrays       counter f64[nc]; gauge f32[ng]; status f32[nst];
               hll u8[ns, R]; h_mean/h_weight f32[nh, C+T];
               h_min/h_max f32[nh]; h_recip f64[nh]
  spill        ForwardSpillBuffer.to_bytes() wire bytes (b"" if none)
  forward      exactly-once forwarding identity + receiver dedup state
               ({"source_id", "epoch", "next_seq", "dedup"}; absent when
               forward_dedup_window is 0) — see forward/envelope.py
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

import numpy as np

from veneur_tpu.aggregation.host import KeyTable


def _table_rows(table: KeyTable, kind: str) -> list:
    rows = []
    for _slot, meta in table.get_meta(kind):
        rows.append([meta.name, list(meta.tags), int(meta.scope),
                     meta.hostname, meta.message, bool(meta.imported_only),
                     meta.kind, meta.joined_tags])
    return rows


def spec_dict(spec) -> Dict[str, object]:
    return {f.name: getattr(spec, f.name)
            for f in dataclasses.fields(spec)}


def build_snapshot(spec, table: KeyTable, result: Dict[str, np.ndarray],
                   raw: Dict[str, np.ndarray], *, agg_kind: str,
                   n_shards: int, interval_ts: float, hostname: str = "",
                   spill: Optional[bytes] = None,
                   spill_entries: int = 0,
                   forward_meta: Optional[dict] = None,
                   watches: Optional[dict] = None,
                   history: Optional[dict] = None,
                   tenants: Optional[dict] = None,
                   keytables: Optional[dict] = None) -> dict:
    """`result`/`raw` are compute_flush's outputs for the interval being
    checkpointed (want_raw=True — both backends emit identical raw keys).
    `table` is the interval's detached KeyTable."""
    arrays = {
        # counter is already the f64 hi+lo fold — exact for any count a
        # double holds, restored via the two-float split in restore.py
        "counter": np.asarray(raw["counter"], np.float64),
        "gauge": np.asarray(raw["gauge"], np.float32),
        # raw has no status lane (status never forwards); the compact
        # flush result carries the same per-live-row values
        "status": np.asarray(result["status"], np.float32),
        # v2: 6-bit packed i32 words straight off the flush raw gather
        # (restore folds dense-u8 v1 rows through the same merge path)
        "hll": np.asarray(raw["hll"], np.int32),
        "h_mean": np.asarray(raw["h_mean"], np.float32),
        "h_weight": np.asarray(raw["h_weight"], np.float32),
        "h_min": np.asarray(raw["h_min"], np.float32),
        "h_max": np.asarray(raw["h_max"], np.float32),
        "h_recip": np.asarray(raw["h_recip"], np.float64),
    }
    tables = {kind: _table_rows(table, kind)
              for kind in ("counter", "gauge", "status", "set")}
    # histogram + timer share the histo device table; the per-row
    # actual_kind field (meta.kind) disambiguates on restore
    tables["histo"] = _table_rows(table, "histogram")
    # the sharded backend's live-slot gather pads index arrays to a
    # bucket size (live_indices), so its rows carry a zero tail past the
    # meta count; pad sits after the live rows (get_meta order), so
    # trimming to n_meta restores the pairing contract
    _kind_arrays = {"counter": ("counter",), "gauge": ("gauge",),
                    "status": ("status",), "set": ("hll",),
                    "histo": ("h_mean", "h_weight", "h_min", "h_max",
                              "h_recip")}
    for kind, arr_keys in _kind_arrays.items():
        n_meta = len(tables[kind])
        for arr_key in arr_keys:
            n_rows = len(arrays[arr_key])
            if n_rows < n_meta:
                raise ValueError(
                    f"snapshot pairing broken for {kind}: {n_meta} table "
                    f"entries vs {n_rows} array rows")
            if n_rows > n_meta:
                arrays[arr_key] = arrays[arr_key][:n_meta]
    return {
        "agg_kind": agg_kind,
        "n_shards": int(n_shards),
        "spec": spec_dict(spec),
        "interval_ts": int(interval_ts),
        "created_at": time.time(),
        "hostname": hostname,
        "tables": tables,
        "arrays": arrays,
        "spill": spill or b"",
        "spill_entries": int(spill_entries),
        # exactly-once forwarding state; None/absent = feature off
        "forward": forward_meta,
        # streaming watch tier registrations + firing state
        # (veneur_tpu/watch/); None/absent = tier off or no watches
        "watches": watches,
        # history ring sidecar (veneur_tpu/history/): key index + raw
        # window arrays, restored byte-exact; None/absent = tier off
        "history": history,
        # tenant quarantine table + exact demoted-row totals
        # (veneur_tpu/reliability/tenancy.py); None/absent = tier off
        "tenants": tenants,
        # self-adjusting key tables (veneur_tpu/tables/): LIVE per-kind
        # capacities + growth accounting. Deliberately OUTSIDE
        # schema_hash (which covers spec field NAMES only) so
        # cross-capacity restore keeps working both directions;
        # None/absent = growth off
        "keytables": keytables,
    }
