"""Versioned, chunked, CRC-checksummed on-disk snapshot format.

One checkpoint is a DIRECTORY holding two files:

  chunks.bin      every chunk's raw bytes, concatenated
  MANIFEST.json   format version, schema hash, spec, per-kind row counts,
                  and the chunk index {name, offset, length, crc32, dtype,
                  shape}; written LAST via temp-file + atomic rename, so
                  its presence certifies every chunk byte already fsynced

The chunk payloads are the COMPACT per-live-row aggregation snapshot (row
i of each array pairs with entry i of the same kind's key-table chunk) —
the same pairing contract as Aggregator.compute_flush — plus the interned
key-table strings as one JSON chunk and the ForwardSpillBuffer's wire
bytes as one opaque chunk. Full-capacity DeviceState arrays are NOT
stored: at the default TableSpec that would be ~130MB per checkpoint
regardless of occupancy.

Schema drift is detected, not silently misread: the manifest pins a hash
over DeviceState._fields + TableSpec's field names, and load refuses a
snapshot whose hash differs (scripts/check_snapshot_schema.py fails CI
when either structure changes without bumping SNAPSHOT_FORMAT_VERSION).
"""

from __future__ import annotations

import json
import logging
import os
import re
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from veneur_tpu.utils.atomicio import atomic_write_bytes, fsync_dir

log = logging.getLogger("veneur_tpu.persistence.codec")

SNAPSHOT_FORMAT_VERSION = 2

# schema_hash() pinned per format version; check_snapshot_schema.py fails
# when the live structures drift from the current version's pin
_SCHEMA_PINS = {
    1: "f2901f08f86fee1c56067eb6c0668195cac0ad5cd042ea50ecad364d6baab4a2",
    2: "fc98f22981986f4c0706c52de3c9a659d66d29e7f943267b51adaa18d8fac7c5",
}

# Older format versions this build still READS, with the layout change
# each bump made. read_manifest accepts a listed version iff the
# snapshot's hash matches that version's frozen pin, and restore.py owns
# the forward conversion; an unlisted old version stays CorruptSnapshot.
# check_snapshot_schema.py requires every superseded pin to appear here —
# a silent layout drift can't pose as an intentional bump.
_SCHEMA_MIGRATIONS = {
    1: "HLL array chunk was dense uint8[rows, 2^p] registers; v2 stores "
       "6-bit packed int32[rows, ceil(2^p*6/32)] words. Dense rows fold "
       "through the normal restore merge path (ops/hll.py merge_rows_"
       "packed), so v1 restores remain byte-exact.",
}

MANIFEST_NAME = "MANIFEST.json"
CHUNKS_NAME = "chunks.bin"

# the per-kind key-table chunks and their paired array chunks
TABLE_KINDS = ("counter", "gauge", "status", "set", "histo")
ARRAY_FIELDS = ("counter", "gauge", "status", "hll", "h_mean", "h_weight",
                "h_min", "h_max", "h_recip")

_CKPT_RE = re.compile(r"^ckpt-(\d{8})$")


class CorruptSnapshot(Exception):
    """A checkpoint that failed validation (bad CRC, truncated manifest,
    unknown version, schema-hash mismatch). Callers quarantine and fall
    back — never crash on one (restore.py restore_latest)."""


def schema_hash() -> str:
    """Hash over the structures the snapshot's meaning depends on:
    DeviceState's field list (order included — it defines what state
    exists to snapshot) and TableSpec's field names (they define the
    capacities/sketch parameters the manifest records)."""
    import dataclasses
    import hashlib

    from veneur_tpu.aggregation.state import DeviceState, TableSpec
    payload = {
        "format_version": SNAPSHOT_FORMAT_VERSION,
        "device_state_fields": list(DeviceState._fields),
        "table_spec_fields": sorted(
            f.name for f in dataclasses.fields(TableSpec)),
    }
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()


def _tables_json(tables: Dict[str, list]) -> bytes:
    # ensure_ascii keeps lone surrogates (non-UTF-8 interned names round-
    # trip host-side via surrogateescape) representable: they escape to
    # \udcXX, which json.loads restores exactly
    return json.dumps(tables, ensure_ascii=True,
                      separators=(",", ":")).encode("ascii")


def encode_to_dir(dirpath: str, snap: dict, fsync: bool = True) -> int:
    """Serialize an in-memory snapshot (persistence/snapshot.py layout)
    into `dirpath` (which must exist and be empty-ish — the writer hands
    us a fresh temp dir). Returns total bytes written."""
    chunks: List[Tuple[str, bytes, Optional[str], Optional[list]]] = []
    for name in ARRAY_FIELDS:
        arr = np.ascontiguousarray(snap["arrays"][name])
        chunks.append((f"array:{name}", arr.tobytes(), str(arr.dtype),
                       list(arr.shape)))
    chunks.append(("tables", _tables_json(snap["tables"]), None, None))
    chunks.append(("spill", snap.get("spill") or b"", None, None))
    # exactly-once forwarding identity + dedup window (JSON; optional —
    # readers of older checkpoints see no "forward" chunk and old readers
    # ignore unknown chunk names, so no format-version bump is needed)
    if snap.get("forward"):
        chunks.append(("forward",
                       json.dumps(snap["forward"],
                                  separators=(",", ":")).encode(),
                       None, None))
    # watch registrations + firing state (JSON sidecar; optional under
    # the same unknown-chunk compatibility rule as "forward")
    if snap.get("watches"):
        chunks.append(("watches",
                       json.dumps(snap["watches"],
                                  separators=(",", ":")).encode(),
                       None, None))
    # tenant quarantine + demoted-row sidecar (JSON; same rule)
    if snap.get("tenants"):
        chunks.append(("tenants",
                       json.dumps(snap["tenants"],
                                  separators=(",", ":")).encode(),
                       None, None))
    # self-adjusting key-table sidecar (veneur_tpu/tables/): live
    # per-kind capacities + growth accounting (JSON; same rule). Named
    # "keytables" — "tables" is the key-table metadata rows chunk.
    if snap.get("keytables"):
        chunks.append(("keytables",
                       json.dumps(snap["keytables"],
                                  separators=(",", ":")).encode(),
                       None, None))
    # history ring sidecar (veneur_tpu/history/): one JSON meta chunk
    # (spec + seq + key index) plus one raw-bytes chunk per ring array.
    # Same unknown-chunk rule — old readers skip all of them.
    if snap.get("history"):
        hist = snap["history"]
        chunks.append(("history",
                       json.dumps(hist["meta"],
                                  separators=(",", ":")).encode(),
                       None, None))
        for name in sorted(hist["arrays"]):
            arr = np.ascontiguousarray(hist["arrays"][name])
            chunks.append((f"history:{name}", arr.tobytes(),
                           str(arr.dtype), list(arr.shape)))

    index = []
    offset = 0
    chunk_path = os.path.join(dirpath, CHUNKS_NAME)
    with open(chunk_path, "wb") as f:
        for name, data, dtype, shape in chunks:
            f.write(data)
            entry = {"name": name, "offset": offset, "length": len(data),
                     "crc32": zlib.crc32(data)}
            if dtype is not None:
                entry["dtype"] = dtype
                entry["shape"] = shape
            index.append(entry)
            offset += len(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())

    manifest = {
        "format_version": SNAPSHOT_FORMAT_VERSION,
        "schema_hash": schema_hash(),
        "agg_kind": snap["agg_kind"],
        "n_shards": int(snap["n_shards"]),
        "spec": snap["spec"],
        "created_at": float(snap["created_at"]),
        "interval_ts": int(snap["interval_ts"]),
        "hostname": snap.get("hostname", ""),
        "rows": {k: len(snap["tables"][k]) for k in TABLE_KINDS},
        "spill_entries": int(snap.get("spill_entries", 0)),
        "chunks": index,
        "total_bytes": offset,
    }
    # the manifest lands LAST and atomically: a crash between chunk bytes
    # and manifest leaves a directory load/list treat as non-existent
    atomic_write_bytes(os.path.join(dirpath, MANIFEST_NAME),
                       json.dumps(manifest, indent=1).encode(),
                       fsync=fsync)
    return offset


def read_manifest(dirpath: str) -> dict:
    """Parse + structurally validate a checkpoint's manifest."""
    path = os.path.join(dirpath, MANIFEST_NAME)
    try:
        with open(path, "rb") as f:
            manifest = json.loads(f.read())
    except FileNotFoundError:
        raise CorruptSnapshot(f"{dirpath}: no {MANIFEST_NAME}")
    except (ValueError, OSError) as e:
        raise CorruptSnapshot(f"{dirpath}: unreadable manifest: {e}")
    if not isinstance(manifest, dict) or "chunks" not in manifest:
        raise CorruptSnapshot(f"{dirpath}: manifest missing chunk index")
    version = manifest.get("format_version")
    if version == SNAPSHOT_FORMAT_VERSION:
        if manifest.get("schema_hash") != schema_hash():
            raise CorruptSnapshot(
                f"{dirpath}: schema hash {manifest.get('schema_hash')!r} "
                f"does not match this build's {schema_hash()!r} — "
                "DeviceState or TableSpec changed shape since the "
                "snapshot was written")
    elif version in _SCHEMA_MIGRATIONS:
        # a migratable older format: the hash must match that version's
        # FROZEN pin (same drift protection the current version gets)
        if manifest.get("schema_hash") != _SCHEMA_PINS.get(version):
            raise CorruptSnapshot(
                f"{dirpath}: v{version} snapshot with schema hash "
                f"{manifest.get('schema_hash')!r}, expected the frozen "
                f"v{version} pin {_SCHEMA_PINS.get(version)!r}")
    else:
        raise CorruptSnapshot(
            f"{dirpath}: format version {version!r}, this build reads "
            f"{SNAPSHOT_FORMAT_VERSION} (+ migratable "
            f"{sorted(_SCHEMA_MIGRATIONS)})")
    return manifest


def _read_chunks(dirpath: str, manifest: dict) -> Dict[str, bytes]:
    path = os.path.join(dirpath, CHUNKS_NAME)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise CorruptSnapshot(f"{dirpath}: unreadable chunks: {e}")
    out = {}
    for entry in manifest["chunks"]:
        lo, hi = entry["offset"], entry["offset"] + entry["length"]
        if hi > len(blob):
            raise CorruptSnapshot(
                f"{dirpath}: chunk {entry['name']} extends to byte {hi} "
                f"but {CHUNKS_NAME} holds {len(blob)}")
        data = blob[lo:hi]
        if zlib.crc32(data) != entry["crc32"]:
            raise CorruptSnapshot(
                f"{dirpath}: chunk {entry['name']} failed CRC")
        out[entry["name"]] = data
    return out


def verify_dir(dirpath: str) -> dict:
    """Full validation without materializing arrays: manifest structure,
    version, schema hash, every chunk's CRC. Returns the manifest.
    Raises CorruptSnapshot on any failure (the CLI `verify` command)."""
    manifest = read_manifest(dirpath)
    _read_chunks(dirpath, manifest)
    return manifest


def load_dir(dirpath: str) -> dict:
    """Read + validate one checkpoint directory back into the in-memory
    snapshot layout (persistence/snapshot.py)."""
    manifest = read_manifest(dirpath)
    chunks = _read_chunks(dirpath, manifest)
    arrays = {}
    by_name = {e["name"]: e for e in manifest["chunks"]}
    for name in ARRAY_FIELDS:
        entry = by_name.get(f"array:{name}")
        if entry is None:
            raise CorruptSnapshot(f"{dirpath}: missing array chunk {name}")
        try:
            arrays[name] = np.frombuffer(
                chunks[f"array:{name}"],
                dtype=np.dtype(entry["dtype"])).reshape(entry["shape"])
        except (TypeError, ValueError) as e:
            raise CorruptSnapshot(
                f"{dirpath}: array chunk {name}: {e}")
    try:
        tables = json.loads(chunks["tables"])
    except (KeyError, ValueError) as e:
        raise CorruptSnapshot(f"{dirpath}: tables chunk: {e}")
    for kind in TABLE_KINDS:
        if kind not in tables:
            raise CorruptSnapshot(f"{dirpath}: tables chunk lacks {kind}")
    forward = None
    if chunks.get("forward"):
        try:
            forward = json.loads(chunks["forward"])
        except ValueError as e:
            raise CorruptSnapshot(f"{dirpath}: forward chunk: {e}")
    watches = None
    if chunks.get("watches"):
        try:
            watches = json.loads(chunks["watches"])
        except ValueError as e:
            raise CorruptSnapshot(f"{dirpath}: watches chunk: {e}")
    tenants = None
    if chunks.get("tenants"):
        try:
            tenants = json.loads(chunks["tenants"])
        except ValueError as e:
            raise CorruptSnapshot(f"{dirpath}: tenants chunk: {e}")
    keytables = None
    if chunks.get("keytables"):
        try:
            keytables = json.loads(chunks["keytables"])
        except ValueError as e:
            raise CorruptSnapshot(f"{dirpath}: keytables chunk: {e}")
    history = None
    if chunks.get("history"):
        try:
            h_arrays = {}
            for entry in manifest["chunks"]:
                name = entry["name"]
                if not name.startswith("history:"):
                    continue
                h_arrays[name[len("history:"):]] = np.frombuffer(
                    chunks[name],
                    dtype=np.dtype(entry["dtype"])).reshape(entry["shape"])
            history = {"meta": json.loads(chunks["history"]),
                       "arrays": h_arrays}
        except (KeyError, TypeError, ValueError) as e:
            raise CorruptSnapshot(f"{dirpath}: history chunks: {e}")
    return {
        "agg_kind": manifest["agg_kind"],
        "n_shards": manifest["n_shards"],
        "spec": manifest["spec"],
        "created_at": manifest["created_at"],
        "interval_ts": manifest["interval_ts"],
        "hostname": manifest.get("hostname", ""),
        "tables": tables,
        "arrays": arrays,
        "spill": chunks.get("spill", b""),
        "forward": forward,
        "watches": watches,
        "history": history,
        "tenants": tenants,
        "keytables": keytables,
    }


def list_checkpoints(root: str) -> List[Tuple[int, str]]:
    """(seq, path) for every complete checkpoint under `root`, oldest
    first. A directory without a manifest (in-flight or crashed write)
    is not a checkpoint."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _CKPT_RE.match(name)
        if not m:
            continue
        path = os.path.join(root, name)
        if os.path.isfile(os.path.join(path, MANIFEST_NAME)):
            out.append((int(m.group(1)), path))
    return sorted(out)


def checkpoint_dirname(seq: int) -> str:
    return f"ckpt-{seq:08d}"


def quarantine(root: str, dirpath: str) -> str:
    """Move a rejected checkpoint aside so restore never retries it and
    an operator can post-mortem the bytes. Returns the new path."""
    qdir = os.path.join(root, "quarantine")
    os.makedirs(qdir, exist_ok=True)
    base = os.path.basename(dirpath.rstrip("/"))
    dest = os.path.join(qdir, base)
    n = 1
    while os.path.exists(dest):
        dest = os.path.join(qdir, f"{base}.{n}")
        n += 1
    os.replace(dirpath, dest)
    fsync_dir(root)
    log.warning("quarantined corrupt checkpoint %s -> %s", dirpath, dest)
    return dest
