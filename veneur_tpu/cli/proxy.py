"""veneur-proxy daemon CLI (reference cmd/veneur-proxy/main.go)."""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main(argv=None):
    ap = argparse.ArgumentParser(prog="veneur-tpu-proxy")
    ap.add_argument("-f", dest="config", required=True)
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    from veneur_tpu.config import parse_duration
    from veneur_tpu.config_proxy import read_proxy_config
    from veneur_tpu.forward.discovery import (
        ConsulDiscoverer, StaticDiscoverer)
    from veneur_tpu.forward.proxysrv import ProxyServer

    cfg = read_proxy_config(args.config)
    service = (cfg.consul_forward_grpc_service_name
               or cfg.consul_forward_service_name)
    static = cfg.grpc_forward_address or cfg.forward_address
    if service:
        disc = ConsulDiscoverer(cfg.consul_url)
    elif static:
        disc = StaticDiscoverer([static])
    else:
        print("proxy needs a discovery service name or a static "
              "forward address", file=sys.stderr)
        return 1

    refresh = (parse_duration(cfg.consul_refresh_interval)
               if cfg.consul_refresh_interval else 0.0)
    proxy = ProxyServer(disc, service=service or "static",
                        refresh_interval=refresh,
                        dedup_window=cfg.forward_dedup_window)
    proxy.start(cfg.grpc_address)
    if cfg.stats_address:
        # runtime-metrics ticker to an external statsd daemon
        # (reference proxy.go:213-217, :354-365 ReportRuntimeMetrics)
        proxy.start_stats(
            cfg.stats_address,
            parse_duration(cfg.runtime_metrics_interval or "10s"))
    if cfg.http_address:
        # v1 HTTP routing surface (reference proxy.go:518): POST /import
        # consistent-hashes a JSONMetric array across the same ring
        proxy.start_http(cfg.http_address)
    logging.getLogger("veneur_tpu").info(
        "veneur-tpu-proxy listening on port %s (http %s)", proxy.port,
        proxy.http_port)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    stop.wait()
    proxy.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
