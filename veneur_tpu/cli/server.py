"""Server daemon CLI (reference cmd/veneur/main.go): -f config.yaml,
-validate-config[-strict]."""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main(argv=None):
    ap = argparse.ArgumentParser(prog="veneur-tpu")
    ap.add_argument("-f", dest="config", required=True,
                    help="path to config YAML")
    ap.add_argument("-validate-config", action="store_true",
                    dest="validate")
    ap.add_argument("-validate-config-strict", action="store_true",
                    dest="validate_strict")
    args = ap.parse_args(argv)

    from veneur_tpu.config import read_config
    logging.basicConfig(
        level=logging.DEBUG if "-v" in (argv or sys.argv) else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    cfg = read_config(args.config)
    if cfg.debug:
        logging.getLogger().setLevel(logging.DEBUG)
    if args.validate or args.validate_strict:
        if args.validate_strict and cfg.unknown_keys:
            print("config contains unknown keys: "
                  + ", ".join(cfg.unknown_keys), file=sys.stderr)
            return 1
        print("config valid")
        return 0

    from veneur_tpu.server.factory import new_from_config
    server = new_from_config(cfg)
    server.exit_on_quit = True  # /quitquitquit ends the daemon process
    server.start()
    logging.getLogger("veneur_tpu").info(
        "veneur-tpu started: listeners=%s interval=%ss backend=%s",
        cfg.statsd_listen_addresses, server.interval,
        cfg.aggregation_backend)

    stop = threading.Event()

    def _sig(_s, _f):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    # einhorn-style graceful handoff (reference server.go:1357-1360: goji
    # graceful treats SIGUSR2/SIGHUP as "drain and exit so the supervisor
    # can hand the socket to a replacement")
    signal.signal(signal.SIGUSR2, _sig)
    # respect nohup/supervisors that ignore hangups
    if signal.getsignal(signal.SIGHUP) is not signal.SIG_IGN:
        signal.signal(signal.SIGHUP, _sig)
    stop.wait()
    server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
