"""veneur-prometheus: poll a Prometheus /metrics endpoint and translate to
statsd (reference cmd/veneur-prometheus: main.go polling loop,
translate.go type translation with counter delta cache).

Translation rules (translate.go):
- counter  -> statsd count of the DELTA since the last poll (first poll
  primes the cache, emits nothing)
- gauge / untyped -> statsd gauge
- histogram -> each bucket count delta as a count tagged le=<bound>, plus
  _sum/_count deltas
- summary -> quantile values as gauges tagged quantile=<q>, plus
  _sum/_count deltas

Scrape transport (cmd/veneur-prometheus/config.go newHTTPClient):
- `-cert`/`-key` present a client certificate (mTLS); `-cacert` trusts
  ONLY the given CA for the server certificate (the reference builds a
  dedicated x509.CertPool, not the system roots)
- `-socket` tunnels the HTTP scrape over a unix domain socket
  (unixtripper.go), for proxy-sidecar setups
"""

from __future__ import annotations

import argparse
import logging
import re
import socket
import sys
import time
import urllib.request

log = logging.getLogger("veneur_tpu.prometheus")

_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^ ]+)(?:\s+\d+)?$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str):
    """-> (types: {name: type}, samples: [(name, labels dict, value)])."""
    types = {}
    samples = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _LINE.match(line)
        if not m:
            continue
        labels = dict(_LABEL.findall(m.group("labels") or ""))
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        samples.append((m.group("name"), labels, value))
    return types, samples


def _series_key(name, labels):
    return (name, tuple(sorted(labels.items())))


def make_fetcher(url, cert=None, key=None, cacert=None, socket_path=None,
                 timeout=10.0):
    """Build the scrape callable (config.go:42 newHTTPClient): plain
    HTTP(S), mTLS with a dedicated trust pool, or HTTP over a unix
    socket (unixtripper.go)."""
    if socket_path:
        import http.client
        from urllib.parse import urlsplit
        parts = urlsplit(url)
        path = (parts.path or "/metrics") + \
            (f"?{parts.query}" if parts.query else "")
        host_hdr = parts.netloc or "localhost"

        class _UnixConn(http.client.HTTPConnection):
            def __init__(self):
                super().__init__("localhost", timeout=timeout)

            def connect(self):
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(timeout)
                s.connect(socket_path)
                self.sock = s

        def fetch():
            conn = _UnixConn()
            try:
                conn.request("GET", path, headers={"Host": host_hdr})
                resp = conn.getresponse()
                if resp.status != 200:
                    raise RuntimeError(f"HTTP {resp.status} over unix "
                                       f"socket {socket_path}")
                return resp.read().decode()
            finally:
                conn.close()
        return fetch

    ctx = None
    if url.startswith("https") or cert or cacert:
        import ssl
        # cafile given -> trust ONLY that CA (the reference's dedicated
        # x509.NewCertPool); otherwise the default system roots
        ctx = ssl.create_default_context(cafile=cacert or None)
        if cert:
            ctx.load_cert_chain(cert, key or None)

    def fetch():
        with urllib.request.urlopen(url, timeout=timeout,
                                    context=ctx) as resp:
            return resp.read().decode()
    return fetch


def scrape_once(fetch, translator):
    """One poll: fetch → parse → translate → statsd packets. The first
    call primes the translator's delta cache, so counters emit nothing
    until the second poll (translate.go cache semantics). Shared by the
    polling loop below and the server's own /metrics round-trip test —
    a veneur-tpu server can scrape ITSELF through this path."""
    types, samples = parse_exposition(fetch())
    return translator.translate(types, samples)


class Translator:
    """Stateful poll-to-statsd translation with the counter delta cache
    (translate.go cache semantics)."""

    def __init__(self, added_tags=(), prefix="", ignored_labels=(),
                 ignored_metrics=()):
        self.cache = {}
        self.added_tags = list(added_tags)
        # reference -p prefix ("include a trailing period") and the
        # ignored-labels / ignored-metrics regex lists (main.go:17-19,
        # prometheus.go:63 shouldExportMetric, translate.go:186)
        self.prefix = prefix
        self.ignored_labels = [re.compile(p) for p in ignored_labels]
        self.ignored_metrics = [re.compile(p) for p in ignored_metrics]
        self.primed = False

    def _ignored(self, name) -> bool:
        return any(p.search(name) for p in self.ignored_metrics)

    def _tags(self, labels, extra=()):
        tags = [f"{k}:{v}" for k, v in sorted(labels.items())
                if not any(p.search(k) for p in self.ignored_labels)]
        tags += self.added_tags
        tags += list(extra)
        return tags

    def _pkt(self, name, value, mtype, tags):
        s = f"{self.prefix}{name}:{value}|{mtype}"
        if tags:
            s += "|#" + ",".join(tags)
        return s.encode()

    def _delta(self, key, value):
        prev = self.cache.get(key)
        self.cache[key] = value
        if prev is None or value < prev:  # reset detection
            return None
        return value - prev

    def translate(self, types, samples):
        packets = []
        for name, labels, value in samples:
            base = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and base[:-len(suffix)] in types:
                    base = name[:-len(suffix)]
                    break
            if self._ignored(base) or (base != name and self._ignored(name)):
                continue
            mtype = types.get(name) or types.get(base, "untyped")
            if mtype == "counter":
                d = self._delta(_series_key(name, labels), value)
                if d is not None and d > 0:
                    packets.append(self._pkt(name, f"{d:g}", "c",
                                             self._tags(labels)))
            elif mtype in ("gauge", "untyped"):
                packets.append(self._pkt(name, f"{value:g}", "g",
                                         self._tags(labels)))
            elif mtype == "histogram":
                # bucket/count/sum are all cumulative -> deltas as counts
                d = self._delta(_series_key(name, labels), value)
                if d is not None and d > 0:
                    packets.append(self._pkt(name, f"{d:g}", "c",
                                             self._tags(labels)))
            elif mtype == "summary":
                if name.endswith(("_sum", "_count")):
                    d = self._delta(_series_key(name, labels), value)
                    if d is not None and d > 0:
                        packets.append(self._pkt(name, f"{d:g}", "c",
                                                 self._tags(labels)))
                else:  # quantile gauge
                    packets.append(self._pkt(name, f"{value:g}", "g",
                                             self._tags(labels)))
        return packets


def main(argv=None):
    ap = argparse.ArgumentParser(prog="veneur-tpu-prometheus")
    ap.add_argument("-p", dest="prometheus_url",
                    default="http://localhost:9090/metrics",
                    help="Prometheus metrics endpoint to poll")
    ap.add_argument("-h2", "--statsd-host", dest="statsd",
                    default="127.0.0.1:8126")
    ap.add_argument("-i", dest="interval", default="10s")
    ap.add_argument("-a", dest="added_tags", default="",
                    help="comma-separated tags added to every metric")
    ap.add_argument("-prefix", default="",
                    help="prefix for every emitted metric name; include "
                         "the trailing period (reference -p)")
    ap.add_argument("-ignored-labels", dest="ignored_labels", default="",
                    help="comma-separated label-name regexes to drop")
    ap.add_argument("-ignored-metrics", dest="ignored_metrics", default="",
                    help="comma-separated metric-name regexes to skip")
    ap.add_argument("-cert", default="",
                    help="client cert to present (mTLS scrape)")
    ap.add_argument("-key", default="",
                    help="client private key for -cert")
    ap.add_argument("-cacert", default="",
                    help="CA cert that alone validates the server")
    ap.add_argument("-socket", default="",
                    help="unix socket path to tunnel the scrape through")
    ap.add_argument("-once", action="store_true",
                    help="poll once (two fetches for deltas) and exit")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    from veneur_tpu.config import parse_duration
    interval = parse_duration(args.interval)
    host, _, port = args.statsd.partition(":")
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    addr = (host, int(port or 8126))

    tr = Translator(
        [t for t in args.added_tags.split(",") if t],
        prefix=args.prefix,
        ignored_labels=[p for p in args.ignored_labels.split(",") if p],
        ignored_metrics=[p for p in args.ignored_metrics.split(",") if p])
    fetch = make_fetcher(args.prometheus_url, cert=args.cert or None,
                         key=args.key or None, cacert=args.cacert or None,
                         socket_path=args.socket or None)
    polls = 0
    while True:
        try:
            packets = scrape_once(fetch, tr)
            for p in packets:
                sock.sendto(p, addr)
            log.info("poll %d: %d packets", polls, len(packets))
        except Exception as e:
            log.warning("poll failed: %s", e)
        polls += 1
        if args.once and polls >= 2:
            return 0
        time.sleep(interval)


if __name__ == "__main__":
    sys.exit(main())
